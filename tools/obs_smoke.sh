#!/usr/bin/env sh
# Observability smoke gate: boot the operator against the fake kubelet,
# drive a cluster to Ready, scrape /metrics and /debug/traces (+ the
# flight recorder, the goodput ledger and the autoscaler audit), and
# assert everything parses — the standing check that the Prometheus
# exposition, the span export and the goodput rollup stay
# machine-readable.  Then the serve half: a gateway + one replica
# sharing the operator's tracer serve one completion, and the response
# traceparent's trace must surface at /debug/traces?tree=1 with BOTH
# gateway and engine spans; /debug/alerts must answer with an empty
# ring on a healthy cluster.  Finally the training-step leg: a fake
# two-host job posts synthetic step heartbeats (one host 3x slow)
# through a coordinator sharing the operator's StepTracker, and the
# straggler must surface — skew at /api/steps and /debug/steps, a
# verdict with the slow host's name, and the per-host step-duration
# histogram on the operator's /metrics.  Finally the critical-path
# profile leg: /debug/profile must decompose the traced serve request
# (self-time fractions summing to 1.0), and a seeded sim scenario run
# twice must export a byte-identical tpu-profile/v1 artifact whose
# self-diff reports zero regressions.  The incident forensics leg rides
# the serve traffic: the TTFT SLO is tightened to an impossible target
# so the completions are a REAL breach, and the background tick must
# open an alert-triggered tpu-incident/v1 bundle at /debug/incidents
# with a non-empty suspect ranking and an exemplar trace that resolves
# at /debug/traces?tree=1.
#
#   tools/obs_smoke.sh
#
# See docs/observability.md for the span model, the goodput phase
# contract and the metric catalog.
set -eu
cd "$(dirname "$0")/.."
timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import re
import urllib.request

from kuberay_tpu.operator import Operator
from kuberay_tpu.sim.scenarios import make_cluster_obj

op = Operator(fake_kubelet=True)
url = op.start(api_port=0)
try:
    op.store.create(make_cluster_obj("smoke", topology="2x2x2", replicas=1))
    for _ in range(6):
        op.run_until_idle()
    state = op.store.get("TpuCluster", "smoke").get("status", {}).get("state")
    assert state == "ready", f"cluster never became ready (state={state!r})"

    # /metrics must parse as Prometheus text exposition: every sample
    # line is <name>{labels} <value>, every meta line # HELP / # TYPE.
    with urllib.request.urlopen(f"{url}/metrics") as resp:
        text = resp.read().decode()
    sample = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? '
                        r'[-+0-9.eEinfa]+$')
    bad = [ln for ln in text.splitlines()
           if ln and not ln.startswith("#") and not sample.match(ln)]
    assert not bad, f"unparseable exposition lines: {bad[:3]}"
    for needed in ("tpu_reconcile_total", "tpu_slice_ready_duration_seconds",
                   "tpu_cluster_provisioned_duration_seconds"):
        assert needed in text, f"{needed} missing from /metrics"

    # /debug/traces must parse as JSON and contain the span pipeline,
    # plus the retention envelope (a truncated window is detectable).
    with urllib.request.urlopen(f"{url}/debug/traces") as resp:
        doc = json.load(resp)
    names = {s["name"] for s in doc["spans"]}
    for needed in ("queue-wait", "reconcile", "store-write", "pod-start",
                   "slice-ready"):
        assert needed in names, f"{needed} span missing: {sorted(names)}"
    assert "retention" in doc and "dropped" in doc["retention"], \
        f"no retention stats in /debug/traces envelope: {sorted(doc)}"

    # And the flight recorder answers for the CR.
    with urllib.request.urlopen(
            f"{url}/debug/flight/TpuCluster/default/smoke") as resp:
        flight = json.load(resp)
    assert flight["records"], "flight recorder empty for the cluster"

    # Goodput ledger: the rollup parses, phases partition the object's
    # wall-clock exactly (sum == total), and the cluster is productive.
    with urllib.request.urlopen(f"{url}/debug/goodput") as resp:
        listing = json.load(resp)
    assert any(o["kind"] == "TpuCluster" and o["name"] == "smoke"
               for o in listing["objects"]), listing
    with urllib.request.urlopen(
            f"{url}/debug/goodput/TpuCluster/default/smoke") as resp:
        good = json.load(resp)
    roll = good["rollup"]
    assert roll["current_phase"] == "productive", roll
    phase_sum = sum(roll["phases"].values())
    assert abs(phase_sum - roll["total"]) < 1e-6, \
        f"phases {phase_sum} != elapsed {roll['total']}"
    assert "tpu_goodput_seconds_total" in text, \
        "goodput series missing from /metrics"

    # Autoscaler decision audit: mounted and parseable (no decisions
    # expected for a static cluster, but the ring must answer).
    with urllib.request.urlopen(f"{url}/debug/autoscaler") as resp:
        audit = json.load(resp)
    assert "decisions" in audit, audit

    # SLO burn-rate alert engine: /debug/alerts answers, and a healthy
    # smoke run fires nothing (empty active set and history ring).
    with urllib.request.urlopen(f"{url}/debug/alerts") as resp:
        alerts = json.load(resp)
    assert alerts["active"] == [], f"unexpected active alerts: {alerts}"
    assert alerts["ring"] == [], f"unexpected alert history: {alerts}"
    assert alerts["specs"], "alert engine mounted with no SLO specs"

    # Serve request tracing end-to-end: one completion through a gateway
    # + replica that share the operator's tracer; the response
    # traceparent's trace id must resolve at /debug/traces?tree=1 to a
    # tree containing the gateway spans AND the engine child spans.
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.gateway import WeightedGateway
    from kuberay_tpu.serve.paged_engine import PagedServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    # Tighten the serve TTFT SLO to an impossible target BEFORE any
    # serve traffic exists: the completions below then breach for real,
    # the background tick fires the alert, and the incident engine must
    # open a bundle from it (asserted in the forensics leg at the end).
    import dataclasses
    op.alerts.specs = [
        dataclasses.replace(s, threshold_s=1e-9)
        if getattr(s, "name", "") == "serve-ttft" else s
        for s in op.alerts.specs]

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = PagedServeEngine(cfg, params, max_slots=2, max_len=48,
                           block_size=16, tracer=op.tracer)
    fe = ServeFrontend(eng, max_queue=8)
    srv, replica_url = fe.serve_background()
    op.store.create({
        "apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
        "metadata": {"name": "smoke-route", "namespace": "default"},
        "spec": {"backends": [{"service": "replica-0", "weight": 1}]},
        "status": {},
    })
    gw = WeightedGateway(op.store, "smoke-route",
                         resolver=lambda s: replica_url,
                         poll_interval=30.0, tracer=op.tracer,
                         flight=op.flight)
    try:
        body = json.dumps({"prompt_tokens": [1, 2, 3, 4],
                           "max_tokens": 4}).encode()
        # Six completions: the alert engine's min_samples, so the
        # tightened TTFT SLO has enough fast-window evidence to fire.
        traceparent = None
        for _ in range(6):
            code, payload, hdrs = gw.forward_ex("/v1/completions", body)
            assert code == 200, (code, payload)
            traceparent = traceparent or hdrs.get("traceparent")
        assert traceparent, f"no traceparent in response headers: {hdrs}"
        trace_id = traceparent.split("-")[1]
        with urllib.request.urlopen(
                f"{url}/debug/traces?trace_id={trace_id}&tree=1") as resp:
            tree = json.load(resp)

        def span_names(nodes):
            out = set()
            for n in nodes:
                out.add(n["name"])
                out |= span_names(n["children"])
            return out

        got = span_names(tree["traces"])
        for needed in ("serve-request", "gateway-queue", "route-decision",
                       "forward", "engine-queue", "prefill", "decode",
                       "kv-alloc"):
            assert needed in got, \
                f"{needed} span missing from trace {trace_id}: {sorted(got)}"
    finally:
        gw.stop()
        srv.shutdown()
        fe.close()

    # Critical-path profile: /debug/profile must decompose the serve
    # request just traced — per-span-kind exclusive self-time fractions
    # summing to 1.0 over the serve shape, with the engine phases
    # present — and carry the same retention envelope.
    with urllib.request.urlopen(f"{url}/debug/profile") as resp:
        prof = json.load(resp)
    assert prof["schema"] == "tpu-profile/v1", prof.get("schema")
    serve_shape = prof["shapes"]["serve"]
    assert serve_shape["traces"] >= 1, prof["shapes"]
    frac = sum(k["fraction"] for k in serve_shape["kinds"].values())
    assert abs(frac - 1.0) < 1e-6, \
        f"serve self-time fractions sum to {frac}"
    for needed in ("prefill", "decode"):
        assert needed in serve_shape["kinds"], sorted(serve_shape["kinds"])
    assert "retention" in prof, sorted(prof)

    # Training-step telemetry end-to-end: a coordinator sharing the
    # operator's StepTracker ingests synthetic heartbeats for a fake
    # 2-host job where host b runs 5x slow — with two hosts the fleet
    # median is the midpoint, so b must exceed 3x a to clear the 1.5
    # skew ratio — long enough to cross the K-consecutive threshold.
    import tempfile

    from kuberay_tpu.runtime.coordinator_server import (
        CoordinatorServer, MemoryBackend)

    coord = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False,
                              auth_token="",
                              log_dir=tempfile.mkdtemp(prefix="obs-smoke-"),
                              steps=op.steps)
    csrv, curl = coord.serve_background()
    try:
        k = op.steps.straggler_steps
        for step in range(1, k + 3):
            beats = [{"type": "step", "name": "step_heartbeat",
                      "job_id": "default/smoke-train", "host": host,
                      "args": {"step": step, "dur_s": dur,
                               "tokens": 4096.0,
                               "collective_wait_s": 0.01,
                               "n_params": 1.0e9, "device_count": 8,
                               "peak_tflops": 197.0}}
                     for host, dur in (("host-a", 0.5), ("host-b", 2.5))]
            req = urllib.request.Request(
                f"{curl}/api/events",
                data=json.dumps({"events": beats}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                assert json.load(resp)["recorded"] == 2

        # Read side, coordinator face: the skew and the verdict.
        with urllib.request.urlopen(
                f"{curl}/api/steps/default/smoke-train") as resp:
            sdoc = json.load(resp)
        hosts = {h["host"]: h for h in sdoc["hosts"]}
        assert hosts["host-b"]["skew_ratio"] > op.steps.straggler_ratio, sdoc
        assert hosts["host-b"]["straggler"], sdoc
        assert not hosts["host-a"]["straggler"], sdoc
        assert any(v["host"] == "host-b" for v in sdoc["verdicts"]), sdoc

        # Same document from the operator's debug face.
        with urllib.request.urlopen(
                f"{url}/debug/steps/default/smoke-train") as resp:
            ddoc = json.load(resp)
        assert {h["host"] for h in ddoc["hosts"]} == {"host-a", "host-b"}

        # And the per-host histogram reached the operator's registry.
        with urllib.request.urlopen(f"{url}/metrics") as resp:
            mtext = resp.read().decode()
        assert "tpu_train_step_duration_seconds" in mtext, \
            "train-step histogram missing from /metrics"
        assert "tpu_train_stragglers_total" in mtext, \
            "straggler counter missing from /metrics"
    finally:
        csrv.shutdown()

    # Incident forensics leg: the TTFT breach above must have opened an
    # alert-triggered bundle on a background tick — poll briefly (the
    # loop runs every second), then assert the ranking is non-empty and
    # the exemplar trace resolves as a span tree.
    import time

    bundle, idx = None, {}
    for _ in range(30):
        with urllib.request.urlopen(f"{url}/debug/incidents") as resp:
            idx = json.load(resp)
        rows = [r for r in idx.get("incidents", [])
                if r.get("trigger") == "alert"]
        if rows:
            with urllib.request.urlopen(
                    f"{url}/debug/incidents/{rows[0]['id']}") as resp:
                bundle = json.load(resp)
            break
        time.sleep(0.5)
    assert bundle is not None, \
        f"no alert-triggered incident bundle after the TTFT breach: {idx}"
    assert bundle["schema"] == "tpu-incident/v1", bundle.get("schema")
    assert bundle["suspects"], \
        f"incident {bundle['id']} ranked no suspects"
    inc_traces = bundle.get("evidence", {}).get("traces") or []
    assert inc_traces, f"incident {bundle['id']} carries no exemplar trace"
    inc_tid = inc_traces[0]["trace_id"]
    with urllib.request.urlopen(
            f"{url}/debug/traces?trace_id={inc_tid}&tree=1") as resp:
        inc_tree = json.load(resp)
    assert inc_tree["traces"], \
        f"incident exemplar trace {inc_tid} unresolvable at /debug/traces"
    # The shared ?limit=N contract holds on the incident index too.
    with urllib.request.urlopen(f"{url}/debug/incidents?limit=1") as resp:
        lim = json.load(resp)
    assert len(lim["incidents"]) <= 1, lim

    print(f"obs smoke ok: {len(doc['spans'])} spans, "
          f"{len(text.splitlines())} metric lines, "
          f"{len(flight['records'])} flight records, "
          f"goodput ratio {roll['goodput_ratio']:.2f} over "
          f"{len(good['intervals'])} intervals, "
          f"{len(audit['decisions'])} autoscaler decisions, "
          f"serve trace {trace_id} spans {sorted(got)}, "
          f"profile shapes {sorted(prof['shapes'])}, "
          f"straggler host-b skew "
          f"{hosts['host-b']['skew_ratio']:.2f}, "
          f"incident {bundle['id']} trigger={bundle['trigger']} "
          f"suspects={len(bundle['suspects'])}")
finally:
    op.stop()
EOF

# Critical-path profile determinism leg: the same seeded sim scenario
# run twice must export a BYTE-identical tpu-profile/v1 artifact (the
# virtual clock and counter span ids leave no wall-clock residue), and
# the noise-gated diff of a run against itself must report zero
# regressions (exit 1 otherwise — `tpuctl profile diff` is the same
# engine the upgrade ramp and tools/bench_serve.sh use).
prof_a="${OBS_PROFILE_A:-/tmp/obs_smoke_profile_a.json}"
prof_b="${OBS_PROFILE_B:-/tmp/obs_smoke_profile_b.json}"
timeout -k 10 120 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario scale-up-storm --seed 3 --profile-out "$prof_a" >/dev/null
timeout -k 10 120 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario scale-up-storm --seed 3 --profile-out "$prof_b" >/dev/null
cmp "$prof_a" "$prof_b" || {
    echo "profile artifact not byte-identical across re-runs" >&2
    exit 1
}
timeout -k 10 60 env JAX_PLATFORMS=cpu python -m kuberay_tpu.cli \
    profile diff "$prof_a" "$prof_b"
echo "obs profile leg ok: byte-identical sim artifact, self-diff clean"
