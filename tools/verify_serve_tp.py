"""Verify drive: boot the real serve server with --tp 2 on the virtual
CPU mesh, hit /v1/completions over HTTP, assert tokens come back.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python tools/verify_serve_tp.py
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

PORT = int(os.environ.get("VERIFY_SERVE_PORT", "18963"))


def main() -> int:
    srv = subprocess.Popen(
        [sys.executable, "-m", "kuberay_tpu.serve.server", "--model",
         "llama_tiny", "--tp", "2", "--port", str(PORT), "--host",
         "127.0.0.1", "--max-slots", "2", "--max-len", "64"],
        env=dict(os.environ), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        import queue
        import threading
        lines: "queue.Queue" = queue.Queue()

        def _reader():
            for ln in srv.stdout:
                lines.put(ln)
            lines.put(None)        # EOF sentinel: server exited

        threading.Thread(target=_reader, daemon=True).start()
        deadline = time.time() + 180
        line = ""
        # Deadline-aware read: a silently hung server must fail at the
        # deadline, a crashed one immediately — not pin this script on a
        # blocking readline().
        while time.time() < deadline:
            try:
                got = lines.get(timeout=max(0.1, deadline - time.time()))
            except queue.Empty:
                break
            if got is None:
                break              # server process exited
            line = got
            print("SRV:", line.rstrip(), flush=True)
            if "serving llama_tiny" in line:
                break
        assert "tp=2" in line, f"server never came up: {line!r}"
        req = json.dumps({"prompt_tokens": [1, 2, 3, 4],
                          "max_tokens": 6}).encode()
        r = urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{PORT}/v1/completions", data=req,
                headers={"Content-Type": "application/json"}),
            timeout=150)
        out = json.loads(r.read())
        print("HTTP RESPONSE:", out, flush=True)
        assert len(out.get("tokens", [])) == 6, out
        print("VERIFY OK: tp=2 server served /v1/completions over HTTP",
              flush=True)
        return 0
    finally:
        srv.kill()


if __name__ == "__main__":
    sys.exit(main())
