"""Preemption chaos benchmark: the goodput value of the advance notice.

The claim behind warm-pool pre-replacement (docs/preemption.md): a slice
kill that arrives WITH an advance warning costs strictly less goodput
than the same kill arriving unwarned, because the control plane builds
the replacement while the doomed slice is still serving.  This harness
measures exactly that, as a seeded regression curve:

- ``warned-warm``: advance notice + a warm pool of one — the controller
  claims the standby slice and retires the doomed one before the kill;
- ``warned-cold``: advance notice, no warm pool — the replacement is
  provisioned cold inside the warning window (maxReplicas headroom);
- ``unwarned``: the same slice dies at the same virtual time with no
  warning at all — the classic preemption.

Every run is a fault-free ``SimHarness`` on the virtual clock (wall
time never enters the numbers), one v5e/4x4 cluster of two slices, one
kill per run.  Per seed, the notice offset and warning window are drawn
from ``random.Random(1000 + seed)`` and SHARED across the three modes,
so the fault windows are equal and the per-seed comparison is paired.

    python benchmark/chaos_bench.py --out benchmark/results/chaos_r10.json

The committed artifact (``tpu-chaos-bench/v1``) is the regression
fence: tests/test_chaos_bench.py recomputes the curve and asserts that
for every seed the warned modes spend strictly fewer
interrupted+recovery seconds and end at a strictly higher goodput
ratio than the unwarned run — and that the numbers still match the
committed file exactly (the whole pipeline is deterministic).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys

# Anchor imports on the repo root, not the CWD — the harness must work
# from any invocation directory.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from kuberay_tpu.sim.harness import SimHarness  # noqa: E402
from kuberay_tpu.sim.scenarios import make_cluster_obj  # noqa: E402
from kuberay_tpu.utils import constants as C  # noqa: E402

SCHEMA = "tpu-chaos-bench/v1"
MODES = ("warned-warm", "warned-cold", "unwarned")
NS = "default"
CLUSTER = "drill"
#: Observation window after the kill: long enough for the slowest
#: (unwarned cold rebuild) recovery to complete and amortize into the
#: ratio, identical across modes so totals stay comparable.
SETTLE_AFTER = 120.0
#: Deterministic pod boot time (creation -> Running) on the virtual
#: clock.  The fake kubelet starts pods instantly by default, which
#: would price cold provisioning at zero; real TPU hosts take minutes.
#: Chosen LONGER than every warning window (15-25s) so warned-cold
#: recovery genuinely overlaps the warning rather than hiding inside
#: it — the warm pool's whole advantage is skipping this.
BOOT_S = 30.0


def _schedule(seed: int):
    """Per-seed (notice offset, warning window), shared by all modes so
    the three runs of a seed see the same fault window."""
    rnd = random.Random(1000 + seed)
    offset = 45.0 + rnd.uniform(0.0, 30.0)
    delta = 15.0 + rnd.uniform(0.0, 10.0)
    return offset, delta


def _warm_pool():
    return {
        "apiVersion": C.API_VERSION, "kind": "WarmSlicePool",
        "metadata": {"name": "reserve", "namespace": NS},
        "spec": {"accelerator": "v5e", "topology": "4x4", "poolSize": 1},
        "status": {},
    }


def _victim_slice(h) -> str:
    """Lowest-indexed live worker slice of the drill cluster —
    deterministic under the seeded store (uid/name counters)."""
    best = None
    for p in h.store.list("Pod", NS, labels={C.LABEL_CLUSTER: CLUSTER}):
        labels = p["metadata"]["labels"]
        sname = labels.get(C.LABEL_SLICE_NAME)
        if not sname or p["metadata"].get("deletionTimestamp"):
            continue
        try:
            idx = int(labels.get(C.LABEL_SLICE_INDEX, "10000"))
        except ValueError:
            continue
        if best is None or (idx, sname) < best:
            best = (idx, sname)
    if best is None:
        raise RuntimeError("no live worker slice to preempt")
    return best[1]


def _install_boot_delay(h):
    """Every pod takes ``BOOT_S`` virtual seconds from creation to
    Running (a hold the settle loop's wakeup scan advances through) —
    the deterministic stand-in for TPU host boot + runtime start."""
    def on_event(ev):
        if ev.kind != "Pod" or ev.type != "ADDED":
            return
        md = ev.obj.get("metadata", {})
        h.kubelet.hold_pod(md.get("name", ""),
                           md.get("namespace", "default"),
                           until=h.clock.now() + BOOT_S)
    return h.store.watch(on_event)


def run_case(mode: str, seed: int) -> dict:
    """One (mode, seed) run -> its goodput accounting."""
    if mode not in MODES:
        raise ValueError(f"unknown mode {mode!r}")
    offset, delta = _schedule(seed)
    # Fault-free plan: the ONLY disturbance is the scripted preemption,
    # so the curve isolates warned-vs-unwarned (not random chaos).
    with SimHarness(seed, fault_profile={}, goodput=True) as h:
        cancel = _install_boot_delay(h)
        h.store.create(make_cluster_obj(
            CLUSTER, accelerator="v5e", topology="4x4",
            replicas=2, max_replicas=4))
        if mode == "warned-warm":
            h.store.create(_warm_pool())
        h.settle()
        if not h.converged:
            raise RuntimeError(f"{mode}/seed={seed}: bootstrap did not "
                               "converge")

        # Idle steady state up to the notice instant.
        h.clock.advance_to(h.clock.now() + offset)
        h.settle()
        sname = _victim_slice(h)
        base = h.clock.now()
        kill_at = base + delta

        if mode == "unwarned":
            # Same kill, zero warning: advance straight to the deadline
            # and drop the slice.
            h.clock.advance_to(kill_at)
            with h.plan.suspended():
                h.kubelet.fail_slice(sname, NS)
            h.settle()
        else:
            # The harness kills the slice at the deadline itself; the
            # settle in between is where the controller spends the
            # warning (drain + claim/pre-provision + retire).
            h.inject_preemption_notice(NS, sname, delta)
            h.settle()
            h.clock.advance_to(kill_at)
            h.settle()

        # Equal-length observation window after the kill.
        h.clock.advance_to(kill_at + SETTLE_AFTER)
        h.settle()
        if not h.converged:
            raise RuntimeError(f"{mode}/seed={seed}: recovery did not "
                               "converge")

        roll = h.goodput.rollup(C.KIND_CLUSTER, NS, CLUSTER)
        phases = roll["phases"]
        violations = [str(v) for v in h.check()]
        cancel()
        return {
            "mode": mode, "seed": seed,
            "notice_offset_s": round(offset, 6),
            "warning_window_s": round(delta, 6),
            "goodput_ratio": round(roll["goodput_ratio"], 9),
            "productive_s": round(phases["productive"], 6),
            "interrupted_s": round(phases["interrupted"], 6),
            "recovery_s": round(phases["recovery"], 6),
            "bootstrap_s": round(phases["bootstrap"], 6),
            "provisioning_s": round(phases["provisioning"], 6),
            "total_s": round(roll["total"], 6),
            "violations": violations,
        }


def run_curve(seeds) -> dict:
    runs = [run_case(mode, seed) for seed in seeds for mode in MODES]
    by = {(r["mode"], r["seed"]): r for r in runs}
    curve = {
        mode: [by[(mode, s)]["goodput_ratio"] for s in seeds]
        for mode in MODES
    }
    return {
        "schema": SCHEMA,
        "scenario": "preemption-drill",
        "seeds": list(seeds),
        "settle_after_s": SETTLE_AFTER,
        "curve": curve,
        "runs": runs,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos_bench")
    ap.add_argument("--seeds", default="0,1,2,3,4",
                    help="comma-separated seed list")
    ap.add_argument("--out", default="",
                    help="write the artifact here (default: stdout)")
    args = ap.parse_args(argv)
    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    doc = run_curve(seeds)
    payload = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as fh:
            fh.write(payload)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(payload)
    # The bench's own gate: warned must beat unwarned on every seed.
    for seed in seeds:
        runs = {r["mode"]: r for r in doc["runs"] if r["seed"] == seed}
        un = runs["unwarned"]
        for mode in ("warned-warm", "warned-cold"):
            w = runs[mode]
            if not (w["interrupted_s"] + w["recovery_s"]
                    < un["interrupted_s"] + un["recovery_s"]):
                print(f"REGRESSION seed={seed} {mode}: downtime not "
                      "strictly below unwarned", file=sys.stderr)
                return 1
            if not w["goodput_ratio"] > un["goodput_ratio"]:
                print(f"REGRESSION seed={seed} {mode}: goodput ratio not "
                      "strictly above unwarned", file=sys.stderr)
                return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
