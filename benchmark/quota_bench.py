"""Quota fairness benchmark: a 1k-job multi-tenant contention storm.

Drives the hierarchical QuotaManager (``controlplane/quota.py``)
directly — no pods, no controllers — through a deterministic
discrete-event loop: seeded job arrivals across three tenants whose
combined offered load oversubscribes the pool ~1.5x for the whole
arrival window, then a drain to empty.  Because the ledger sees no pod
objects, an evicted claim frees exactly at its notice deadline, which
models an instantly-compliant workload and isolates the *ledger's*
fairness from controller teardown latency (the sim scenarios cover the
latter).

The committed artifact (``tpu-quota-bench/v1``) is the regression
fence: tests/test_quota_bench.py recomputes the storm and asserts the
shape of the fairness curve — guaranteed tenants get at least their
share while backlogged, the zero-guarantee tenant still makes progress
(bounded starvation), nobody violates conservation — and that the
numbers still match the committed file exactly.  Everything runs on a
fake clock and ``random.Random(1000 + seed)``; no wall time enters the
numbers, so the artifact is byte-identical across re-runs per seed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import random

from kuberay_tpu.controlplane.quota import QuotaManager
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.sim.scenarios import make_quota_pool_obj
from kuberay_tpu.utils import constants as C

SCHEMA = "tpu-quota-bench/v1"

NS = "default"
JOBS = 1000
TICK_S = 5.0
ASK_EVERY = 3             # waiting gangs re-ask every 3rd tick (15s), the
                          # controllers' hold-off requeue cadence; running
                          # gangs re-ask every tick (level-triggered).
MAX_TICKS = 4000          # hard stop; an undrained run is a violation
STARVATION_BOUND_S = 300.0
NOTICE_S = 15.0
TOTAL_CHIPS = 64
# (tenant, guaranteed, ceiling [0 = pool total]) — sum of guarantees 48
# of 64, so there is always borrowable headroom to fight over.
TENANTS = (("prod", 32, 0), ("batch", 16, 48), ("free", 0, 32))


def _pool_obj():
    return make_quota_pool_obj(
        "fleet", TOTAL_CHIPS,
        [(name, [("default", guaranteed, ceiling, True)])
         for name, guaranteed, ceiling in TENANTS],
        starvation=STARVATION_BOUND_S, notice=NOTICE_S)


def _schedule(seed: int):
    """The seeded storm: 1000 jobs with arrival time, tenant, shape.

    Offered chip rate ~= 57 chips/s (mean interarrival 4s, mean demand
    ~228 chip-seconds) against a 64-chip pool: ~0.9x loaded on average,
    so Poisson bursts regularly saturate the pool but the backlog always
    clears.  By construction prod's own offered rate (~17 chips/s) sits
    well under its 32-chip guarantee while batch overruns its 16 and
    free owns nothing at all, so the curve separates "protected by
    guarantee" (short waits, almost no reclaim) from "living on
    borrowed capacity plus the starvation guard" (longer waits, the
    reclaim notices, the escalations).
    """
    rng = random.Random(1000 + seed)
    jobs = []
    t = 0.0
    for i in range(JOBS):
        t += rng.expovariate(1.0 / 4.0)
        r = rng.random()
        tenant = "prod" if r < 0.30 else ("batch" if r < 0.70 else "free")
        r = rng.random()
        chips = 4 if r < 0.50 else (8 if r < 0.80 else 16)
        jobs.append({
            "idx": i,
            "name": f"storm-{i:04d}",
            "arrival": t,
            "tenant": tenant,
            "chips": chips,
            "duration": rng.uniform(15.0, 45.0),
            "priority": rng.randrange(3),
        })
    return jobs


def _demand(job: dict) -> dict:
    return {
        "kind": C.KIND_JOB, "namespace": NS, "name": job["name"],
        "tpuChips": job["chips"], "chips": job["chips"], "minMember": 1,
        "tenant": job["tenant"], "queue": "default",
        "priority": job["priority"],
        "key": (C.KIND_JOB, NS, job["name"]),
    }


def _check_tick(now: float, snapshot: dict, jobs_by_name: dict,
                violations: list) -> None:
    """The bench-side mirror of the sim's quota invariants."""
    ceilings = {name: (ceiling or TOTAL_CHIPS)
                for name, _, ceiling in TENANTS}
    used = {}
    total_used = 0
    for claim in snapshot["claims"]:
        chips = claim["chips"]
        job = jobs_by_name.get(claim["key"][2])
        if job is None or chips != job["chips"]:
            violations.append(
                f"t={now:.0f}: partial/orphan claim {claim['key']} "
                f"chips={chips}")
        used[claim["tenant"]] = used.get(claim["tenant"], 0) + chips
        total_used += chips
    if total_used > TOTAL_CHIPS:
        violations.append(
            f"t={now:.0f}: conservation broken {total_used} > {TOTAL_CHIPS}")
    for tenant, chips in used.items():
        if chips > ceilings.get(tenant, TOTAL_CHIPS):
            violations.append(
                f"t={now:.0f}: {tenant} over ceiling: {chips}")
    for p in snapshot["pending"]:
        # Grace of one ask interval: escalation is stamped on the first
        # re-ask after the pending entry crosses the bound.
        if now - p["since"] > STARVATION_BOUND_S + \
                (ASK_EVERY + 1) * TICK_S and not p["escalated"]:
            violations.append(
                f"t={now:.0f}: {p['key']} pending "
                f"{now - p['since']:.0f}s without escalation")


def run_case(seed: int) -> dict:
    jobs = _schedule(seed)
    jobs_by_name = {j["name"]: j for j in jobs}
    window_end = jobs[-1]["arrival"]

    store = ObjectStore()
    store.create(_pool_obj())
    clock = {"t": 0.0}
    notices = []
    quota = QuotaManager(store, clock=lambda: clock["t"],
                         preemptor=lambda claim, deadline:
                         notices.append((claim["key"][2], deadline)))

    for j in jobs:
        j.update(state="waiting", progress=0.0, first_admit=None,
                 done_at=None, preemptions=0, delivered_window=0.0,
                 hot=False)
    violations: list = []
    escalated_keys = set()
    # Per-tenant usage while that tenant has a backlog — the fairness
    # denominator (an idle tenant "under" its guarantee is not starved).
    backlog_ticks = {name: 0 for name, _, _ in TENANTS}
    backlog_used = {name: 0.0 for name, _, _ in TENANTS}

    tick = 0
    while tick < MAX_TICKS:
        now = clock["t"]
        active = [j for j in jobs
                  if j["arrival"] <= now and j["done_at"] is None]
        if not active and now > window_end:
            break
        admitted_now = []
        for j in active:
            # Cold waiters re-ask at the hold-off cadence; escalated
            # ones every tick (their reservation makes the next free
            # chip theirs — don't let it idle for an ask interval).
            if j["state"] == "waiting" and not j["hot"] and \
                    (tick + j["idx"]) % ASK_EVERY != 0:
                continue
            verdict = quota.admit(_demand(j))
            if verdict.escalated:
                j["hot"] = True
            if verdict.admitted:
                if j["first_admit"] is None:
                    j["first_admit"] = now
                if j["state"] == "evicted":
                    j["preemptions"] += 1
                j["state"] = "running"
                admitted_now.append(j)
            else:
                if j["state"] == "running":
                    j["state"] = "evicted"
                elif j["state"] != "evicted":
                    j["state"] = "waiting"

        snapshot = quota.debug_snapshot()
        _check_tick(now, snapshot, jobs_by_name, violations)
        for p in snapshot["pending"]:
            if p["escalated"]:
                escalated_keys.add((p["tenant"], p["key"][2]))
        backlogged = {j["tenant"] for j in active
                      if j["state"] in ("waiting", "evicted")}
        used_now = {}
        for claim in snapshot["claims"]:
            used_now[claim["tenant"]] = \
                used_now.get(claim["tenant"], 0) + claim["chips"]
        for tenant in backlogged:
            backlog_ticks[tenant] += 1
            backlog_used[tenant] += used_now.get(tenant, 0)

        # Advance the clock, crediting this tick's chip-seconds to every
        # gang that held its claim across it (checkpoint semantics:
        # progress survives preemption, per PR 10).
        clock["t"] = now + TICK_S
        for j in admitted_now:
            step = min(TICK_S, j["duration"] - j["progress"])
            j["progress"] += step
            if now < window_end:
                j["delivered_window"] += step * j["chips"]
            if j["progress"] >= j["duration"] - 1e-9:
                j["done_at"] = clock["t"]
                quota.release({"key": (C.KIND_JOB, NS, j["name"])})
        tick += 1

    undone = [j["name"] for j in jobs if j["done_at"] is None]
    if undone:
        violations.append(f"undrained: {len(undone)} jobs incomplete")

    total_window = sum(j["delivered_window"] for j in jobs) or 1.0
    guaranteed_total = sum(g for _, g, _ in TENANTS) or 1
    tenants = {}
    for name, guaranteed, ceiling in TENANTS:
        mine = [j for j in jobs if j["tenant"] == name]
        waits = sorted((j["first_admit"] - j["arrival"]) for j in mine
                       if j["first_admit"] is not None)
        ticks = backlog_ticks[name]
        tenants[name] = {
            "jobs": len(mine),
            "completed": sum(1 for j in mine if j["done_at"] is not None),
            "guaranteed_chips": guaranteed,
            "guaranteed_share": round(guaranteed / guaranteed_total, 9),
            "demanded_chip_s": round(
                sum(j["chips"] * j["duration"] for j in mine), 6),
            "delivered_window_chip_s": round(
                sum(j["delivered_window"] for j in mine), 6),
            "goodput_share": round(
                sum(j["delivered_window"] for j in mine) / total_window, 9),
            "avg_backlogged_chips": round(
                backlog_used[name] / ticks, 6) if ticks else 0.0,
            "backlogged_ticks": ticks,
            "mean_wait_s": round(sum(waits) / len(waits), 6)
            if waits else 0.0,
            "p95_wait_s": round(waits[int(0.95 * (len(waits) - 1))], 6)
            if waits else 0.0,
            "max_wait_s": round(waits[-1], 6) if waits else 0.0,
            "preemptions": sum(j["preemptions"] for j in mine),
            "reclaim_notices": sum(1 for n, _ in notices
                                   if jobs_by_name[n]["tenant"] == name),
            "starvation_escalations": sum(1 for t, _ in escalated_keys
                                          if t == name),
        }
    return {
        "seed": seed,
        "makespan_s": round(clock["t"], 6),
        "arrival_window_s": round(window_end, 6),
        "completed": JOBS - len(undone),
        "violations": violations,
        "tenants": tenants,
    }


def run_curve(seeds) -> dict:
    runs = [run_case(seed) for seed in seeds]
    curve = {
        name: [r["tenants"][name]["goodput_share"] for r in runs]
        for name, _, _ in TENANTS
    }
    return {
        "schema": SCHEMA,
        "scenario": "contention-storm-1k",
        "jobs": JOBS,
        "tick_s": TICK_S,
        "pool": {
            "totalChips": TOTAL_CHIPS,
            "starvationBoundSeconds": STARVATION_BOUND_S,
            "reclaimNoticeSeconds": NOTICE_S,
            "tenants": [{"name": n, "guaranteedChips": g,
                         "ceilingChips": c} for n, g, c in TENANTS],
        },
        "seeds": list(seeds),
        "curve": curve,
        "runs": runs,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="quota_bench")
    ap.add_argument("--seeds", default="0,1,2,3,4",
                    help="comma-separated seed list")
    ap.add_argument("--out", default="",
                    help="write the artifact here (default: stdout)")
    args = ap.parse_args(argv)
    seeds = [int(s) for s in args.seeds.split(",") if s != ""]
    doc = run_curve(seeds)
    payload = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as fh:
            fh.write(payload)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(payload)
    bad = [r["seed"] for r in doc["runs"] if r["violations"]]
    if bad:
        print(f"violations in seeds {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
