"""Control-plane hot-path micro-benchmark: the scale-up storm.

Measures the store -> workqueue -> reconcile pipeline under the
workload the ROADMAP's 10k-cluster north star cares about: K TpuClusters
x N hosts created at once, pods run by the in-process fake kubelet,
REAL worker threads and wall-clock time (no virtual clock) — so the
numbers isolate exactly the paths the indexed-store/CoW-read/off-lock
fan-out/workqueue overhaul touches (docs/performance.md).

    python benchmark/controlplane_bench.py --clusters 24 --workers 4
    python benchmark/controlplane_bench.py --clusters 3000 --shards 4 \
        --template light

Emits ONE JSON object on stdout (the ``tpu-bench/v1`` artifact schema
the scale ladder commits under benchmark/results/ — see
``ARTIFACT_KEYS``):

    {"schema": "tpu-bench/v1", "events_per_sec": ...,
     "reconciles_per_sec": ..., "store_write_p99_ms": ...,
     "workqueue_depth_max": ..., "workqueue_wait_p99_ms": ...,
     "rss_peak_mib": ..., ...}

Runs against older checkouts too (``--dispatch``/``--shards`` degrade
gracefully when the store/manager predate them), which is how the
before/after tables in docs/performance.md were produced.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# Anchor imports on the repo root (this file's parent's parent), not the
# CWD — the harness must work from any invocation directory.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kuberay_tpu.controlplane.cluster_controller import TpuClusterController  # noqa: E402
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet  # noqa: E402
from kuberay_tpu.controlplane.manager import Manager, owned_pod_mapper  # noqa: E402
from kuberay_tpu.controlplane.store import ObjectStore  # noqa: E402
from kuberay_tpu.utils import constants as C  # noqa: E402
from kuberay_tpu.utils.metrics import ControlPlaneMetrics  # noqa: E402


def _template(role: str) -> dict:
    """A production-shaped pod template (env, resources, annotations):
    read-path cost scales with object size, so a toy template would
    flatter whole-object-copy implementations."""
    return {
        "metadata": {
            "labels": {"app.kubernetes.io/part-of": "storm-bench",
                       "role": role},
            "annotations": {
                "prometheus.io/scrape": "true",
                "prometheus.io/port": "8080",
                "cluster-autoscaler.kubernetes.io/safe-to-evict": "false",
            },
        },
        "spec": {
            "containers": [{
                "name": role, "image": "rt:bench",
                "command": ["python", "-m", "kuberay_tpu.runtime.worker"],
                "env": [{"name": f"BENCH_ENV_{j}", "value": f"v{j}"}
                        for j in range(16)],
                "ports": [{"name": "grpc", "containerPort": 50051},
                          {"name": "metrics", "containerPort": 8080}],
                "resources": {
                    "requests": {"cpu": "8", "memory": "32Gi",
                                 "google.com/tpu": "4"},
                    "limits": {"cpu": "8", "memory": "32Gi",
                               "google.com/tpu": "4"},
                },
            }],
            "nodeSelector": {"cloud.google.com/gke-spot": "false"},
            "tolerations": [{"key": "google.com/tpu", "operator": "Exists",
                             "effect": "NoSchedule"}],
        },
    }


def _light_template(role: str) -> dict:
    """Minimal pod template for orchestration-scale rungs (the
    clusterloader2 shape): at 10k clusters the production template's
    per-object weight dominates RSS, which is a different experiment —
    the ladder isolates control-plane throughput."""
    return {"spec": {"containers": [{"name": role, "image": "rt:bench"}]}}


def cluster_manifest(i: int, topology: str, slices: int,
                     accelerator: str = "v5p",
                     template: str = "production") -> dict:
    tmpl = _template if template == "production" else _light_template
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_CLUSTER,
        "metadata": {"name": f"storm-{i:05d}", "namespace": "default"},
        "spec": {
            "headGroupSpec": {"template": tmpl("head")},
            "workerGroupSpecs": [{
                "groupName": "workers", "accelerator": accelerator,
                "topology": topology, "replicas": slices,
                "maxReplicas": max(slices, 1),
                "template": tmpl("worker")}],
        },
    }


def vm_rss_mib() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def rss_peak_mib() -> float:
    """Process high-water RSS (ru_maxrss is KiB on Linux)."""
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except Exception:
        return 0.0


# Interpolated quantile over a pre-sorted list — the shared estimator
# (kuberay_tpu/utils/quantiles.py), same convention as serve_bench.
from kuberay_tpu.utils.quantiles import sorted_quantile as quantile  # noqa: E402


class _AdmissionScheduler:
    """Gang-admission stand-in with the latency profile of a real batch
    scheduler adapter (Volcano/YuniKorn/Kai all do a network round-trip
    per submission): reconciles that admit clusters BLOCK for
    ``delay_s``.  This is the component multi-worker reconcile overlaps
    — a pure-CPU storm is GIL-serialized and hides that win."""

    def __init__(self, delay_s: float):
        self.delay_s = delay_s

    def on_cluster_submission(self, cluster: dict) -> bool:
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        return True

    def add_metadata(self, cluster: dict, pod: dict) -> None:
        pass

    def cleanup(self, cluster: dict) -> None:
        pass


class _Timed:
    """Wall-clock sample collector for a wrapped callable."""

    def __init__(self, fn):
        self.fn = fn
        self.samples = []
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        t0 = time.perf_counter()
        try:
            return self.fn(*args, **kwargs)
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.samples.append(dt)


def build_store(dispatch: str, backlog_max: int = 0) -> ObjectStore:
    try:
        if backlog_max:
            return ObjectStore(dispatch=dispatch, backlog_max=backlog_max)
        return ObjectStore(dispatch=dispatch)
    except TypeError:
        # Pre-overhaul store (the "before" leg of docs/performance.md):
        # no dispatch modes, always-inline fan-out.
        return ObjectStore()


class _QueueStats:
    """Wraps the metrics facade's workqueue hooks to keep raw samples
    (the registry only has histogram buckets; the artifact wants
    interpolated quantiles + max depth)."""

    def __init__(self, metrics):
        self._metrics = metrics
        self._lock = threading.Lock()
        self.depth_max = 0
        self.waits = []

    def __getattr__(self, name):
        return getattr(self._metrics, name)

    def workqueue_depth(self, queue, depth):
        with self._lock:
            if depth > self.depth_max:
                self.depth_max = depth
        self._metrics.workqueue_depth(queue, depth)

    def workqueue_latency(self, queue, seconds):
        with self._lock:
            self.waits.append(seconds)
        self._metrics.workqueue_latency(queue, seconds)


def run_storm(clusters: int, slices: int, topology: str, workers: int,
              dispatch: str, timeout: float,
              sched_latency_ms: float = 2.0, shards: int = 1,
              accelerator: str = "v5p",
              template: str = "production",
              backlog_max: int = 0) -> dict:
    rss0 = vm_rss_mib()
    store = build_store(dispatch, backlog_max=backlog_max)
    metrics = _QueueStats(ControlPlaneMetrics())
    try:
        manager = Manager(store, metrics=metrics, shards=shards)
    except TypeError:
        # Pre-sharding manager (older checkout): single pool only.
        manager = Manager(store, metrics=metrics)
        shards = 1
    controller = TpuClusterController(
        store, expectations=manager.expectations, metrics=metrics,
        scheduler=_AdmissionScheduler(sched_latency_ms / 1e3))
    reconcile = _Timed(controller.reconcile)
    manager.register(C.KIND_CLUSTER, reconcile)
    manager.map_owned(owned_pod_mapper)
    kubelet = FakeKubelet(store)

    # Store-write latency: every mutating verb the storm exercises.
    writes = _Timed(None)

    def timed(fn):
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                dt = time.perf_counter() - t0
                with writes._lock:
                    writes.samples.append(dt)
        return wrapper

    store.create = timed(store.create)
    store.update = timed(store.update)          # update_status routes here
    store.delete = timed(store.delete)

    stop = threading.Event()

    def kubelet_loop():
        while not stop.is_set():
            kubelet.step()
            stop.wait(0.002)

    kt = threading.Thread(target=kubelet_loop, daemon=True,
                          name="bench-kubelet")

    manager.start(workers=workers)
    kt.start()
    t0 = time.perf_counter()
    for i in range(clusters):
        store.create(cluster_manifest(i, topology, slices,
                                      accelerator=accelerator,
                                      template=template))
    create_phase = time.perf_counter() - t0

    deadline = t0 + timeout
    ready = 0
    # Readiness polling scales with the rung: a 10 ms full-list poll at
    # 10k clusters would burn a core in the measuring loop itself.
    poll = min(0.25, max(0.01, clusters / 20000.0))
    while time.perf_counter() < deadline:
        ready = sum(1 for c in store.list(C.KIND_CLUSTER)
                    if c.get("status", {}).get("state") == "ready")
        if ready >= clusters:
            break
        time.sleep(poll)
    elapsed = time.perf_counter() - t0
    stop.set()
    manager.stop()
    kt.join(timeout=2.0)
    kubelet.close()
    if hasattr(store, "close"):
        store.close()

    rec = sorted(reconcile.samples)
    wr = sorted(writes.samples)
    with metrics._lock:
        waits = sorted(metrics.waits)
        depth_max = metrics.depth_max
    events = store.resource_version()
    evictions = (store.backlog_evictions_total()
                 if hasattr(store, "backlog_evictions_total") else 0)
    return {
        "schema": "tpu-bench/v1",
        "workload": {"clusters": clusters, "slices_per_cluster": slices,
                     "topology": topology, "accelerator": accelerator,
                     "template": template, "pods": store.count("Pod"),
                     "workers": workers, "shards": shards,
                     "dispatch": dispatch,
                     "sched_latency_ms": sched_latency_ms},
        "ready_clusters": ready,
        "converged": ready >= clusters,
        "elapsed_s": round(elapsed, 3),
        "create_phase_s": round(create_phase, 3),
        "events": events,
        "events_per_sec": round(events / elapsed, 1),
        "reconciles": len(rec),
        "reconciles_per_sec": round(len(rec) / elapsed, 1),
        "reconcile_p50_ms": round(quantile(rec, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(quantile(rec, 0.99) * 1e3, 3),
        "store_writes": len(wr),
        "store_write_p50_ms": round(quantile(wr, 0.50) * 1e3, 3),
        "store_write_p99_ms": round(quantile(wr, 0.99) * 1e3, 3),
        "workqueue_depth_max": depth_max,
        "workqueue_wait_p50_ms": round(quantile(waits, 0.50) * 1e3, 3),
        "workqueue_wait_p99_ms": round(quantile(waits, 0.99) * 1e3, 3),
        "watch_backlog_evictions": evictions,
        "rss_mib": round(vm_rss_mib() - rss0, 1),
        "rss_peak_mib": round(rss_peak_mib(), 1),
    }


#: The artifact contract tools/bench_scale.sh asserts: every ladder rung
#: JSON must carry at least these keys.
ARTIFACT_KEYS = (
    "schema", "workload", "ready_clusters", "converged", "elapsed_s",
    "events", "events_per_sec", "reconciles", "reconciles_per_sec",
    "store_writes", "store_write_p99_ms", "workqueue_depth_max",
    "workqueue_wait_p99_ms", "rss_peak_mib",
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="scale-up-storm control-plane benchmark")
    ap.add_argument("--clusters", type=int, default=24)
    ap.add_argument("--slices", type=int, default=2,
                    help="worker slices per cluster")
    ap.add_argument("--topology", default="2x2x2",
                    help="v5p slice topology (2x2x2 = 2 hosts/slice)")
    ap.add_argument("--workers", type=int, default=4,
                    help="reconcile worker threads PER SHARD")
    ap.add_argument("--shards", type=int, default=1,
                    help="hash-sharded reconcile pools (sharding.py)")
    ap.add_argument("--accelerator", default="v5p")
    ap.add_argument("--template", default="production",
                    choices=("production", "light"),
                    help="pod template weight: production (16 env vars, "
                         "resources — honest read cost) or light (the "
                         "clusterloader2 orchestration-scale shape)")
    ap.add_argument("--dispatch", default="async",
                    choices=("sync", "async"))
    ap.add_argument("--backlog-max", type=int, default=0,
                    help="store watch-backlog window (0 = store default)")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--sched-latency-ms", type=float, default=2.0,
                    help="blocking gang-admission latency per cluster "
                         "reconcile (models the batch-scheduler network "
                         "round-trip; 0 disables)")
    ap.add_argument("--out", default="",
                    help="also write the artifact JSON to this path")
    args = ap.parse_args(argv)
    result = run_storm(args.clusters, args.slices, args.topology,
                       args.workers, args.dispatch, args.timeout,
                       sched_latency_ms=args.sched_latency_ms,
                       shards=args.shards, accelerator=args.accelerator,
                       template=args.template,
                       backlog_max=args.backlog_max)
    text = json.dumps(result, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0 if result["converged"] else 1


if __name__ == "__main__":
    sys.exit(main())
