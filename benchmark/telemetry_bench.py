"""Step-telemetry overhead benchmark: the microscope must be ~free.

The straggler microscope (obs/steps.py) rides the hot path of every
sim tick (and, in production, every coordinator heartbeat batch), so
its cost is gated, not assumed.  Both legs run the SAME seeded
``straggler-drill`` scenario — heartbeat emission, slow-window
bookkeeping, and the virtual clock advance all run identically — and
differ only in what ``h.steps`` points at:

- ``tracker``: the real :class:`StepTracker` (windowed distributions,
  skew, verdicts, metric/flight/goodput fan-out);
- ``noop``: :class:`NoopStepTracker` swapped in right after harness
  construction — same surface, zero work.

The delta between the two legs is therefore the tracker's cost alone.
Each repetition times the two legs back-to-back (order alternating per
rep) so load bursts hit both legs of a pair; the per-seed overhead is
the median paired delta over the median noop wall, which survives
outlier reps that a min-of-mins estimator does not.  GC is paused
inside the timed region.  The run self-gates: mean overhead across
seeds must stay under ``--gate-pct`` (default 5%) or the process exits
nonzero.  Both legs must also produce byte-identical journal hashes —
the observational-only contract, re-checked here.

    python benchmark/telemetry_bench.py --out benchmark/results/telemetry_r5.json
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import statistics
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from kuberay_tpu.obs import NoopStepTracker  # noqa: E402
from kuberay_tpu.sim.harness import SimHarness  # noqa: E402
from kuberay_tpu.sim.scenarios import get_scenario  # noqa: E402

SCHEMA = "tpu-telemetry-bench/v1"
TICKS = 12


def _leg(seed: int, noop: bool) -> tuple:
    """One drill run; returns (wall seconds, journal hash, beats)."""
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        with SimHarness(seed, scenario=get_scenario("straggler-drill"),
                        steps=True, goodput=True) as h:
            if noop:
                h.steps = NoopStepTracker()
            res = h.run(TICKS)
            # Stop the clock before the read-side accounting below: the
            # noop leg would answer it for free, skewing the comparison.
            wall = time.perf_counter() - t0
            if not res.ok:
                raise SystemExit(
                    f"seed {seed} violations: {res.violations}")
            beats = sum(host["steps_observed"]
                        for row in h.steps.to_dict()["jobs"]
                        for host in h.steps.job_doc(row["job"])["hosts"])
    finally:
        gc.enable()
    return wall, res.journal_hash, beats


def run(seeds: int, reps: int) -> dict:
    rows = []
    for seed in range(seeds):
        hashes = set()
        beats = 0
        deltas = []
        noop_walls = []
        tracker_walls = []
        _leg(seed, False)  # warmup: fill code/alloc caches off the clock
        for rep in range(reps):
            order = ((False, True) if rep % 2 == 0 else (True, False))
            pair = {}
            for noop in order:
                wall, jh, n = _leg(seed, noop)
                hashes.add(jh)
                beats = max(beats, n)
                pair[noop] = wall
            deltas.append(pair[False] - pair[True])
            tracker_walls.append(pair[False])
            noop_walls.append(pair[True])
        if len(hashes) != 1:
            raise SystemExit(f"seed {seed}: journal hash diverged "
                             f"between legs: {sorted(hashes)}")
        base = statistics.median(noop_walls)
        overhead = statistics.median(deltas) / base * 100.0
        rows.append({"seed": seed,
                     "tracker_s": round(statistics.median(tracker_walls), 6),
                     "noop_s": round(base, 6),
                     "heartbeats": beats,
                     "overhead_pct": round(overhead, 3)})
    return {"schema": SCHEMA, "ticks": TICKS, "reps": reps, "runs": rows}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--reps", type=int, default=3,
                    help="repetitions per leg; min wall time wins")
    ap.add_argument("--gate-pct", type=float, default=5.0,
                    help="max mean overhead before the bench fails")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    doc = run(args.seeds, args.reps)
    mean = sum(r["overhead_pct"] for r in doc["runs"]) / len(doc["runs"])
    doc["mean_overhead_pct"] = round(mean, 3)
    doc["gate_pct"] = args.gate_pct
    doc["gate_ok"] = mean < args.gate_pct

    for r in doc["runs"]:
        print(f"seed {r['seed']}: tracker {r['tracker_s']:.4f}s  "
              f"noop {r['noop_s']:.4f}s  "
              f"({r['heartbeats']} beats)  "
              f"overhead {r['overhead_pct']:+.2f}%")
    print(f"mean overhead {mean:+.2f}%  "
          f"(gate < {args.gate_pct:.1f}%): "
          f"{'OK' if doc['gate_ok'] else 'FAIL'}")
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        print(f"wrote {args.out}")
    return 0 if doc["gate_ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
