"""Orchestration-scale benchmark: the clusterloader2-analogue.

The reference's only published performance numbers are orchestration-scale
(BASELINE.md: 100/1,000/5,000/10,000 RayClusters all-pods-Running within
clusterloader2 timeouts on GKE).  This harness reproduces that shape
against our control plane: N TpuClusters (or TpuJobs) created through the
operator, measuring wall time until every cluster reports ready — pods
executed by the in-process fake kubelet, so the number isolates
control-plane throughput exactly like the reference's memory/scale
benchmarks isolate the operator.

    python benchmark/scale_bench.py --clusters 1000
    python benchmark/scale_bench.py --jobs 100

Outputs one JSON line per phase (compatible with BENCH recording).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

from kuberay_tpu.api.config import OperatorConfiguration  # noqa: E402
from kuberay_tpu.operator import Operator  # noqa: E402
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient  # noqa: E402
from kuberay_tpu.utils import constants as C  # noqa: E402


def cluster_manifest(i: int) -> dict:
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_CLUSTER,
        "metadata": {"name": f"bench-{i}", "namespace": "default"},
        "spec": {
            "headGroupSpec": {"template": {"spec": {"containers": [
                {"name": "head", "image": "rt:bench"}]}}},
            "workerGroupSpecs": [{
                "groupName": "workers", "accelerator": "v5e",
                "topology": "2x2", "replicas": 1, "maxReplicas": 1,
                "template": {"spec": {"containers": [
                    {"name": "w", "image": "rt:bench"}]}}}],
        },
    }


def job_manifest(i: int) -> dict:
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": f"bench-job-{i}", "namespace": "default"},
        "spec": {
            "entrypoint": f"python -m noop --i {i}",
            "submissionMode": "HTTPMode",
            "shutdownAfterJobFinishes": True,
            "clusterSpec": cluster_manifest(i)["spec"],
        },
    }


def vm_rss_mib() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def run_cluster_scale(n: int, timeout: float) -> dict:
    rss0 = vm_rss_mib()
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=4),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    t0 = time.time()
    for i in range(n):
        op.store.create(cluster_manifest(i))
    created = time.time() - t0

    deadline = time.time() + timeout
    ready = 0
    while time.time() < deadline:
        ready = sum(
            1 for c in op.store.list(C.KIND_CLUSTER)
            if c.get("status", {}).get("state") == "ready")
        if ready >= n:
            break
        time.sleep(0.2)
    elapsed = time.time() - t0
    pods = op.store.count("Pod")
    rss = round(vm_rss_mib() - rss0, 1)
    op.stop()
    return {
        "metric": "tpucluster_scale_all_ready_seconds",
        "value": round(elapsed, 2),
        "unit": "s",
        "detail": {"clusters": n, "ready": ready, "pods": pods,
                   "create_phase_s": round(created, 2),
                   "clusters_per_s": round(n / elapsed, 1),
                   # Memory is what kills operators at 5000-cluster scale
                   # (reference memory benchmark, see docs/memory_benchmark.md);
                   # track it alongside latency on every run.
                   "rss_mib": rss,
                   "rss_kib_per_cluster": round(rss * 1024 / max(n, 1), 1),
                   "pass": ready >= n,
                   "reference": "BASELINE.md: 100-10000 RayClusters within "
                                "30m clusterloader2 steps"},
    }


def run_job_scale(n: int, timeout: float) -> dict:
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=4),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    t0 = time.time()
    for i in range(n):
        op.store.create(job_manifest(i))
    deadline = time.time() + timeout
    done = 0
    while time.time() < deadline:
        jobs = op.store.list(C.KIND_JOB)
        # Drive the fake coordinator: finish any running app jobs.
        for j in jobs:
            jid = j.get("status", {}).get("jobId")
            if jid and jid in coord.jobs and \
                    coord.jobs[jid].status == "PENDING":
                coord.set_job_status(jid, "SUCCEEDED")
        done = sum(1 for j in jobs
                   if j.get("status", {}).get("jobDeploymentStatus")
                   == "Complete")
        if done >= n:
            break
        time.sleep(0.2)
    elapsed = time.time() - t0
    op.stop()
    return {
        "metric": "tpujob_scale_all_complete_seconds",
        "value": round(elapsed, 2),
        "unit": "s",
        "detail": {"jobs": n, "complete": done,
                   "jobs_per_s": round(n / elapsed, 1), "pass": done >= n,
                   "reference": "BASELINE.md: 100-5000 RayJobs to completion"},
    }


def _memory_experiment(exp: str, timeout: float) -> dict:
    """One 150-pod shape, measured in THIS process via VmRSS delta."""
    baseline = vm_rss_mib()
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=2),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    if exp == "exp1":          # 150 head-only clusters
        objs = [{**cluster_manifest(i),
                 "spec": {**cluster_manifest(i)["spec"],
                          "workerGroupSpecs": []}} for i in range(150)]
        want = 150
    elif exp == "exp2":        # 1 cluster with 150 single-host slices
        big = cluster_manifest(9000)
        big["spec"]["workerGroupSpecs"][0].update(replicas=150,
                                                  maxReplicas=150)
        objs, want = [big], 1
    else:                      # exp3: 30 five-pod clusters (head + 4 hosts)
        objs = []
        for i in range(30):
            m = cluster_manifest(9100 + i)
            m["spec"]["workerGroupSpecs"][0].update(accelerator="v5e",
                                                    topology="4x4")
            objs.append(m)
        want = 30
    for obj in objs:
        op.store.create(obj)
    deadline = time.time() + timeout
    while time.time() < deadline:
        ready = sum(1 for c in op.store.list(C.KIND_CLUSTER)
                    if c.get("status", {}).get("state") == "ready")
        if ready >= want:
            break
        time.sleep(0.2)
    out = {"pods": op.store.count("Pod"),
           "rss_mib": round(vm_rss_mib() - baseline, 1)}
    op.stop()
    return out


def run_memory_bench(timeout: float) -> dict:
    """Operator memory envelope (ref benchmark/memory_benchmark: 150 Ray
    pods across three shapes).  Each experiment runs in its OWN subprocess
    so the measurements are independent footprints, not cumulative maxima.
    """
    import subprocess
    import sys as _sys

    results = {}
    for exp in ("exp1", "exp2", "exp3"):
        out = subprocess.run(
            [_sys.executable, __file__, "--memory-exp", exp,
             "--timeout", str(timeout)],
            capture_output=True, text=True, timeout=timeout + 120)
        data = json.loads(out.stdout.strip().splitlines()[-1])
        results[exp + "_pods"] = data["pods"]
        results[exp + "_rss_mib"] = data["rss_mib"]
    return {
        "metric": "operator_memory_envelope_mib",
        "value": max(results["exp1_rss_mib"], results["exp2_rss_mib"],
                     results["exp3_rss_mib"]),
        "unit": "MiB RSS delta",
        "detail": {**results,
                   "reference": "BASELINE.md: 150-pod shapes on "
                                "e2-highcpu-16 nodes (graph only)"},
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clusters", type=int, default=0)
    ap.add_argument("--jobs", type=int, default=0)
    ap.add_argument("--memory", action="store_true",
                    help="run the 150-pod operator memory envelope")
    ap.add_argument("--memory-exp", default="",
                    help=argparse.SUPPRESS)   # internal: one experiment
    ap.add_argument("--timeout", type=float, default=1800.0)
    args = ap.parse_args(argv)
    if args.memory_exp:
        print(json.dumps(_memory_experiment(args.memory_exp, args.timeout)),
              flush=True)
        return
    if not args.clusters and not args.jobs and not args.memory:
        args.clusters = 100
    if args.clusters:
        print(json.dumps(run_cluster_scale(args.clusters, args.timeout)),
              flush=True)
    if args.jobs:
        print(json.dumps(run_job_scale(args.jobs, args.timeout)), flush=True)
    if args.memory:
        print(json.dumps(run_memory_bench(args.timeout)), flush=True)


if __name__ == "__main__":
    main()
