"""Orchestration-scale benchmark: the clusterloader2-analogue.

The reference's only published performance numbers are orchestration-scale
(BASELINE.md: 100/1,000/5,000/10,000 RayClusters all-pods-Running within
clusterloader2 timeouts on GKE).  This harness reproduces that shape
against our control plane: N TpuClusters (or TpuJobs) created through the
operator, measuring wall time until every cluster reports ready — pods
executed by the in-process fake kubelet, so the number isolates
control-plane throughput exactly like the reference's memory/scale
benchmarks isolate the operator.

    python benchmark/scale_bench.py --clusters 1000
    python benchmark/scale_bench.py --jobs 100

Outputs one JSON line per phase (compatible with BENCH recording).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

from kuberay_tpu.api.config import OperatorConfiguration  # noqa: E402
from kuberay_tpu.operator import Operator  # noqa: E402
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient  # noqa: E402
from kuberay_tpu.utils import constants as C  # noqa: E402


def cluster_manifest(i: int) -> dict:
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_CLUSTER,
        "metadata": {"name": f"bench-{i}", "namespace": "default"},
        "spec": {
            "headGroupSpec": {"template": {"spec": {"containers": [
                {"name": "head", "image": "rt:bench"}]}}},
            "workerGroupSpecs": [{
                "groupName": "workers", "accelerator": "v5e",
                "topology": "2x2", "replicas": 1, "maxReplicas": 1,
                "template": {"spec": {"containers": [
                    {"name": "w", "image": "rt:bench"}]}}}],
        },
    }


def job_manifest(i: int) -> dict:
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": f"bench-job-{i}", "namespace": "default"},
        "spec": {
            "entrypoint": f"python -m noop --i {i}",
            "submissionMode": "HTTPMode",
            "shutdownAfterJobFinishes": True,
            "clusterSpec": cluster_manifest(i)["spec"],
        },
    }


def run_cluster_scale(n: int, timeout: float) -> dict:
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=4),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    t0 = time.time()
    for i in range(n):
        op.store.create(cluster_manifest(i))
    created = time.time() - t0

    deadline = time.time() + timeout
    ready = 0
    while time.time() < deadline:
        ready = sum(
            1 for c in op.store.list(C.KIND_CLUSTER)
            if c.get("status", {}).get("state") == "ready")
        if ready >= n:
            break
        time.sleep(0.2)
    elapsed = time.time() - t0
    pods = op.store.count("Pod")
    op.stop()
    return {
        "metric": "tpucluster_scale_all_ready_seconds",
        "value": round(elapsed, 2),
        "unit": "s",
        "detail": {"clusters": n, "ready": ready, "pods": pods,
                   "create_phase_s": round(created, 2),
                   "clusters_per_s": round(n / elapsed, 1),
                   "pass": ready >= n,
                   "reference": "BASELINE.md: 100-10000 RayClusters within "
                                "30m clusterloader2 steps"},
    }


def run_job_scale(n: int, timeout: float) -> dict:
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=4),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    t0 = time.time()
    for i in range(n):
        op.store.create(job_manifest(i))
    deadline = time.time() + timeout
    done = 0
    while time.time() < deadline:
        jobs = op.store.list(C.KIND_JOB)
        # Drive the fake coordinator: finish any running app jobs.
        for j in jobs:
            jid = j.get("status", {}).get("jobId")
            if jid and jid in coord.jobs and \
                    coord.jobs[jid].status == "PENDING":
                coord.set_job_status(jid, "SUCCEEDED")
        done = sum(1 for j in jobs
                   if j.get("status", {}).get("jobDeploymentStatus")
                   == "Complete")
        if done >= n:
            break
        time.sleep(0.2)
    elapsed = time.time() - t0
    op.stop()
    return {
        "metric": "tpujob_scale_all_complete_seconds",
        "value": round(elapsed, 2),
        "unit": "s",
        "detail": {"jobs": n, "complete": done,
                   "jobs_per_s": round(n / elapsed, 1), "pass": done >= n,
                   "reference": "BASELINE.md: 100-5000 RayJobs to completion"},
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clusters", type=int, default=0)
    ap.add_argument("--jobs", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=1800.0)
    args = ap.parse_args(argv)
    if not args.clusters and not args.jobs:
        args.clusters = 100
    if args.clusters:
        print(json.dumps(run_cluster_scale(args.clusters, args.timeout)),
              flush=True)
    if args.jobs:
        print(json.dumps(run_job_scale(args.jobs, args.timeout)), flush=True)


if __name__ == "__main__":
    main()
