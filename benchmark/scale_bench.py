"""Orchestration-scale benchmark: the clusterloader2-analogue.

The reference's only published performance numbers are orchestration-scale
(BASELINE.md: 100/1,000/5,000/10,000 RayClusters all-pods-Running within
clusterloader2 timeouts on GKE).  This harness reproduces that shape
against our control plane: N TpuClusters (or TpuJobs) created through the
operator, measuring wall time until every cluster reports ready — pods
executed by the in-process fake kubelet, so the number isolates
control-plane throughput exactly like the reference's memory/scale
benchmarks isolate the operator.

    python benchmark/scale_bench.py --clusters 1000
    python benchmark/scale_bench.py --jobs 100
    python benchmark/scale_bench.py --ladder 300,1000,3000,10000 \
        --ladder-shards 1,4 --out benchmark/results/ladder.json

Outputs one JSON line per phase (compatible with BENCH recording);
``--ladder`` runs the published clusterloader2-shaped rung set — each
(rung, shards) leg in its own subprocess of
``controlplane_bench.py`` so every leg gets an independent RSS
envelope — and writes ONE ``tpu-bench-ladder/v1`` artifact whose rungs
all carry the ``tpu-bench/v1`` schema (docs/performance.md trendline).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# Anchor imports on the repo root, not the CWD — the harness must work
# from any invocation directory.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from kuberay_tpu.api.config import OperatorConfiguration  # noqa: E402
from kuberay_tpu.operator import Operator  # noqa: E402
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient  # noqa: E402
from kuberay_tpu.utils import constants as C  # noqa: E402


def cluster_manifest(i: int) -> dict:
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_CLUSTER,
        "metadata": {"name": f"bench-{i}", "namespace": "default"},
        "spec": {
            "headGroupSpec": {"template": {"spec": {"containers": [
                {"name": "head", "image": "rt:bench"}]}}},
            "workerGroupSpecs": [{
                "groupName": "workers", "accelerator": "v5e",
                "topology": "2x2", "replicas": 1, "maxReplicas": 1,
                "template": {"spec": {"containers": [
                    {"name": "w", "image": "rt:bench"}]}}}],
        },
    }


def job_manifest(i: int) -> dict:
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": f"bench-job-{i}", "namespace": "default"},
        "spec": {
            "entrypoint": f"python -m noop --i {i}",
            "submissionMode": "HTTPMode",
            "shutdownAfterJobFinishes": True,
            "clusterSpec": cluster_manifest(i)["spec"],
        },
    }


def vm_rss_mib() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def rss_peak_mib() -> float:
    """Process high-water RSS (ru_maxrss is KiB on Linux)."""
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except Exception:
        return 0.0


def run_cluster_scale(n: int, timeout: float, shards: int = 1) -> dict:
    rss0 = vm_rss_mib()
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=4,
                                        shardCount=shards),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    t0 = time.time()
    for i in range(n):
        op.store.create(cluster_manifest(i))
    created = time.time() - t0

    deadline = time.time() + timeout
    ready = 0
    poll = min(0.25, max(0.02, n / 20000.0))
    while time.time() < deadline:
        ready = sum(
            1 for c in op.store.list(C.KIND_CLUSTER)
            if c.get("status", {}).get("state") == "ready")
        if ready >= n:
            break
        time.sleep(poll)
    elapsed = time.time() - t0
    pods = op.store.count("Pod")
    events = op.store.resource_version()
    # Reconcile count from the operator's own registry (the _timed
    # wrapper counts tpu_reconcile_total per kind).
    reconciles = int(sum(
        v for (name, _), v in op.metrics.registry._counters.items()
        if name == "tpu_reconcile_total"))
    rss = round(vm_rss_mib() - rss0, 1)
    op.stop()
    return {
        "metric": "tpucluster_scale_all_ready_seconds",
        "value": round(elapsed, 2),
        "unit": "s",
        # tpu-bench/v1 parity with controlplane_bench.py so ladder
        # tooling consumes either harness's output unchanged.
        "schema": "tpu-bench/v1",
        "workload": {"clusters": n, "slices_per_cluster": 1,
                     "topology": "2x2", "accelerator": "v5e",
                     "template": "light", "pods": pods,
                     "workers": 4, "shards": shards, "dispatch": "sync",
                     "sched_latency_ms": 0.0},
        "ready_clusters": ready,
        "converged": ready >= n,
        "elapsed_s": round(elapsed, 3),
        "create_phase_s": round(created, 3),
        "events": events,
        "events_per_sec": round(events / elapsed, 1),
        "reconciles": reconciles,
        "reconciles_per_sec": round(reconciles / elapsed, 1),
        "rss_mib": rss,
        "rss_peak_mib": round(rss_peak_mib(), 1),
        "detail": {"clusters": n, "ready": ready, "pods": pods,
                   "create_phase_s": round(created, 2),
                   "clusters_per_s": round(n / elapsed, 1),
                   # Memory is what kills operators at 5000-cluster scale
                   # (reference memory benchmark, see docs/memory_benchmark.md);
                   # track it alongside latency on every run.
                   "rss_mib": rss,
                   "rss_kib_per_cluster": round(rss * 1024 / max(n, 1), 1),
                   "pass": ready >= n,
                   "reference": "BASELINE.md: 100-10000 RayClusters within "
                                "30m clusterloader2 steps"},
    }


def run_job_scale(n: int, timeout: float) -> dict:
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=4),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    t0 = time.time()
    for i in range(n):
        op.store.create(job_manifest(i))
    deadline = time.time() + timeout
    done = 0
    while time.time() < deadline:
        jobs = op.store.list(C.KIND_JOB)
        # Drive the fake coordinator: finish any running app jobs.
        for j in jobs:
            jid = j.get("status", {}).get("jobId")
            if jid and jid in coord.jobs and \
                    coord.jobs[jid].status == "PENDING":
                coord.set_job_status(jid, "SUCCEEDED")
        done = sum(1 for j in jobs
                   if j.get("status", {}).get("jobDeploymentStatus")
                   == "Complete")
        if done >= n:
            break
        time.sleep(0.2)
    elapsed = time.time() - t0
    op.stop()
    return {
        "metric": "tpujob_scale_all_complete_seconds",
        "value": round(elapsed, 2),
        "unit": "s",
        "detail": {"jobs": n, "complete": done,
                   "jobs_per_s": round(n / elapsed, 1), "pass": done >= n,
                   "reference": "BASELINE.md: 100-5000 RayJobs to completion"},
    }


def _memory_experiment(exp: str, timeout: float) -> dict:
    """One 150-pod shape, measured in THIS process via VmRSS delta."""
    baseline = vm_rss_mib()
    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(reconcileConcurrency=2),
                  client_provider=lambda s: coord, fake_kubelet=True)
    op.start(api_port=0)
    if exp == "exp1":          # 150 head-only clusters
        objs = [{**cluster_manifest(i),
                 "spec": {**cluster_manifest(i)["spec"],
                          "workerGroupSpecs": []}} for i in range(150)]
        want = 150
    elif exp == "exp2":        # 1 cluster with 150 single-host slices
        big = cluster_manifest(9000)
        big["spec"]["workerGroupSpecs"][0].update(replicas=150,
                                                  maxReplicas=150)
        objs, want = [big], 1
    else:                      # exp3: 30 five-pod clusters (head + 4 hosts)
        objs = []
        for i in range(30):
            m = cluster_manifest(9100 + i)
            m["spec"]["workerGroupSpecs"][0].update(accelerator="v5e",
                                                    topology="4x4")
            objs.append(m)
        want = 30
    for obj in objs:
        op.store.create(obj)
    deadline = time.time() + timeout
    while time.time() < deadline:
        ready = sum(1 for c in op.store.list(C.KIND_CLUSTER)
                    if c.get("status", {}).get("state") == "ready")
        if ready >= want:
            break
        time.sleep(0.2)
    out = {"pods": op.store.count("Pod"),
           "rss_mib": round(vm_rss_mib() - baseline, 1)}
    op.stop()
    return out


def run_memory_bench(timeout: float) -> dict:
    """Operator memory envelope (ref benchmark/memory_benchmark: 150 Ray
    pods across three shapes).  Each experiment runs in its OWN subprocess
    so the measurements are independent footprints, not cumulative maxima.
    """
    import subprocess
    import sys as _sys

    results = {}
    for exp in ("exp1", "exp2", "exp3"):
        out = subprocess.run(
            [_sys.executable, __file__, "--memory-exp", exp,
             "--timeout", str(timeout)],
            capture_output=True, text=True, timeout=timeout + 120)
        data = json.loads(out.stdout.strip().splitlines()[-1])
        results[exp + "_pods"] = data["pods"]
        results[exp + "_rss_mib"] = data["rss_mib"]
    return {
        "metric": "operator_memory_envelope_mib",
        "value": max(results["exp1_rss_mib"], results["exp2_rss_mib"],
                     results["exp3_rss_mib"]),
        "unit": "MiB RSS delta",
        "detail": {**results,
                   "reference": "BASELINE.md: 150-pod shapes on "
                                "e2-highcpu-16 nodes (graph only)"},
    }


def run_ladder(rungs, shard_list, timeout: float, workers: int = 4,
               template: str = "light") -> dict:
    """The published scale ladder: every (rung, shards) leg runs
    ``controlplane_bench.py`` in its own subprocess (independent RSS
    envelope per leg, like the memory bench) with the orchestration-
    scale workload shape — 1 single-host slice per cluster (v5e 2x2),
    light templates — and a watch backlog sized so the storm itself is
    resumable (the 10k rung emits far more than the 10k default).
    """
    bench = os.path.join(_REPO_ROOT, "benchmark", "controlplane_bench.py")
    legs = []
    for n in rungs:
        for shards in shard_list:
            cmd = [sys.executable, bench,
                   "--clusters", str(n), "--shards", str(shards),
                   "--workers", str(workers), "--slices", "1",
                   "--topology", "2x2", "--accelerator", "v5e",
                   "--template", template,
                   "--backlog-max", str(max(10000, 16 * n)),
                   "--timeout", str(timeout)]
            print(f"# ladder leg: clusters={n} shards={shards}",
                  file=sys.stderr, flush=True)
            t0 = time.time()
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout + 300)
            try:
                leg = json.loads(proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                leg = {"schema": "tpu-bench/v1", "converged": False,
                       "error": (proc.stderr or proc.stdout)[-2000:],
                       "workload": {"clusters": n, "shards": shards}}
            leg["leg_wall_s"] = round(time.time() - t0, 1)
            legs.append(leg)
            print(json.dumps(leg, sort_keys=True), flush=True)
    return {
        "schema": "tpu-bench-ladder/v1",
        "rungs": sorted(rungs),
        "shards": sorted(shard_list),
        "workers_per_shard": workers,
        "legs": legs,
        "converged": all(leg.get("converged") for leg in legs),
    }


def _int_list(spec: str):
    return [int(x) for x in spec.split(",") if x.strip()]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clusters", type=int, default=0)
    ap.add_argument("--shards", type=int, default=1,
                    help="reconcile shard pools for --clusters mode")
    ap.add_argument("--jobs", type=int, default=0)
    ap.add_argument("--memory", action="store_true",
                    help="run the 150-pod operator memory envelope")
    ap.add_argument("--memory-exp", default="",
                    help=argparse.SUPPRESS)   # internal: one experiment
    ap.add_argument("--ladder", default="",
                    help="comma-separated rungs, e.g. 300,1000,3000,10000: "
                         "run the published scale ladder via "
                         "controlplane_bench subprocesses")
    ap.add_argument("--ladder-shards", default="1,4",
                    help="shard counts per rung (comma-separated)")
    ap.add_argument("--ladder-workers", type=int, default=4,
                    help="worker threads per shard on each leg")
    ap.add_argument("--out", default="",
                    help="write the final JSON artifact to this path")
    ap.add_argument("--timeout", type=float, default=1800.0)
    args = ap.parse_args(argv)
    if args.memory_exp:
        print(json.dumps(_memory_experiment(args.memory_exp, args.timeout)),
              flush=True)
        return

    def emit(doc):
        print(json.dumps(doc, sort_keys=True), flush=True)
        if args.out:
            os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                        exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(doc, f, sort_keys=True, indent=1)
                f.write("\n")

    if args.ladder:
        emit(run_ladder(_int_list(args.ladder),
                        _int_list(args.ladder_shards),
                        args.timeout, workers=args.ladder_workers))
        return
    if not args.clusters and not args.jobs and not args.memory:
        args.clusters = 100
    if args.clusters:
        emit(run_cluster_scale(args.clusters, args.timeout,
                               shards=args.shards))
    if args.jobs:
        print(json.dumps(run_job_scale(args.jobs, args.timeout)), flush=True)
    if args.memory:
        print(json.dumps(run_memory_bench(args.timeout)), flush=True)


if __name__ == "__main__":
    main()
