"""Serving throughput bench: dense vs paged KV engines.

Prints one JSON line per engine with decode tokens/s and (paged) prefix
cache hit rate, over a workload of concurrent requests sharing a system
prompt — the shape paged attention + prefix caching exist for.  The
train-side counterpart of the driver's bench.py; run with --cpu off-chip.

Usage: python benchmark/serve_bench.py [--cpu] [--model llama_tiny]
       [--requests 16] [--prefix 64] [--new 32] [--slots 8]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def percentile(samples, pct):
    """Interpolated percentile (statistics.quantiles 'inclusive' method).

    The previous truncating index ``int(n * 0.99) - 1`` collapses
    small-sample p99 toward p90: for n=21 it picks index 19 and never
    reports the tail sample at all — exactly the latency outlier a p99
    exists to surface.  Interpolation uses the full tail: for n=21 over
    1..21 the p99 is 20.8 (between the two largest samples).
    """
    import statistics
    xs = sorted(samples)
    if not xs:
        raise ValueError("percentile() of no samples")
    if len(xs) == 1:
        return xs[0]
    return statistics.quantiles(xs, n=100, method="inclusive")[pct - 1]


def run(args) -> None:
    import jax
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    shared = list(range(1, args.prefix + 1))

    def requests():
        return [Request(f"r{i}", shared + [100 + i],
                        max_new_tokens=args.new)
                for i in range(args.requests)]

    def drive(engine, label):
        # Warmup: compile every program the timed pass will hit (full
        # prefill bucket, cached-suffix bucket on the paged path, decode)
        # — otherwise compile seconds dwarf decode ms and invert the
        # comparison.  The timed pass therefore measures warm-cache
        # steady state for the paged engine (its serving regime).
        for i in range(2):
            engine.add_request(Request(f"warm{i}", shared + [90 + i],
                                       max_new_tokens=2))
            engine.run()
        for r in requests():
            engine.add_request(r)
        t0 = time.perf_counter()
        out = engine.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in out)
        rec = {
            "metric": f"serve_decode_tokens_per_sec_{label}",
            "value": round(toks / dt, 1),
            "unit": "tokens/s",
            "detail": {"model": args.model, "requests": len(out),
                       "prefix_len": args.prefix, "new_tokens": args.new,
                       "slots": args.slots, "wall_s": round(dt, 2)},
        }
        stats = getattr(engine, "stats", None)
        if stats:
            q = max(1, stats["prefix_query_tokens"])
            rec["detail"]["prefix_hit_rate"] = round(
                stats["prefix_hit_tokens"] / q, 3)
        print(json.dumps(rec), flush=True)

    max_len = args.prefix + args.new + 8
    drive(ServeEngine(cfg, params, max_slots=args.slots, max_len=max_len),
          "dense")
    drive(PagedServeEngine(cfg, params, max_slots=args.slots,
                           max_len=max_len, block_size=16), "paged")

    def stall(chunk, label):
        # Decode-stall probe: short requests are mid-decode when one long
        # prompt arrives; the worst step time while its prefill is in
        # flight IS the stall chunked prefill exists to bound.
        long_len = max(4 * args.prefix, 128)
        eng = ServeEngine(cfg, params, max_slots=args.slots,
                          max_len=long_len + args.new + 8,
                          prefill_chunk=chunk)
        eng.add_request(Request("warm", list(range(1, long_len + 1)),
                                max_new_tokens=2))
        eng.run()                                   # compile all programs
        for i in range(3):
            eng.add_request(Request(f"bg{i}", [7 + i], max_new_tokens=500))
        for _ in range(4):
            eng.step()
        eng.add_request(Request("long", list(range(1, long_len + 1)),
                                max_new_tokens=2))
        worst = 0.0
        while eng.queue or eng._inflight is not None:
            t0 = time.perf_counter()
            eng.step()
            worst = max(worst, time.perf_counter() - t0)
        print(json.dumps({
            "metric": f"serve_decode_stall_ms_{label}",
            "value": round(worst * 1e3, 2), "unit": "ms",
            "detail": {"long_prompt": long_len, "chunk": chunk}}),
            flush=True)

    stall(0, "whole_prefill")
    stall(32, "chunked_prefill")

    def spec(gamma, label):
        # Repetitive continuation workload — the regime prompt-lookup
        # speculation exists for (code/quotes/structured text).  max_len
        # is sized from the ACTUAL prompt length (24 tokens), not
        # --prefix, so small flag values can't silently cancel requests.
        plen = 24
        eng = ServeEngine(cfg, params, max_slots=args.slots,
                          max_len=plen + 2 * args.new + 8,
                          speculative=gamma)
        eng.add_request(Request("warm", [5, 6] * 8, max_new_tokens=4))
        eng.run()
        if gamma:
            # The warm request only hits _verify if a draft happened to
            # match; force-compile the verify program so its first
            # compile can't land in the timed region.
            import jax as _jax
            import jax.numpy as _jnp
            import numpy as _np
            zeros = _np.zeros((args.slots, gamma + 1), _np.int32)
            # Sampling params travel as per-slot [temp, top_p, top_k]
            # rows (engine._samp); greedy warmup = zeros with top_p=1.
            samp = _np.zeros((args.slots, 3), _np.float32)
            samp[:, 1] = 1.0
            _, _, eng.cache = eng._verify(
                eng.params, eng.cache, _jnp.asarray(zeros),
                _jnp.asarray(eng.lens),
                _jnp.zeros(args.slots, _jnp.int32),     # ntok
                _jax.random.PRNGKey(0),
                _jnp.asarray(samp),
                _jnp.zeros(args.slots, _jnp.float32))   # all rows masked
        for i in range(args.requests):
            pat = [10 + i, 11 + i, 12 + i]
            eng.add_request(Request(f"s{i}", pat * 8,
                                    max_new_tokens=2 * args.new))
        t0 = time.perf_counter()
        out = eng.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in out)
        rec = {"metric": f"serve_decode_tokens_per_sec_{label}",
               "value": round(toks / dt, 1), "unit": "tokens/s",
               "detail": {"gamma": gamma, "requests": len(out)}}
        if gamma and eng.spec_stats["drafted"]:
            rec["detail"]["accept_rate"] = round(
                eng.spec_stats["accepted"] / eng.spec_stats["drafted"], 3)
        print(json.dumps(rec), flush=True)

    spec(0, "sequential")
    spec(4, "speculative")


def matrix(args) -> None:
    """The engine matrix (VERDICT r4 task 3): every serving variant on
    one workload, with tokens/s, TTFT p50/p99, and overhead relative to
    the dense baseline.  Off-chip the ABSOLUTE numbers are CPU-bound
    noise; the RELATIVE ratios are the published evidence (e.g. W8A16
    must not regress decode, int8-kv must not regress dense) and the
    same harness records on-chip numbers when a tunnel window opens
    (tools/tpu_capture.py step serve_matrix)."""
    import statistics

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    shared = list(range(1, args.prefix + 1))
    max_len = args.prefix + args.new + 8

    # (label, engine class, kwargs, token hook attached?).  The bare
    # "dense" baseline runs WITHOUT the hook so the "streaming" row
    # (identical config + hook) isolates the hook's true cost; all other
    # rows carry the hook for TTFT measurement, so their vs_dense ratio
    # includes that (measured-tiny) cost uniformly.
    variants = [
        ("dense", ServeEngine, {}, False),
        ("streaming", ServeEngine, {}, True),
        ("dense_int8kv", ServeEngine, {"kv_quant": "int8"}, True),
        ("w8a16", ServeEngine, {"weight_quant": "int8"}, True),
        ("chunked_prefill", ServeEngine, {"prefill_chunk": 32}, True),
        ("speculative", ServeEngine, {"speculative": 4}, True),
        ("paged", PagedServeEngine, {"block_size": 16}, True),
        ("paged_int8kv", PagedServeEngine,
         {"block_size": 16, "kv_quant": "int8"}, True),
    ]

    results = []
    baseline = None
    for label, engine_cls, kwargs, streaming in variants:
        engine = engine_cls(cfg, params, max_slots=args.slots,
                            max_len=max_len, **kwargs)
        submit_t: dict = {}
        first_tok: dict = {}
        consumed = [0]

        def hook(rid, tokens, _s=submit_t, _f=first_tok, _c=consumed):
            _c[0] += len(tokens)
            if rid not in _f and rid in _s:
                _f[rid] = time.perf_counter() - _s[rid]

        if streaming:
            engine.token_callback = hook
        # Warmup compiles every program the timed pass hits.
        for i in range(2):
            engine.add_request(Request(f"warm{i}", shared + [90 + i],
                                       max_new_tokens=2))
            engine.run()
        if kwargs.get("speculative"):
            # The warmup only reaches _verify if a draft happened to
            # match; force-compile it so the first compile cannot land
            # in the timed region (same trick as spec()).
            import jax.numpy as _jnp
            import numpy as _np
            gamma = kwargs["speculative"]
            samp = _np.zeros((args.slots, 3), _np.float32)
            samp[:, 1] = 1.0
            _, _, engine.cache = engine._verify(
                engine.params, engine.cache,
                _jnp.zeros((args.slots, gamma + 1), _jnp.int32),
                _jnp.asarray(engine.lens),
                _jnp.zeros(args.slots, _jnp.int32),
                jax.random.PRNGKey(0), _jnp.asarray(samp),
                _jnp.zeros(args.slots, _jnp.float32))
        # Repeats with a median collapse scheduler noise on a shared
        # CPU box — a single ~0.5 s window swings ratios by ±30%.
        rates = []
        nreq = 0
        for rep in range(args.repeats):
            reqs = [Request(f"r{rep}-{i}", shared + [100 + i],
                            max_new_tokens=args.new)
                    for i in range(args.requests)]
            t0 = time.perf_counter()
            for r in reqs:
                submit_t[r.request_id] = time.perf_counter()
                engine.add_request(r)
            out = engine.run()
            dt = time.perf_counter() - t0
            rates.append(sum(len(r.tokens) for r in out) / dt)
            nreq = len(out)
        ttfts = sorted(first_tok.values())
        rec = {
            "variant": label,
            "tokens_per_sec": round(statistics.median(rates), 1),
            "tokens_per_sec_spread": [round(min(rates), 1),
                                      round(max(rates), 1)],
            "ttft_p50_ms": round(
                statistics.median(ttfts) * 1e3, 2) if ttfts else None,
            "ttft_p99_ms": round(
                percentile(ttfts, 99) * 1e3, 2) if ttfts else None,
            "requests": nreq,
            "repeats": args.repeats,
        }
        if baseline is None:
            baseline = rec["tokens_per_sec"]
        rec["vs_dense"] = round(rec["tokens_per_sec"] / baseline, 3)
        stats = getattr(engine, "stats", None)
        if callable(stats):
            stats = stats()
        if stats and stats.get("prefix_query_tokens"):
            rec["prefix_hit_rate"] = round(
                stats["prefix_hit_tokens"]
                / max(1, stats["prefix_query_tokens"]), 3)
        if kwargs.get("speculative") and engine.spec_stats["drafted"]:
            rec["accept_rate"] = round(
                engine.spec_stats["accepted"]
                / engine.spec_stats["drafted"], 3)
        if streaming:
            rec["tokens_streamed"] = consumed[0]
        else:
            rec.pop("ttft_p50_ms"), rec.pop("ttft_p99_ms")
        results.append(rec)
        print(json.dumps(rec), flush=True)

    doc = {
        "workload": {"model": args.model, "requests": args.requests,
                     "prefix_len": args.prefix, "new_tokens": args.new,
                     "slots": args.slots},
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "results": results,
    }
    if args.json_out:
        pathlib.Path(args.json_out).parent.mkdir(parents=True,
                                                 exist_ok=True)
        pathlib.Path(args.json_out).write_text(
            json.dumps(doc, indent=2) + "\n")
        print(f"wrote {args.json_out}", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="serve-bench")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (off-chip smoke)")
    ap.add_argument("--model", default="llama_tiny")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--prefix", type=int, default=64,
                    help="shared prompt-prefix length (tokens)")
    ap.add_argument("--new", type=int, default=32,
                    help="decode tokens per request")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--matrix", action="store_true",
                    help="run the full engine matrix with TTFT "
                         "percentiles and relative overheads")
    ap.add_argument("--json-out", default="",
                    help="write matrix results to this JSON file")
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed rounds per variant; median is published")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from kuberay_tpu.utils.platform import pin_platform_from_env
        pin_platform_from_env()
    if args.matrix:
        matrix(args)
    else:
        run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
