"""Serving throughput bench: dense vs paged KV engines.

Prints one JSON line per engine with decode tokens/s and (paged) prefix
cache hit rate, over a workload of concurrent requests sharing a system
prompt — the shape paged attention + prefix caching exist for.  The
train-side counterpart of the driver's bench.py; run with --cpu off-chip.

Usage: python benchmark/serve_bench.py [--cpu] [--model llama_tiny]
       [--requests 16] [--prefix 64] [--new 32] [--slots 8]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time
import zlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Interpolated percentile — the shared inclusive-method estimator
# (kuberay_tpu/utils/quantiles.py).  A truncating index collapses
# small-sample p99 toward p90; tests/test_bench_quantile.py pins the
# interpolated behavior.
from kuberay_tpu.utils.quantiles import percentile  # noqa: E402


def run(args) -> None:
    import jax
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    shared = list(range(1, args.prefix + 1))

    def requests():
        return [Request(f"r{i}", shared + [100 + i],
                        max_new_tokens=args.new)
                for i in range(args.requests)]

    def drive(engine, label):
        # Warmup: compile every program the timed pass will hit (full
        # prefill bucket, cached-suffix bucket on the paged path, decode)
        # — otherwise compile seconds dwarf decode ms and invert the
        # comparison.  The timed pass therefore measures warm-cache
        # steady state for the paged engine (its serving regime).
        for i in range(2):
            engine.add_request(Request(f"warm{i}", shared + [90 + i],
                                       max_new_tokens=2))
            engine.run()
        for r in requests():
            engine.add_request(r)
        t0 = time.perf_counter()
        out = engine.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in out)
        rec = {
            "metric": f"serve_decode_tokens_per_sec_{label}",
            "value": round(toks / dt, 1),
            "unit": "tokens/s",
            "detail": {"model": args.model, "requests": len(out),
                       "prefix_len": args.prefix, "new_tokens": args.new,
                       "slots": args.slots, "wall_s": round(dt, 2)},
        }
        stats = getattr(engine, "stats", None)
        if stats:
            q = max(1, stats["prefix_query_tokens"])
            rec["detail"]["prefix_hit_rate"] = round(
                stats["prefix_hit_tokens"] / q, 3)
        print(json.dumps(rec), flush=True)

    max_len = args.prefix + args.new + 8
    drive(ServeEngine(cfg, params, max_slots=args.slots, max_len=max_len),
          "dense")
    drive(PagedServeEngine(cfg, params, max_slots=args.slots,
                           max_len=max_len, block_size=16), "paged")

    def stall(chunk, label):
        # Decode-stall probe: short requests are mid-decode when one long
        # prompt arrives; the worst step time while its prefill is in
        # flight IS the stall chunked prefill exists to bound.
        long_len = max(4 * args.prefix, 128)
        eng = ServeEngine(cfg, params, max_slots=args.slots,
                          max_len=long_len + args.new + 8,
                          prefill_chunk=chunk)
        eng.add_request(Request("warm", list(range(1, long_len + 1)),
                                max_new_tokens=2))
        eng.run()                                   # compile all programs
        for i in range(3):
            eng.add_request(Request(f"bg{i}", [7 + i], max_new_tokens=500))
        for _ in range(4):
            eng.step()
        eng.add_request(Request("long", list(range(1, long_len + 1)),
                                max_new_tokens=2))
        worst = 0.0
        while eng.queue or eng._inflight is not None:
            t0 = time.perf_counter()
            eng.step()
            worst = max(worst, time.perf_counter() - t0)
        print(json.dumps({
            "metric": f"serve_decode_stall_ms_{label}",
            "value": round(worst * 1e3, 2), "unit": "ms",
            "detail": {"long_prompt": long_len, "chunk": chunk}}),
            flush=True)

    stall(0, "whole_prefill")
    stall(32, "chunked_prefill")

    def spec(gamma, label):
        # Repetitive continuation workload — the regime prompt-lookup
        # speculation exists for (code/quotes/structured text).  max_len
        # is sized from the ACTUAL prompt length (24 tokens), not
        # --prefix, so small flag values can't silently cancel requests.
        plen = 24
        eng = ServeEngine(cfg, params, max_slots=args.slots,
                          max_len=plen + 2 * args.new + 8,
                          speculative=gamma)
        eng.add_request(Request("warm", [5, 6] * 8, max_new_tokens=4))
        eng.run()
        if gamma:
            # The warm request only hits _verify if a draft happened to
            # match; force-compile the verify program so its first
            # compile can't land in the timed region.
            import jax as _jax
            import jax.numpy as _jnp
            import numpy as _np
            zeros = _np.zeros((args.slots, gamma + 1), _np.int32)
            # Sampling params travel as per-slot [temp, top_p, top_k]
            # rows (engine._samp); greedy warmup = zeros with top_p=1.
            samp = _np.zeros((args.slots, 3), _np.float32)
            samp[:, 1] = 1.0
            _, _, eng.cache = eng._verify(
                eng.params, eng.cache, _jnp.asarray(zeros),
                _jnp.asarray(eng.lens),
                _jnp.zeros(args.slots, _jnp.int32),     # ntok
                _jax.random.PRNGKey(0),
                _jnp.asarray(samp),
                _jnp.zeros(args.slots, _jnp.float32))   # all rows masked
        for i in range(args.requests):
            pat = [10 + i, 11 + i, 12 + i]
            eng.add_request(Request(f"s{i}", pat * 8,
                                    max_new_tokens=2 * args.new))
        t0 = time.perf_counter()
        out = eng.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in out)
        rec = {"metric": f"serve_decode_tokens_per_sec_{label}",
               "value": round(toks / dt, 1), "unit": "tokens/s",
               "detail": {"gamma": gamma, "requests": len(out)}}
        if gamma and eng.spec_stats["drafted"]:
            rec["detail"]["accept_rate"] = round(
                eng.spec_stats["accepted"] / eng.spec_stats["drafted"], 3)
        print(json.dumps(rec), flush=True)

    spec(0, "sequential")
    spec(4, "speculative")


def matrix(args) -> None:
    """The engine matrix (VERDICT r4 task 3): every serving variant on
    one workload, with tokens/s, TTFT p50/p99, and overhead relative to
    the dense baseline.  Off-chip the ABSOLUTE numbers are CPU-bound
    noise; the RELATIVE ratios are the published evidence (e.g. W8A16
    must not regress decode, int8-kv must not regress dense) and the
    same harness records on-chip numbers when a tunnel window opens
    (tools/tpu_capture.py step serve_matrix)."""
    import statistics

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    shared = list(range(1, args.prefix + 1))
    max_len = args.prefix + args.new + 8

    # (label, engine class, kwargs, token hook attached?).  The bare
    # "dense" baseline runs WITHOUT the hook so the "streaming" row
    # (identical config + hook) isolates the hook's true cost; all other
    # rows carry the hook for TTFT measurement, so their vs_dense ratio
    # includes that (measured-tiny) cost uniformly.
    variants = [
        ("dense", ServeEngine, {}, False),
        ("streaming", ServeEngine, {}, True),
        ("dense_int8kv", ServeEngine, {"kv_quant": "int8"}, True),
        ("w8a16", ServeEngine, {"weight_quant": "int8"}, True),
        ("chunked_prefill", ServeEngine, {"prefill_chunk": 32}, True),
        ("speculative", ServeEngine, {"speculative": 4}, True),
        ("paged", PagedServeEngine, {"block_size": 16}, True),
        ("paged_int8kv", PagedServeEngine,
         {"block_size": 16, "kv_quant": "int8"}, True),
    ]

    results = []
    baseline = None
    for label, engine_cls, kwargs, streaming in variants:
        engine = engine_cls(cfg, params, max_slots=args.slots,
                            max_len=max_len, **kwargs)
        submit_t: dict = {}
        first_tok: dict = {}
        consumed = [0]

        def hook(rid, tokens, _s=submit_t, _f=first_tok, _c=consumed):
            _c[0] += len(tokens)
            if rid not in _f and rid in _s:
                _f[rid] = time.perf_counter() - _s[rid]

        if streaming:
            engine.token_callback = hook
        # Warmup compiles every program the timed pass hits.
        for i in range(2):
            engine.add_request(Request(f"warm{i}", shared + [90 + i],
                                       max_new_tokens=2))
            engine.run()
        if kwargs.get("speculative"):
            # The warmup only reaches _verify if a draft happened to
            # match; force-compile it so the first compile cannot land
            # in the timed region (same trick as spec()).
            import jax.numpy as _jnp
            import numpy as _np
            gamma = kwargs["speculative"]
            samp = _np.zeros((args.slots, 3), _np.float32)
            samp[:, 1] = 1.0
            _, _, engine.cache = engine._verify(
                engine.params, engine.cache,
                _jnp.zeros((args.slots, gamma + 1), _jnp.int32),
                _jnp.asarray(engine.lens),
                _jnp.zeros(args.slots, _jnp.int32),
                jax.random.PRNGKey(0), _jnp.asarray(samp),
                _jnp.zeros(args.slots, _jnp.float32))
        # Repeats with a median collapse scheduler noise on a shared
        # CPU box — a single ~0.5 s window swings ratios by ±30%.
        rates = []
        nreq = 0
        for rep in range(args.repeats):
            reqs = [Request(f"r{rep}-{i}", shared + [100 + i],
                            max_new_tokens=args.new)
                    for i in range(args.requests)]
            t0 = time.perf_counter()
            for r in reqs:
                submit_t[r.request_id] = time.perf_counter()
                engine.add_request(r)
            out = engine.run()
            dt = time.perf_counter() - t0
            rates.append(sum(len(r.tokens) for r in out) / dt)
            nreq = len(out)
        ttfts = sorted(first_tok.values())
        rec = {
            "variant": label,
            "tokens_per_sec": round(statistics.median(rates), 1),
            "tokens_per_sec_spread": [round(min(rates), 1),
                                      round(max(rates), 1)],
            "ttft_p50_ms": round(
                statistics.median(ttfts) * 1e3, 2) if ttfts else None,
            "ttft_p99_ms": round(
                percentile(ttfts, 99) * 1e3, 2) if ttfts else None,
            "requests": nreq,
            "repeats": args.repeats,
        }
        if baseline is None:
            baseline = rec["tokens_per_sec"]
        rec["vs_dense"] = round(rec["tokens_per_sec"] / baseline, 3)
        stats = getattr(engine, "stats", None)
        if callable(stats):
            stats = stats()
        if stats and stats.get("prefix_query_tokens"):
            rec["prefix_hit_rate"] = round(
                stats["prefix_hit_tokens"]
                / max(1, stats["prefix_query_tokens"]), 3)
        if kwargs.get("speculative") and engine.spec_stats["drafted"]:
            rec["accept_rate"] = round(
                engine.spec_stats["accepted"]
                / engine.spec_stats["drafted"], 3)
        if streaming:
            rec["tokens_streamed"] = consumed[0]
        else:
            rec.pop("ttft_p50_ms"), rec.pop("ttft_p99_ms")
        results.append(rec)
        print(json.dumps(rec), flush=True)

    doc = {
        "workload": {"model": args.model, "requests": args.requests,
                     "prefix_len": args.prefix, "new_tokens": args.new,
                     "slots": args.slots},
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "results": results,
    }
    if args.json_out:
        pathlib.Path(args.json_out).parent.mkdir(parents=True,
                                                 exist_ok=True)
        pathlib.Path(args.json_out).write_text(
            json.dumps(doc, indent=2) + "\n")
        print(f"wrote {args.json_out}", flush=True)


# ---------------------------------------------------------------------------
# Seeded open-loop traffic generator: the fleet-serving gate (PR 7)
# ---------------------------------------------------------------------------

TRAFFIC_SCHEMA = "tpu-bench-serve/v1"
# Per-leg keys the smoke gate (tools/bench_serve.sh) asserts on.
TRAFFIC_LEG_KEYS = (
    "workload", "seed", "replicas", "affinity", "shedding", "requests",
    "completed", "shed", "errors", "tokens_per_sec", "ttft_p50_ms",
    "ttft_p99_ms", "prefix_hit_rate", "gateway_prefix_picks",
)


class _Fleet:
    """N paged serve replicas behind a WeightedGateway, all in-process.

    Open-loop harness detail: the generator never waits for responses to
    send the next request (arrival times are a seeded schedule), so
    overload genuinely queues/sheds instead of self-throttling — the
    regime closed-loop drivers can't reach.
    """

    def __init__(self, cfg, params, replicas, *, slots, max_len,
                 num_blocks, block_size, seed, affinity, shedding,
                 max_queue=512, tiers=None, kv_max_blocks=0,
                 prefill_beta=None, host_blocks=0):
        import random as _random

        from kuberay_tpu.controlplane.store import ObjectStore
        from kuberay_tpu.serve.gateway import GatewayConfig, WeightedGateway
        from kuberay_tpu.serve.paged_engine import PagedServeEngine
        from kuberay_tpu.serve.server import ServeFrontend
        from kuberay_tpu.utils.metrics import MetricsRegistry

        self.frontends = []
        self.servers = []
        urls = {}
        for i in range(replicas):
            eng = PagedServeEngine(cfg, params, max_slots=slots,
                                   max_len=max_len, num_blocks=num_blocks,
                                   block_size=block_size,
                                   host_blocks=host_blocks)
            fe = ServeFrontend(eng, max_queue=max_queue)
            srv, url = fe.serve_background()
            self.frontends.append(fe)
            self.servers.append(srv)
            urls[f"replica-{i}"] = url
        backends = []
        for i, s in enumerate(urls):
            b = {"service": s, "weight": 1}
            # tiers: one role per replica ("prefill"/"decode") turns the
            # gateway into the two-hop scheduler; None = colocated.
            if tiers is not None:
                b["tier"] = tiers[i]
            backends.append(b)
        store = ObjectStore()
        store.create({
            "apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
            "metadata": {"name": "bench", "namespace": "default"},
            "spec": {"backends": backends},
            "status": {},
        })
        gw_cfg = GatewayConfig(
            affinity=affinity,
            # The on-leg isolates scored routing (ε exploration would
            # re-spray ~5% of hot traffic — its distribution properties
            # are unit-tested, not re-measured here); the off-leg IS the
            # weighted-random baseline.
            epsilon=0.0 if affinity else 1.0,
            block_size=block_size,
            # Shedding on: admit at most the fleet's concurrent service
            # capacity per replica and bound the hold queue; off: admit
            # everything (backend queues absorb the burst and TTFT pays).
            max_inflight=(2 * slots) if shedding else 0,
            max_queue=16 if shedding else 4096,
            queue_timeout=2.0 if shedding else 600.0,
            # Disagg legs budget the KV handoff: past a few blocks the
            # base64/JSON serialization costs the gateway CPU more than
            # hop 2 recomputing the tail from the shipped prefix.
            kv_max_blocks=kv_max_blocks,
            # Prefill hop spreads bursts across the tier instead of
            # convoying on the preamble's home replica (the tier's
            # caches hold the same hot preambles within seconds).
            prefill_beta=prefill_beta)
        self.metrics = MetricsRegistry()
        self.gateway = WeightedGateway(
            store, "bench", resolver=lambda s: urls[s],
            poll_interval=30.0, metrics=self.metrics, config=gw_cfg,
            rng=_random.Random(seed))

    def warm(self, prompts):
        """Compile every program the timed pass hits, once per replica,
        by routing a warmup prompt straight at each frontend."""
        for fe in self.frontends:
            for p in prompts:
                fe.submit(p, max_tokens=2, timeout=600.0)

    def prefix_stats(self):
        hits = queries = 0
        for fe in self.frontends:
            st = fe.engine.stats
            hits += st["prefix_hit_tokens"]
            queries += st["prefix_query_tokens"]
        return hits, queries

    def reset_counters(self):
        for fe in self.frontends:
            a = fe.engine.allocator
            a.prefix_hits = 0
            a.prefix_queries = 0

    def set_tracer(self, tracer):
        """Swap every tracing seam (gateway + each replica engine) on the
        already-compiled fleet, so tracing-on/off legs share XLA programs
        and the measured delta is the tracer alone."""
        self.gateway.tracer = tracer
        for fe in self.frontends:
            fe.engine._tracer = tracer

    def close(self):
        self.gateway.stop()
        for srv in self.servers:
            srv.shutdown()
        for fe in self.frontends:
            fe.close()


def _hot_prompts(prefix_len, hot_prefixes):
    return [[1000 + 97 * h + j for j in range(prefix_len)]
            for h in range(hot_prefixes)]


def _gen_arrivals(rng, workload, duration_s, base_rate, prefix_len,
                  block_size, hot_prefixes, hot_fraction,
                  cold_len=64, lengths=None, length_probs=None):
    """Seeded open-loop schedule: [(t_offset, prompt_tokens)].  Rates:
    diurnal = sinusoidal ramp peaking mid-run at 2x base; burst = base
    with a 4x storm in the middle third; hot-prefix = flat base with
    ``hot_fraction`` of prompts drawn from ``hot_prefixes`` shared
    prefixes (the prefix-skew regime affinity routing exists for) and
    SHORT unique cold prompts (``cold_len``) in between — chat turns
    against long system preambles, not a second long-prefill class that
    would bury the hit/miss contrast in the tail; long-prompt = flat
    base with every prompt = shared hot preamble + unique filler to a
    length drawn from the heavy-tailed DISCRETE mixture ``lengths`` /
    ``length_probs`` (discrete so the prefill compile buckets stay
    bounded and warmable — a continuous tail would put an XLA compile
    inside the timed window of whichever leg saw that length first)."""
    import math

    hots = _hot_prompts(prefix_len, hot_prefixes)
    arrivals = []
    t = 0.0
    n = 0
    while t < duration_s:
        if workload == "diurnal":
            rate = base_rate * (1.0 + math.sin(math.pi * t / duration_s))
        elif workload == "burst":
            mid = duration_s / 3 <= t < 2 * duration_s / 3
            rate = base_rate * (4.0 if mid else 1.0)
        else:                                      # hot-prefix
            rate = base_rate
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        n += 1
        if workload == "long-prompt":
            r = rng.random()
            acc, length = 0.0, lengths[-1]
            for cand, p in zip(lengths, length_probs):
                acc += p
                if r < acc:
                    length = cand
                    break
            prompt = list(hots[rng.randrange(hot_prefixes)])
            prompt += [50_000 + (n * 331 + j) % 30_000
                       for j in range(length - prefix_len)]
        elif workload == "hot-prefix" and rng.random() < hot_fraction:
            prompt = list(rng.choice(hots))
        else:
            length = cold_len if workload == "hot-prefix" else prefix_len
            # Cold prompt: unique head so no block-aligned prefix ever
            # repeats (rng-free of the hot pool).
            prompt = [50_000 + (n * block_size + j) % 30_000
                      for j in range(length)]
        prompt = prompt + [40_000 + n % 9000]      # unique tail token
        arrivals.append((t, prompt))
    return arrivals


def _drive_open_loop(gateway_url, arrivals, max_new, timeout=120.0):
    """Replay the schedule against the gateway over real HTTP; returns
    per-request records."""
    import concurrent.futures
    import urllib.error
    import urllib.request

    records = []
    lock = __import__("threading").Lock()

    def fire(prompt):
        body = json.dumps({"prompt_tokens": prompt,
                           "max_tokens": max_new}).encode()
        req = urllib.request.Request(
            f"{gateway_url}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                doc = json.load(resp)
                rec = {"code": resp.status,
                       "latency_s": time.perf_counter() - t0,
                       "ttft_ms": doc.get("ttft_ms"),
                       "tokens": len(doc.get("tokens", []))}
        except urllib.error.HTTPError as e:
            e.read()
            rec = {"code": e.code,
                   "latency_s": time.perf_counter() - t0,
                   "ttft_ms": None, "tokens": 0}
        except Exception:
            rec = {"code": -1, "latency_s": time.perf_counter() - t0,
                   "ttft_ms": None, "tokens": 0}
        with lock:
            records.append(rec)

    start = time.perf_counter()
    # Enough client threads that the pool NEVER back-pressures the
    # schedule — an open-loop generator that waits for free workers is
    # secretly closed-loop exactly when overload makes it matter.
    with concurrent.futures.ThreadPoolExecutor(max_workers=256) as pool:
        for t_off, prompt in arrivals:
            delay = start + t_off - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            pool.submit(fire, prompt)
    wall = time.perf_counter() - start
    return records, wall


def _gateway_hits(fleet):
    return sum(
        float(line.rsplit(" ", 1)[1])
        for line in fleet.metrics.render().splitlines()
        if line.startswith("tpu_gateway_prefix_cache_hits_total{"))


def _kv_transfer_counts(fleet):
    """(sent, skipped) KV blocks from the gateway's transfer counter —
    skipped > 0 is the delta-only evidence the r12 artifact publishes."""
    sent = skipped = 0.0
    for line in fleet.metrics.render().splitlines():
        if line.startswith("tpu_serve_kv_transfer_blocks_total{"):
            val = float(line.rsplit(" ", 1)[1])
            if 'outcome="sent"' in line:
                sent += val
            elif 'outcome="skipped"' in line:
                skipped += val
    return sent, skipped


def _leg_summary(workload, seed, replicas, affinity, shedding, records,
                 wall, fleet, gw_hits_base=0.0):
    completed = [r for r in records if r["code"] == 200]
    shed = sum(1 for r in records if r["code"] == 429)
    errors = sum(1 for r in records if r["code"] not in (200, 429))
    ttfts = sorted(r["ttft_ms"] for r in completed
                   if r["ttft_ms"] is not None)
    lats = sorted(r["latency_s"] for r in completed)
    hits, queries = fleet.prefix_stats()
    gw_hits = _gateway_hits(fleet) - gw_hits_base
    return {
        "workload": workload, "seed": seed, "replicas": replicas,
        "affinity": affinity, "shedding": shedding,
        "requests": len(records), "completed": len(completed),
        "shed": shed, "errors": errors,
        "tokens_per_sec": round(
            sum(r["tokens"] for r in completed) / wall, 1),
        "ttft_p50_ms": round(percentile(ttfts, 50), 2) if ttfts else None,
        "ttft_p99_ms": round(percentile(ttfts, 99), 2) if ttfts else None,
        "latency_p99_ms": round(
            percentile(lats, 99) * 1e3, 2) if lats else None,
        "prefix_hit_rate": round(hits / queries, 3) if queries else 0.0,
        "gateway_prefix_picks": int(gw_hits),
        "wall_s": round(wall, 2),
    }


# Per-workload regimes (CPU-calibrated on llama_tiny; the RELATIVE
# contrasts are the published evidence, the same harness records on-chip
# numbers through a tunnel window):
# - hot-prefix: long shared prefixes so a cache miss pays a real prefill,
#   pool sized so ONE replica cannot hold every hot prefix on top of
#   live traffic — spraying (affinity off) thrashes, partitioning
#   (affinity on) fits;
# - burst: a 4x arrival storm over the middle third against a fleet
#   provisioned for the base rate — the load-shedding regime;
# - diurnal: a sinusoidal ramp peaking at 2x base, run at 1 and 2
#   replicas — TTFT p99 vs replica count for the SLO autoscaler story;
# - long-prompt: heavy-tailed prompt lengths (discrete mixture; shared
#   hot preamble + unique filler) with SHORT decodes — the prefill-bound
#   regime disaggregation exists for, run colocated (4 mixed) vs disagg
#   (2 prefill + 2 decode) at equal total replica count.
TRAFFIC_PROFILES = {
    "hot-prefix": dict(prefix=496, new=8, slots=4, rate=5.0),
    "burst": dict(prefix=48, new=32, slots=2, rate=18.0),
    "diurnal": dict(prefix=48, new=32, slots=2, rate=12.0),
    # kv_max_blocks budgets the disagg KV handoff (blocks per request);
    # see GatewayConfig.kv_max_blocks.
    "long-prompt": dict(prefix=128, new=16, slots=4, rate=8.0,
                        lengths=[160, 256, 416],
                        length_probs=[0.55, 0.3, 0.15],
                        kv_max_blocks=2, cache_prefixes=1,
                        prefill_beta=8.0),
}

HOT_PREFIXES = 8
HOT_FRACTION = 0.85


def traffic(args) -> None:
    """Seeded open-loop traffic gate: hot-prefix skew (affinity on/off),
    burst storm (shedding on/off), diurnal ramp (1 vs 2 replicas).  One
    JSON line per leg; ``--json-out`` writes the tpu-bench-serve/v1
    artifact (benchmark/results/serve_r07.json)."""
    import random as _random

    import jax
    from kuberay_tpu.models import llama

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    bs = 16

    # (workload, replicas, affinity, shedding, tiers) — tiers=None is a
    # colocated fleet; a role list turns on two-hop disaggregation.
    workloads = []
    if args.traffic in ("hot-prefix", "all"):
        workloads += [("hot-prefix", 2, True, False, None),
                      ("hot-prefix", 2, False, False, None)]
    if args.traffic in ("burst", "all"):
        workloads += [("burst", 2, True, True, None),
                      ("burst", 2, True, False, None)]
    if args.traffic in ("diurnal", "all"):
        workloads += [("diurnal", 1, True, True, None),
                      ("diurnal", 2, True, True, None)]
    if args.traffic == "long-prompt":
        # Deliberately NOT in "all": the colocated-vs-disagg comparison
        # is its own gate (tools/bench_serve.sh --disagg leg) and the
        # "all" artifact's legs stay byte-stable.
        workloads += [
            # 3 replicas vs the colocated 4: the per-replica throughput
            # column is the point — tier separation serves the same
            # offered load with less hardware (prefill interference off
            # the decode replica, preamble cache concentrated on fewer
            # pools), and the prefill-only replicas keep the TTFT tail
            # free of resident-decode interference.
            ("long-prompt", 4, True, False, None),
            ("long-prompt", 3, True, False,
             ["prefill", "prefill", "decode"]),
        ]

    legs = []
    for seed in args.seeds:
        for workload, replicas, affinity, shedding, tiers in workloads:
            prof = TRAFFIC_PROFILES[workload]
            prefix_len = prof["prefix"]
            new_tokens = prof["new"]
            slots = prof["slots"]
            rate = prof["rate"] * args.rate_scale
            lengths = prof.get("lengths")
            longest = max(lengths) if lengths else prefix_len
            max_len = longest + new_tokens + 16
            blocks_per_prompt = (max_len + bs - 1) // bs
            # cache_prefixes: how many hot preambles the pool budget
            # leaves room for beyond the active slots.  long-prompt
            # runs it tight — cache pressure is where colocated decode
            # pins (unevictable mid-decode blocks) squeeze the prefix
            # cache while a prefill tier's transients free immediately.
            num_blocks = slots * blocks_per_prompt + \
                prof.get("cache_prefixes", HOT_PREFIXES // 2 + 1) * \
                (prefix_len // bs)
            fleet = _Fleet(cfg, params, replicas, slots=slots,
                           max_len=max_len, num_blocks=num_blocks,
                           block_size=bs, seed=seed, affinity=affinity,
                           shedding=shedding, tiers=tiers,
                           kv_max_blocks=(prof.get("kv_max_blocks", 0)
                                          if tiers else 0),
                           prefill_beta=(prof.get("prefill_beta")
                                         if tiers else None))
            tracer = None
            try:
                # Warm every compiled shape OUTSIDE the timed window:
                # full prefill bucket, cold-prompt bucket, cached-suffix
                # bucket, decode.
                warm = [11_111 + j for j in range(prefix_len)]
                cold_warm = [12_345 + j for j in range(64)]
                warm_prompts = [warm + [7], warm + [8], cold_warm + [9]]
                if lengths:
                    warm_prompts += [[13_000 + j for j in range(ln)] + [7]
                                     for ln in lengths]
                fleet.warm(warm_prompts)
                gw_srv, gw_url = fleet.gateway.serve_background_http()
                try:
                    if workload == "hot-prefix":
                        # Steady-state measurement: drive every hot
                        # prefix through the GATEWAY twice so routing
                        # homes are learned and replica caches warm the
                        # same way live traffic warms them (first-touch
                        # compulsory misses are cold-start, not routing,
                        # and 8 of them would own a 150-request p99).
                        hots = _hot_prompts(prefix_len, HOT_PREFIXES)
                        hot_warm = [(0.25 * i, list(p) + [31337])
                                    for i, p in enumerate(hots * 2)]
                        _drive_open_loop(gw_url, hot_warm, new_tokens)
                    if workload == "long-prompt":
                        # Gateway-level warm pass: compiles the cached-
                        # suffix buckets both legs hit (two-hop decode
                        # re-prefill on the disagg leg, preamble hits on
                        # the colocated one) and teaches routing homes —
                        # an alternate-seed schedule so it never leaks
                        # the measured arrivals.
                        wrng = _random.Random(
                            (seed << 8) ^ 0xD15A ^
                            (zlib.crc32(workload.encode()) & 0xFFFF))
                        warm_arr = _gen_arrivals(
                            wrng, workload, min(5.0, args.duration), rate,
                            prefix_len, bs, HOT_PREFIXES,
                            hot_fraction=HOT_FRACTION, lengths=lengths,
                            length_probs=prof["length_probs"])
                        _drive_open_loop(gw_url, warm_arr, new_tokens)
                    fleet.reset_counters()
                    gw_hits_base = _gateway_hits(fleet)
                    kv_base = _kv_transfer_counts(fleet)
                    if workload == "long-prompt":
                        # Both legs pay the tracer uniformly; the disagg
                        # leg's kv-transfer span count is the smoke
                        # gate's trace evidence.
                        from kuberay_tpu.obs.trace import Tracer
                        tracer = Tracer(max_spans=65536)
                        fleet.set_tracer(tracer)
                    # zlib.crc32, not hash(): str hashing is salted per
                    # process and would unseed the schedule.
                    rng = _random.Random(
                        (seed << 8)
                        ^ (zlib.crc32(workload.encode()) & 0xFFFF))
                    arrivals = _gen_arrivals(
                        rng, workload, args.duration, rate, prefix_len,
                        bs, HOT_PREFIXES, hot_fraction=HOT_FRACTION,
                        lengths=lengths,
                        length_probs=prof.get("length_probs"))
                    records, wall = _drive_open_loop(gw_url, arrivals,
                                                     new_tokens)
                finally:
                    gw_srv.shutdown()
                leg = _leg_summary(workload, seed, replicas, affinity,
                                   shedding, records, wall, fleet,
                                   gw_hits_base=gw_hits_base)
                if workload == "long-prompt":
                    leg["mode"] = "disagg" if tiers else "colocated"
                    leg["tokens_per_sec_per_replica"] = round(
                        leg["tokens_per_sec"] / replicas, 2)
                    sent, skipped = _kv_transfer_counts(fleet)
                    leg["kv_sent_blocks"] = int(sent - kv_base[0])
                    leg["kv_skipped_blocks"] = int(skipped - kv_base[1])
                    if tracer is not None:
                        leg["kv_transfer_spans"] = sum(
                            1 for s in tracer.store.export()
                            if s["name"] == "kv-transfer")
                legs.append(leg)
                print(json.dumps(leg), flush=True)
            finally:
                fleet.close()

    doc = {
        "schema": TRAFFIC_SCHEMA,
        "workload_params": {
            "model": args.model, "duration_s": args.duration,
            "rate_scale": args.rate_scale, "block_size": bs,
            "hot_prefixes": HOT_PREFIXES, "hot_fraction": HOT_FRACTION,
            "profiles": TRAFFIC_PROFILES,
        },
        "seeds": list(args.seeds),
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "legs": legs,
    }
    if args.json_out:
        pathlib.Path(args.json_out).parent.mkdir(parents=True,
                                                 exist_ok=True)
        pathlib.Path(args.json_out).write_text(
            json.dumps(doc, indent=2) + "\n")
        print(f"wrote {args.json_out}", flush=True)


def trace_overhead(args) -> None:
    """--trace: the tracing-overhead gate.  One hot-prefix fleet, two
    legs over the IDENTICAL seeded arrival schedule — tracing off, then
    on (gateway spans + traceparent propagation + engine child spans +
    exemplars) — on the same compiled engines, so the delta is the
    tracer's cost and nothing else.  tools/bench_serve.sh asserts the
    throughput overhead stays under its budget (default 5%)."""
    import random as _random

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.obs.trace import NOOP_TRACER, Tracer

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    bs = 16
    prof = TRAFFIC_PROFILES["hot-prefix"]
    prefix_len, new_tokens = prof["prefix"], prof["new"]
    slots = prof["slots"]
    rate = prof["rate"] * args.rate_scale
    max_len = prefix_len + new_tokens + 16
    blocks_per_prompt = (max_len + bs - 1) // bs
    num_blocks = slots * blocks_per_prompt + \
        (HOT_PREFIXES // 2 + 1) * (prefix_len // bs)
    seed = args.seeds[0]
    replicas = 2

    fleet = _Fleet(cfg, params, replicas, slots=slots, max_len=max_len,
                   num_blocks=num_blocks, block_size=bs, seed=seed,
                   affinity=True, shedding=False)
    legs = []
    spans_recorded = 0
    try:
        warm = [11_111 + j for j in range(prefix_len)]
        cold_warm = [12_345 + j for j in range(64)]
        fleet.warm([warm + [7], warm + [8], cold_warm + [9]])
        gw_srv, gw_url = fleet.gateway.serve_background_http()
        try:
            hots = _hot_prompts(prefix_len, HOT_PREFIXES)
            hot_warm = [(0.25 * i, list(p) + [31337])
                        for i, p in enumerate(hots * 2)]
            _drive_open_loop(gw_url, hot_warm, new_tokens)
            # Off leg FIRST: it inherits the warmed caches exactly like
            # the on leg does, and any residual drift (cache aging)
            # biases AGAINST tracing — an overhead gate that passes
            # under that bias is conservative.
            for tracing in (False, True):
                tracer = Tracer(max_spans=65536) if tracing \
                    else NOOP_TRACER
                fleet.set_tracer(tracer)
                fleet.reset_counters()
                gw_hits_base = _gateway_hits(fleet)
                rng = _random.Random(
                    (seed << 8) ^ (zlib.crc32(b"hot-prefix") & 0xFFFF))
                arrivals = _gen_arrivals(
                    rng, "hot-prefix", args.duration, rate, prefix_len,
                    bs, HOT_PREFIXES, hot_fraction=HOT_FRACTION)
                records, wall = _drive_open_loop(gw_url, arrivals,
                                                 new_tokens)
                leg = _leg_summary("hot-prefix", seed, replicas, True,
                                   False, records, wall, fleet,
                                   gw_hits_base=gw_hits_base)
                leg["tracing"] = tracing
                if tracing:
                    spans_recorded = len(tracer.store)
                    leg["spans_recorded"] = spans_recorded
                legs.append(leg)
                print(json.dumps(leg), flush=True)
        finally:
            gw_srv.shutdown()
    finally:
        fleet.close()

    off, on = legs
    tps_off, tps_on = off["tokens_per_sec"], on["tokens_per_sec"]
    overhead = {
        "tokens_per_sec_off": tps_off,
        "tokens_per_sec_on": tps_on,
        "overhead_pct": round((tps_off - tps_on) / tps_off * 100.0, 2)
        if tps_off else 0.0,
        "ttft_p99_off_ms": off["ttft_p99_ms"],
        "ttft_p99_on_ms": on["ttft_p99_ms"],
        "ttft_p99_delta_ms": round(on["ttft_p99_ms"] - off["ttft_p99_ms"],
                                   2)
        if off["ttft_p99_ms"] is not None and on["ttft_p99_ms"] is not None
        else None,
        "spans_recorded": spans_recorded,
    }
    print(json.dumps({"trace_overhead": overhead}), flush=True)

    doc = {
        "schema": TRAFFIC_SCHEMA,
        "workload_params": {
            "model": args.model, "duration_s": args.duration,
            "rate_scale": args.rate_scale, "block_size": bs,
            "hot_prefixes": HOT_PREFIXES, "hot_fraction": HOT_FRACTION,
            "profiles": {"hot-prefix": TRAFFIC_PROFILES["hot-prefix"]},
        },
        "seeds": [seed],
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "legs": legs,
        "trace_overhead": overhead,
    }
    if args.json_out:
        pathlib.Path(args.json_out).parent.mkdir(parents=True,
                                                 exist_ok=True)
        pathlib.Path(args.json_out).write_text(
            json.dumps(doc, indent=2) + "\n")
        print(f"wrote {args.json_out}", flush=True)


# ---------------------------------------------------------------------------
# Critical-path profile gate: per-span-kind decomposition + self-diff (PR 18)
# ---------------------------------------------------------------------------

PROFILE_BENCH_SCHEMA = "tpu-bench-profile/v1"
# Per-leg keys the smoke gate (tools/bench_serve.sh profile leg) asserts on.
PROFILE_LEG_KEYS = (
    "workload", "seed", "replicas", "tracing", "requests", "completed",
    "errors", "tokens_per_sec", "requests_per_sec",
)


def profile_gate(args) -> None:
    """--profile: the critical-path profile gate.  Per seed, one
    hot-prefix fleet runs the IDENTICAL seeded arrival schedule twice —
    tracing off (NOOP), then on — on the same compiled engines, like
    the --trace gate; the on legs' span trees fold into ONE
    tpu-profile/v1 serve profile (where did the fleet's request time
    go, per span kind), the profile is diffed against ITSELF (the
    determinism canary tools/bench_serve.sh asserts reports zero
    regressions), and the off-vs-on requests/sec delta gates profiling
    overhead (same <5%% budget as tracing)."""
    import random as _random

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.obs.profile import (aggregate, diff_profiles,
                                         trace_records)
    from kuberay_tpu.obs.trace import NOOP_TRACER, Tracer

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    bs = 16
    prof = TRAFFIC_PROFILES["hot-prefix"]
    prefix_len, new_tokens = prof["prefix"], prof["new"]
    slots = prof["slots"]
    rate = prof["rate"] * args.rate_scale
    max_len = prefix_len + new_tokens + 16
    blocks_per_prompt = (max_len + bs - 1) // bs
    num_blocks = slots * blocks_per_prompt + \
        (HOT_PREFIXES // 2 + 1) * (prefix_len // bs)
    replicas = 2

    legs = []
    profiled_records = []
    spans_total = 0
    for seed in args.seeds:
        fleet = _Fleet(cfg, params, replicas, slots=slots,
                       max_len=max_len, num_blocks=num_blocks,
                       block_size=bs, seed=seed, affinity=True,
                       shedding=False)
        try:
            warm = [11_111 + j for j in range(prefix_len)]
            cold_warm = [12_345 + j for j in range(64)]
            fleet.warm([warm + [7], warm + [8], cold_warm + [9]])
            gw_srv, gw_url = fleet.gateway.serve_background_http()
            try:
                hots = _hot_prompts(prefix_len, HOT_PREFIXES)
                hot_warm = [(0.25 * i, list(p) + [31337])
                            for i, p in enumerate(hots * 2)]
                _drive_open_loop(gw_url, hot_warm, new_tokens)
                # Off leg first, same rationale as trace_overhead: any
                # cache-aging drift biases AGAINST profiling, so a
                # passing overhead gate is conservative.
                for tracing in (False, True):
                    tracer = Tracer(max_spans=65536) if tracing \
                        else NOOP_TRACER
                    fleet.set_tracer(tracer)
                    fleet.reset_counters()
                    gw_hits_base = _gateway_hits(fleet)
                    rng = _random.Random(
                        (seed << 8) ^ (zlib.crc32(b"hot-prefix") & 0xFFFF))
                    arrivals = _gen_arrivals(
                        rng, "hot-prefix", args.duration, rate,
                        prefix_len, bs, HOT_PREFIXES,
                        hot_fraction=HOT_FRACTION)
                    records, wall = _drive_open_loop(gw_url, arrivals,
                                                     new_tokens)
                    leg = _leg_summary("hot-prefix", seed, replicas, True,
                                       False, records, wall, fleet,
                                       gw_hits_base=gw_hits_base)
                    leg["tracing"] = tracing
                    leg["requests_per_sec"] = round(
                        leg["completed"] / wall, 2) if wall else 0.0
                    if tracing:
                        spans = tracer.export()
                        recs = trace_records(
                            spans, roots={"serve-request": "serve"})
                        leg["spans_recorded"] = len(spans)
                        leg["profiled_windows"] = len(recs)
                        spans_total += len(spans)
                        profiled_records.extend(recs)
                    legs.append(leg)
                    print(json.dumps(leg), flush=True)
            finally:
                gw_srv.shutdown()
        finally:
            fleet.close()

    profile = aggregate(profiled_records, meta={
        "source": "serve_bench --profile", "workload": "hot-prefix",
        "seeds": list(args.seeds)})
    self_diff = diff_profiles(profile, profile)
    offs = [leg for leg in legs if not leg["tracing"]]
    ons = [leg for leg in legs if leg["tracing"]]
    rps_off = sum(leg["requests_per_sec"] for leg in offs) / len(offs)
    rps_on = sum(leg["requests_per_sec"] for leg in ons) / len(ons)
    overhead = {
        "requests_per_sec_off": round(rps_off, 2),
        "requests_per_sec_on": round(rps_on, 2),
        "overhead_pct": round((rps_off - rps_on) / rps_off * 100.0, 2)
        if rps_off else 0.0,
        "spans_recorded": spans_total,
        "profiled_windows": len(profiled_records),
    }
    print(json.dumps({"profile_overhead": overhead}), flush=True)

    doc = {
        "schema": PROFILE_BENCH_SCHEMA,
        "workload_params": {
            "model": args.model, "duration_s": args.duration,
            "rate_scale": args.rate_scale, "block_size": bs,
            "hot_prefixes": HOT_PREFIXES, "hot_fraction": HOT_FRACTION,
            "profiles": {"hot-prefix": TRAFFIC_PROFILES["hot-prefix"]},
        },
        "seeds": list(args.seeds),
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "legs": legs,
        "profile": profile,
        "self_diff": self_diff,
        "overhead": overhead,
    }
    if args.json_out:
        pathlib.Path(args.json_out).parent.mkdir(parents=True,
                                                 exist_ok=True)
        pathlib.Path(args.json_out).write_text(
            json.dumps(doc, indent=2) + "\n")
        print(f"wrote {args.json_out}", flush=True)


# ---------------------------------------------------------------------------
# Multi-turn session gate: resume-with-tiers vs full-recompute (PR 17,
# docs/kv-tiers.md)
# ---------------------------------------------------------------------------

KV_SCHEMA = "tpu-bench-kv/v1"
# Per-leg keys the smoke gate (tools/bench_serve.sh kv leg) asserts on.
KV_LEG_KEYS = (
    "mode", "seed", "sessions", "turns", "requests", "completed",
    "errors", "device_blocks", "host_blocks", "context_tokens_total",
    "device_token_capacity", "prefill_tokens_total", "prefill_tokens_p50",
    "prefill_tokens_p99", "tier_fetch_blocks", "session_resumes",
)

# Closed-loop regime: sessions' final contexts must dwarf the device
# pool (so turn N+1 finds its blocks cannibalized and the contrast is
# tiers-vs-recompute, not cache-vs-cache), while the host tier holds
# every live chain comfortably.
MULTI_TURN_PROFILE = dict(
    sessions=10, rounds=6, init_ctx=40, user_lo=8, user_hi=16,
    new=8, slots=2, replicas=2, device_blocks=34, host_blocks=256)


def _gen_turn_schedule(seed, prof):
    """The seeded conversation schedule BOTH legs replay: per round a
    shuffled session order, per turn the user's appended tokens.  Fully
    materialized up front so the resume and recompute legs see byte-
    identical prompts (decode is greedy, so outputs — and therefore the
    grown contexts — match too)."""
    import random as _random
    rng = _random.Random((seed << 8) ^ (zlib.crc32(b"multi-turn")
                                        & 0xFFFF))
    schedule = []
    sids = list(range(prof["sessions"]))
    for _ in range(prof["rounds"]):
        order = rng.sample(sids, len(sids))
        for sid in order:
            n = rng.randint(prof["user_lo"], prof["user_hi"])
            schedule.append((sid, [rng.randint(1, 255)
                                   for _ in range(n)]))
    return schedule


def _kv_leg(cfg, params, mode, seed, args, schedule) -> dict:
    """One closed-loop leg: sequential turns through the gateway, no
    wall-clock anywhere in the record — TTFT is proxied by the tokens
    each turn actually prefilled (query minus cache-hit deltas from the
    replica allocators), which is deterministic and is the quantity the
    hierarchy exists to shrink."""
    prof = MULTI_TURN_PROFILE
    bs = 16
    tiered = mode == "resume"
    longest = prof["init_ctx"] + prof["rounds"] * \
        (prof["user_hi"] + prof["new"])
    max_len = ((longest + bs - 1) // bs) * bs + bs
    fleet = _Fleet(cfg, params, prof["replicas"], slots=prof["slots"],
                   max_len=max_len, num_blocks=prof["device_blocks"],
                   block_size=bs, seed=seed, affinity=True,
                   shedding=False,
                   host_blocks=prof["host_blocks"] if tiered else 0)

    def drain_pump():
        # The engine pumps demotions a few blocks per step; between
        # turns the replica is idle, so drain explicitly — this is the
        # "async demotion off the hot path" contract, virtualized.
        for fe in fleet.frontends:
            fe.call_engine(lambda e: e._pump_demotions(1 << 20)
                           if getattr(e, "tiers", None) else 0)

    def prefill_snapshot():
        q = h = fetched = 0
        for fe in fleet.frontends:
            st = fe.engine.stats
            q += st["prefix_query_tokens"]
            h += st["prefix_hit_tokens"]
            fetched += st.get("tier_fetch_blocks", 0)
        return q, h, fetched

    contexts = {sid: [20_000 + sid * 64 + j
                      for j in range(prof["init_ctx"])]
                for sid in range(prof["sessions"])}
    per_turn_prefill = []
    errors = 0
    try:
        # One tiny request per replica compiles the decode program; the
        # artifact carries no wall-clock, so remaining compile stalls
        # only cost smoke runtime, never numbers.
        for fe in fleet.frontends:
            fe.submit([3, 1, 4, 1, 5], max_tokens=2, timeout=600.0)
        for sid, user_toks in schedule:
            ctx = contexts[sid]
            ctx.extend(user_toks)
            body = {"prompt_tokens": list(ctx),
                    "max_tokens": prof["new"], "temperature": 0.0}
            if tiered:
                body["session"] = f"sess-{seed}-{sid}"
            q0, h0, f0 = prefill_snapshot()
            code, payload, _ = fleet.gateway.forward_ex(
                "/v1/completions", json.dumps(body).encode(), 600.0)
            q1, h1, f1 = prefill_snapshot()
            if code != 200:
                errors += 1
                continue
            ctx.extend(json.loads(payload).get("tokens", []))
            per_turn_prefill.append(
                {"prefill_tokens": (q1 - q0) - (h1 - h0),
                 "tier_fetch_blocks": f1 - f0})
            drain_pump()
        resumes = 0
        if tiered:
            resumes = fleet.gateway.session_stats()["session_resumes"]
        prefills = sorted(r["prefill_tokens"] for r in per_turn_prefill)
        return {
            "mode": mode, "seed": seed,
            "sessions": prof["sessions"],
            "turns": prof["rounds"],
            "requests": len(schedule),
            "completed": len(per_turn_prefill),
            "errors": errors,
            "device_blocks": prof["device_blocks"],
            "host_blocks": prof["host_blocks"] if tiered else 0,
            "context_tokens_total": sum(len(c)
                                        for c in contexts.values()),
            "device_token_capacity": prof["device_blocks"] * bs,
            "prefill_tokens_total": sum(prefills),
            "prefill_tokens_p50": round(percentile(prefills, 50), 1)
            if prefills else None,
            "prefill_tokens_p99": round(percentile(prefills, 99), 1)
            if prefills else None,
            "tier_fetch_blocks": sum(r["tier_fetch_blocks"]
                                     for r in per_turn_prefill),
            "session_resumes": resumes,
        }
    finally:
        fleet.close()


def multi_turn(args) -> None:
    """--traffic multi-turn: the stateful-session gate.  Per seed, the
    same seeded conversation schedule runs twice — ``resume`` (tiered
    replicas, gateway sessions; a turn re-enters with its chain parked
    in the host tier and promotes instead of prefilling) and
    ``recompute`` (flat device-only fleet, no sessions; the eviction
    churn makes turn N+1 pay its full context again).  Closed-loop and
    sequential with zero wall-clock in the artifact, so re-runs are
    byte-identical (tools/bench_serve.sh kv leg re-runs seed 0 and
    diffs)."""
    import jax

    from kuberay_tpu.models import llama

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    legs, comparisons = [], []
    for seed in args.seeds:
        schedule = _gen_turn_schedule(seed, MULTI_TURN_PROFILE)
        by = {}
        for mode in ("resume", "recompute"):
            leg = _kv_leg(cfg, params, mode, seed, args, schedule)
            by[mode] = leg
            legs.append(leg)
            print(json.dumps(leg), flush=True)
        cmp_rec = {
            "seed": seed,
            "resume_prefill_p99": by["resume"]["prefill_tokens_p99"],
            "recompute_prefill_p99":
                by["recompute"]["prefill_tokens_p99"],
            "prefill_total_ratio": round(
                by["resume"]["prefill_tokens_total"]
                / max(1, by["recompute"]["prefill_tokens_total"]), 4),
            "resume_beats_recompute":
                by["resume"]["prefill_tokens_p99"] is not None
                and by["recompute"]["prefill_tokens_p99"] is not None
                and by["resume"]["prefill_tokens_p99"]
                < by["recompute"]["prefill_tokens_p99"],
        }
        comparisons.append(cmp_rec)
        print(json.dumps({"kv_comparison": cmp_rec}), flush=True)

    doc = {
        "schema": KV_SCHEMA,
        "workload_params": {"model": args.model, "block_size": 16,
                            "profile": MULTI_TURN_PROFILE},
        "seeds": list(args.seeds),
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "legs": legs,
        "comparisons": comparisons,
    }
    if args.json_out:
        pathlib.Path(args.json_out).parent.mkdir(parents=True,
                                                 exist_ok=True)
        pathlib.Path(args.json_out).write_text(
            json.dumps(doc, indent=2) + "\n")
        print(f"wrote {args.json_out}", flush=True)


# ---------------------------------------------------------------------------
# Blue/green upgrade gate: burn-rate-gated vs naive timer ramp under a
# mid-upgrade fault (PR 13, docs/upgrades.md)
# ---------------------------------------------------------------------------

UPGRADE_SCHEMA = "tpu-bench-upgrade/v1"
# Per-leg keys the smoke gate (tools/bench_serve.sh upgrade leg) asserts.
UPGRADE_LEG_KEYS = (
    "mode", "seed", "requests", "completed", "shed", "errors",
    "ttft_p50_ms", "ttft_p99_ms", "final_green_weight", "steps",
    "rollbacks", "rolled_back", "promoted", "prewarm_replayed",
    "prewarm_hit_rate", "fault_at_weight", "wall_s",
)

# Small hot-prefix regime: prefixes long enough that the green pre-warm
# replay has something to cache, short enough that a leg's three ramps
# fit a smoke duration.
UPGRADE_PROFILE = dict(prefix=64, new=8, slots=4, rate=8.0)


class _UpgradeFleet:
    """One blue and one green serve replica behind a WeightedGateway,
    with the TrafficRoute owned by the BENCH's ramp loop: the bench
    plays service controller, driving the same UpgradeOrchestrator +
    BurnRateGate decision core the control plane mounts
    (kuberay_tpu/controlplane/upgrade.py) against real HTTP backends.

    Routing is pure weighted-random (affinity off, epsilon 1.0): the
    ramp's weight split IS the traffic split, which is the thing under
    test — affinity scoring would route by prefix residency instead."""

    def __init__(self, cfg, params, *, slots, max_len, num_blocks,
                 block_size, seed):
        import random as _random

        from kuberay_tpu.controlplane.store import ObjectStore
        from kuberay_tpu.serve.gateway import GatewayConfig, WeightedGateway
        from kuberay_tpu.serve.paged_engine import PagedServeEngine
        from kuberay_tpu.serve.server import ServeFrontend
        from kuberay_tpu.utils.metrics import MetricsRegistry

        self.frontends = {}
        self.servers = {}
        self.urls = {}
        for role in ("blue", "green"):
            eng = PagedServeEngine(cfg, params, max_slots=slots,
                                   max_len=max_len, num_blocks=num_blocks,
                                   block_size=block_size)
            fe = ServeFrontend(eng, max_queue=512)
            srv, url = fe.serve_background()
            self.frontends[role] = fe
            self.servers[role] = srv
            self.urls[role] = url
        self.store = ObjectStore()
        self.store.create({
            "apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
            "metadata": {"name": "bench", "namespace": "default"},
            "spec": {"backends": [{"service": "blue", "weight": 100},
                                  {"service": "green", "weight": 0}]},
            "status": {},
        })
        self.metrics = MetricsRegistry()
        self.gateway = WeightedGateway(
            self.store, "bench", resolver=lambda s: self.urls[s],
            poll_interval=30.0, metrics=self.metrics,
            config=GatewayConfig(affinity=False, epsilon=1.0,
                                 block_size=block_size, max_queue=4096,
                                 queue_timeout=600.0),
            rng=_random.Random(seed))

    def set_weights(self, green: int, *, prewarm: int = 0,
                    drain: bool = False) -> None:
        """Write the ramp's weight split and re-sync the gateway — the
        bench's stand-in for the controller's weighted-route reconcile
        (which the traffic-weight-through-gate analysis rule pins to the
        orchestrator seam in the real controller)."""
        from kuberay_tpu.controlplane.store import Conflict

        blue = {"service": "blue", "weight": 100 - green}
        grn = {"service": "green", "weight": green}
        if prewarm:
            grn["prewarm"] = prewarm
        if drain:
            blue["drain"] = True
        for _ in range(5):
            route = self.store.get("TrafficRoute", "bench", "default")
            route["spec"]["backends"] = [blue, grn]
            try:
                self.store.update(route)
                break
            except Conflict:
                continue        # gateway ack raced the write; re-read
        self.gateway._refresh()

    def prewarm_replayed(self) -> int:
        route = self.store.get("TrafficRoute", "bench", "default")
        acked = (route.get("status") or {}).get("prewarmed") or {}
        return int(acked.get("green", 0) or 0)

    def reset_green_counters(self) -> None:
        a = self.frontends["green"].engine.allocator
        a.prefix_hits = 0
        a.prefix_queries = 0

    def green_hit_rate(self):
        st = self.frontends["green"].engine.stats
        q = st["prefix_query_tokens"]
        return round(st["prefix_hit_tokens"] / q, 3) if q else None

    def kill_green(self) -> None:
        """Mid-upgrade fault: green's endpoint starts refusing
        connections (the replacement-pod regime).  Rewire its URL at
        the gateway to a dead port — instant ECONNREFUSED, with no
        half-open accept backlog for clients to hang on (shutting the
        real listener leaves OS-backlogged connects waiting forever)."""
        dead = "http://127.0.0.1:9"         # discard port: refused
        self.urls["green"] = dead
        with self.gateway._lock:
            st = self.gateway._states.get("green")
            if st is not None:
                st.url = dead

    def warm(self, prompts) -> None:
        for fe in self.frontends.values():
            for p in prompts:
                fe.submit(p, max_tokens=2, timeout=600.0)

    def close(self) -> None:
        self.gateway.stop()
        for srv in self.servers.values():
            srv.shutdown()
        for fe in self.frontends.values():
            fe.close()


def _run_upgrade_ramp(fleet, mode, stop_evt, ramp, *, step_size, interval_s,
                      fault_at, prewarm_n, ttft_target_s, min_samples,
                      tick=0.2):
    """Control loop for one ramp leg.  ``gated`` consults the
    BurnRateGate before every decision; ``naive`` feeds the orchestrator
    a vacuously-healthy verdict — the open-loop timer ramp this PR
    replaced, kept as the bench's control arm.  The fault fires the
    first time green weight reaches ``fault_at``."""
    from kuberay_tpu.controlplane.upgrade import (
        ABORT,
        PROMOTE,
        ROLLBACK,
        STEP,
        BurnRateGate,
        UpgradeObservation,
        UpgradeOrchestrator,
    )

    orch = UpgradeOrchestrator()
    # min_samples below the controller's default (5): smoke legs run
    # seconds, not minutes — three bad attempts on a 2-replica fleet is
    # already a 60%+ error ratio, far past the 14x burn threshold.
    gate = BurnRateGate(fleet.metrics, ttft_target_s=ttft_target_s,
                        min_samples=min_samples) \
        if mode == "gated" else None
    want_prewarm = prewarm_n if mode == "gated" else 0
    # First write runs the gateway's prefix replay synchronously inside
    # _refresh (gated leg); reset green's counters after so the reported
    # hit rate is real ramp traffic against the pre-warmed cache.
    fleet.set_weights(0, prewarm=want_prewarm)
    ramp["prewarm_replayed"] = fleet.prewarm_replayed()
    fleet.reset_green_counters()
    while not stop_evt.is_set():
        if not ramp["faulted"] and ramp["weight"] >= fault_at \
                and time.time() - ramp["last_step"] >= interval_s:
            # Fire only after green served a full interval at the fault
            # weight, so the pre-warm hit-rate evidence reflects real
            # ramp traffic (and the fault lands between a gate check
            # and the next step — the worst-case window).
            fleet.kill_green()
            ramp["faulted"] = True
        if ramp["promoted"]:
            stop_evt.wait(tick)
            continue
        healthy, alert = (True, None)
        if gate is not None:
            healthy, alert = gate.verdict("green")
        obs = UpgradeObservation(
            now=time.time(), green_weight=ramp["weight"],
            step_size=step_size, interval_s=interval_s,
            last_step_time=ramp["last_step"],
            ready_slices=1, desired_slices=1,   # bench rings stay whole
            gate_healthy=healthy, firing_alert=alert,
            rollbacks=ramp["rollbacks"], max_rollbacks=1,
            hold_seconds=3600.0,                # hold for the leg's rest
            last_rollback_time=ramp["last_rollback"],
            prewarm_requested=bool(want_prewarm),
            prewarm_done=ramp["prewarm_replayed"] > 0)
        dec = orch.decide(obs)
        if dec.action == STEP:
            ramp["weight"] = dec.green_weight
            ramp["last_step"] = time.time()
            ramp["steps"] += 1
            fleet.set_weights(ramp["weight"], prewarm=want_prewarm)
        elif dec.action in (ROLLBACK, ABORT):
            ramp["weight"] = 0
            ramp["rollbacks"] += 1
            ramp["rolled_back"] = True
            ramp["last_rollback"] = time.time()
            fleet.set_weights(0, prewarm=want_prewarm)
        elif dec.action == PROMOTE:
            ramp["weight"] = 100
            ramp["promoted"] = True
            fleet.set_weights(100)
        stop_evt.wait(tick)


def _upgrade_summary(mode, seed, records, wall, ramp):
    completed = [r for r in records if r["code"] == 200]
    shed = sum(1 for r in records if r["code"] == 429)
    errors = sum(1 for r in records if r["code"] not in (200, 429))
    ttfts = sorted(r["ttft_ms"] for r in completed
                   if r["ttft_ms"] is not None)
    return {
        "mode": mode, "seed": seed,
        "requests": len(records), "completed": len(completed),
        "shed": shed, "errors": errors,
        "ttft_p50_ms": round(percentile(ttfts, 50), 2) if ttfts else None,
        "ttft_p99_ms": round(percentile(ttfts, 99), 2) if ttfts else None,
        "final_green_weight": ramp["weight"],
        "steps": ramp["steps"], "rollbacks": ramp["rollbacks"],
        "rolled_back": ramp["rolled_back"], "promoted": ramp["promoted"],
        "prewarm_replayed": ramp["prewarm_replayed"],
        "prewarm_hit_rate": ramp["prewarm_hit_rate"],
        "fault_at_weight": ramp["fault_at"],
        "wall_s": round(wall, 2),
    }


def _upgrade_leg(cfg, params, mode, seed, args) -> dict:
    import random as _random
    import threading

    prof = UPGRADE_PROFILE
    bs = 16
    prefix_len, new_tokens = prof["prefix"], prof["new"]
    slots = prof["slots"]
    rate = prof["rate"] * args.rate_scale
    max_len = prefix_len + new_tokens + 16
    blocks_per_prompt = (max_len + bs - 1) // bs
    num_blocks = slots * blocks_per_prompt + \
        HOT_PREFIXES * (prefix_len // bs)
    fleet = _UpgradeFleet(cfg, params, slots=slots, max_len=max_len,
                          num_blocks=num_blocks, block_size=bs, seed=seed)
    ramp = {"weight": 0, "steps": 0, "rollbacks": 0, "last_step": 0.0,
            "last_rollback": 0.0, "rolled_back": False, "promoted": False,
            "faulted": False, "prewarm_replayed": 0,
            "prewarm_hit_rate": None, "fault_at": None}
    try:
        # Compile every bucket on BOTH replicas outside the timed window:
        # green's first real request lands mid-ramp where a compile stall
        # would read as a gate-worthy latency spike.
        warm = [11_111 + j for j in range(prefix_len)]
        fleet.warm([warm + [7], warm + [8]])
        gw_srv, gw_url = fleet.gateway.serve_background_http()
        try:
            # Blue-only warm pass through the GATEWAY: teaches the
            # gateway's HotPrompts tracker the fleet's hot prefixes —
            # the set the pre-warm replay sends at green.
            hots = _hot_prompts(prefix_len, HOT_PREFIXES)
            hot_warm = [(0.2 * i, list(p) + [31337])
                        for i, p in enumerate(hots * 2)]
            _drive_open_loop(gw_url, hot_warm, new_tokens)
            stop = threading.Event()
            ramp_thread = None
            if mode != "baseline":
                ramp["fault_at"] = args.upgrade_fault_at
                ramp_thread = threading.Thread(
                    target=_run_upgrade_ramp,
                    args=(fleet, mode, stop, ramp),
                    kwargs=dict(step_size=25,
                                interval_s=args.upgrade_interval,
                                fault_at=args.upgrade_fault_at,
                                prewarm_n=HOT_PREFIXES,
                                ttft_target_s=10.0, min_samples=3),
                    daemon=True, name=f"upgrade-ramp-{mode}")
                ramp_thread.start()
            rng = _random.Random(
                (seed << 8) ^ (zlib.crc32(b"upgrade") & 0xFFFF))
            arrivals = _gen_arrivals(
                rng, "hot-prefix", args.duration, rate, prefix_len, bs,
                HOT_PREFIXES, hot_fraction=HOT_FRACTION)
            records, wall = _drive_open_loop(gw_url, arrivals, new_tokens,
                                             timeout=60.0)
            stop.set()
            if ramp_thread is not None:
                ramp_thread.join(timeout=10.0)
                # Green serves nothing after the fault, so the end-of-leg
                # hit rate IS the pre-fault ramp-traffic hit rate: the
                # pre-warm evidence (gated leg warm, naive leg cold).
                ramp["prewarm_hit_rate"] = fleet.green_hit_rate()
        finally:
            gw_srv.shutdown()
        return _upgrade_summary(mode, seed, records, wall, ramp)
    finally:
        fleet.close()


def upgrade(args) -> None:
    """--upgrade: the zero-downtime upgrade gate.  Per seed, three legs
    over the same seeded hot-prefix schedule: ``baseline`` (blue only —
    the TTFT yardstick), ``gated`` (orchestrator ramp, burn-rate gate
    live, green endpoint dies at ``--upgrade-fault-at``% — must roll
    back with ZERO client-visible failures), ``naive`` (the pre-PR-13
    open-loop timer ramp under the same fault — promotes the dead build
    and fails requests, which is the point).  tools/bench_serve.sh
    asserts the contrast; full-scale numbers live in
    benchmark/results/upgrade_r13.json."""
    import jax

    from kuberay_tpu.models import llama

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    legs = []
    for seed in args.seeds:
        for mode in ("baseline", "gated", "naive"):
            leg = _upgrade_leg(cfg, params, mode, seed, args)
            legs.append(leg)
            print(json.dumps(leg), flush=True)

    comparisons = []
    for seed in args.seeds:
        by = {leg["mode"]: leg for leg in legs if leg["seed"] == seed}
        base, gated, naive = by["baseline"], by["gated"], by["naive"]
        inflation = None
        if base["ttft_p99_ms"] and gated["ttft_p99_ms"] is not None:
            inflation = round(gated["ttft_p99_ms"] / base["ttft_p99_ms"],
                              3)
        comparisons.append({
            "seed": seed,
            "gated_errors": gated["errors"],
            "gated_rolled_back": gated["rolled_back"],
            "ttft_inflation": inflation,
            "naive_errors": naive["errors"],
            "naive_promoted_bad_build": naive["promoted"],
        })
        print(json.dumps({"upgrade_comparison": comparisons[-1]}),
              flush=True)

    doc = {
        "schema": UPGRADE_SCHEMA,
        "workload_params": {
            "model": args.model, "duration_s": args.duration,
            "rate_scale": args.rate_scale, "block_size": 16,
            "hot_prefixes": HOT_PREFIXES, "hot_fraction": HOT_FRACTION,
            "profile": UPGRADE_PROFILE,
            "step_size": 25, "interval_s": args.upgrade_interval,
            "fault_at_weight": args.upgrade_fault_at,
            "ttft_inflation_limit": args.upgrade_ttft_limit,
        },
        "seeds": list(args.seeds),
        "device": str(jax.devices()[0]),
        "platform": jax.devices()[0].platform,
        "legs": legs,
        "comparisons": comparisons,
    }
    if args.json_out:
        pathlib.Path(args.json_out).parent.mkdir(parents=True,
                                                 exist_ok=True)
        pathlib.Path(args.json_out).write_text(
            json.dumps(doc, indent=2) + "\n")
        print(f"wrote {args.json_out}", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="serve-bench")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (off-chip smoke)")
    ap.add_argument("--model", default="llama_tiny")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--prefix", type=int, default=64,
                    help="shared prompt-prefix length (tokens)")
    ap.add_argument("--new", type=int, default=32,
                    help="decode tokens per request")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--matrix", action="store_true",
                    help="run the full engine matrix with TTFT "
                         "percentiles and relative overheads")
    ap.add_argument("--traffic", default="",
                    choices=["", "hot-prefix", "burst", "diurnal",
                             "long-prompt", "multi-turn", "all"],
                    help="seeded open-loop traffic generator through the "
                         "prefix-aware gateway (tpu-bench-serve/v1); "
                         "long-prompt runs the colocated-vs-disaggregated "
                         "comparison; multi-turn runs the closed-loop "
                         "session gate (tpu-bench-kv/v1, byte-stable)")
    ap.add_argument("--trace", action="store_true",
                    help="tracing-overhead gate: hot-prefix legs with "
                         "end-to-end request tracing off vs on, same "
                         "compiled fleet and arrival schedule")
    ap.add_argument("--profile", action="store_true",
                    help="critical-path profile gate: hot-prefix legs "
                         "tracer off vs on per seed, folded into one "
                         "tpu-profile/v1 serve profile + self-diff + "
                         "requests/sec overhead (tpu-bench-profile/v1)")
    ap.add_argument("--upgrade", action="store_true",
                    help="blue/green upgrade gate: burn-rate-gated vs "
                         "naive timer ramp under a mid-upgrade fault "
                         "(tpu-bench-upgrade/v1)")
    ap.add_argument("--upgrade-fault-at", type=int, default=50,
                    help="green weight %% at which the green endpoint "
                         "starts refusing connections")
    ap.add_argument("--upgrade-interval", type=float, default=1.2,
                    help="ramp step interval in seconds")
    ap.add_argument("--upgrade-ttft-limit", type=float, default=5.0,
                    help="max gated-leg TTFT p99 as a multiple of the "
                         "blue-only baseline (recorded in the artifact; "
                         "tools/bench_serve.sh asserts it)")
    ap.add_argument("--seeds", default="0",
                    help="traffic seeds: single (7) or range (0..2)")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="seconds of open-loop traffic per leg")
    ap.add_argument("--rate-scale", type=float, default=1.0,
                    help="multiply every traffic profile's base rate "
                         "(smoke runs shrink with --duration + this)")
    ap.add_argument("--json-out", default="",
                    help="write matrix/traffic results to this JSON file")
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed rounds per variant; median is published")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from kuberay_tpu.utils.platform import pin_platform_from_env
        pin_platform_from_env()
    if args.traffic or args.trace or args.upgrade or args.profile:
        if ".." in args.seeds:
            lo, hi = args.seeds.split("..", 1)
            args.seeds = list(range(int(lo), int(hi) + 1))
        else:
            args.seeds = [int(args.seeds)]
        if args.traffic == "multi-turn":
            multi_turn(args)
        elif args.traffic:
            traffic(args)
        if args.trace:
            trace_overhead(args)
        if args.profile:
            profile_gate(args)
        if args.upgrade:
            upgrade(args)
    elif args.matrix:
        matrix(args)
    else:
        run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
