"""Serving throughput bench: dense vs paged KV engines.

Prints one JSON line per engine with decode tokens/s and (paged) prefix
cache hit rate, over a workload of concurrent requests sharing a system
prompt — the shape paged attention + prefix caching exist for.  The
train-side counterpart of the driver's bench.py; run with --cpu off-chip.

Usage: python benchmark/serve_bench.py [--cpu] [--model llama_tiny]
       [--requests 16] [--prefix 64] [--new 32] [--slots 8]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def run(args) -> None:
    import jax
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = llama.CONFIGS[args.model]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    shared = list(range(1, args.prefix + 1))

    def requests():
        return [Request(f"r{i}", shared + [100 + i],
                        max_new_tokens=args.new)
                for i in range(args.requests)]

    def drive(engine, label):
        # Warmup: compile every program the timed pass will hit (full
        # prefill bucket, cached-suffix bucket on the paged path, decode)
        # — otherwise compile seconds dwarf decode ms and invert the
        # comparison.  The timed pass therefore measures warm-cache
        # steady state for the paged engine (its serving regime).
        for i in range(2):
            engine.add_request(Request(f"warm{i}", shared + [90 + i],
                                       max_new_tokens=2))
            engine.run()
        for r in requests():
            engine.add_request(r)
        t0 = time.perf_counter()
        out = engine.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in out)
        rec = {
            "metric": f"serve_decode_tokens_per_sec_{label}",
            "value": round(toks / dt, 1),
            "unit": "tokens/s",
            "detail": {"model": args.model, "requests": len(out),
                       "prefix_len": args.prefix, "new_tokens": args.new,
                       "slots": args.slots, "wall_s": round(dt, 2)},
        }
        stats = getattr(engine, "stats", None)
        if stats:
            q = max(1, stats["prefix_query_tokens"])
            rec["detail"]["prefix_hit_rate"] = round(
                stats["prefix_hit_tokens"] / q, 3)
        print(json.dumps(rec), flush=True)

    max_len = args.prefix + args.new + 8
    drive(ServeEngine(cfg, params, max_slots=args.slots, max_len=max_len),
          "dense")
    drive(PagedServeEngine(cfg, params, max_slots=args.slots,
                           max_len=max_len, block_size=16), "paged")

    def stall(chunk, label):
        # Decode-stall probe: short requests are mid-decode when one long
        # prompt arrives; the worst step time while its prefill is in
        # flight IS the stall chunked prefill exists to bound.
        long_len = max(4 * args.prefix, 128)
        eng = ServeEngine(cfg, params, max_slots=args.slots,
                          max_len=long_len + args.new + 8,
                          prefill_chunk=chunk)
        eng.add_request(Request("warm", list(range(1, long_len + 1)),
                                max_new_tokens=2))
        eng.run()                                   # compile all programs
        for i in range(3):
            eng.add_request(Request(f"bg{i}", [7 + i], max_new_tokens=500))
        for _ in range(4):
            eng.step()
        eng.add_request(Request("long", list(range(1, long_len + 1)),
                                max_new_tokens=2))
        worst = 0.0
        while eng.queue or eng._inflight is not None:
            t0 = time.perf_counter()
            eng.step()
            worst = max(worst, time.perf_counter() - t0)
        print(json.dumps({
            "metric": f"serve_decode_stall_ms_{label}",
            "value": round(worst * 1e3, 2), "unit": "ms",
            "detail": {"long_prompt": long_len, "chunk": chunk}}),
            flush=True)

    stall(0, "whole_prefill")
    stall(32, "chunked_prefill")

    def spec(gamma, label):
        # Repetitive continuation workload — the regime prompt-lookup
        # speculation exists for (code/quotes/structured text).  max_len
        # is sized from the ACTUAL prompt length (24 tokens), not
        # --prefix, so small flag values can't silently cancel requests.
        plen = 24
        eng = ServeEngine(cfg, params, max_slots=args.slots,
                          max_len=plen + 2 * args.new + 8,
                          speculative=gamma)
        eng.add_request(Request("warm", [5, 6] * 8, max_new_tokens=4))
        eng.run()
        if gamma:
            # The warm request only hits _verify if a draft happened to
            # match; force-compile the verify program so its first
            # compile can't land in the timed region.
            import jax as _jax
            import jax.numpy as _jnp
            import numpy as _np
            zeros = _np.zeros((args.slots, gamma + 1), _np.int32)
            # Sampling params travel as per-slot [temp, top_p, top_k]
            # rows (engine._samp); greedy warmup = zeros with top_p=1.
            samp = _np.zeros((args.slots, 3), _np.float32)
            samp[:, 1] = 1.0
            _, _, eng.cache = eng._verify(
                eng.params, eng.cache, _jnp.asarray(zeros),
                _jnp.asarray(eng.lens),
                _jnp.zeros(args.slots, _jnp.int32),     # ntok
                _jax.random.PRNGKey(0),
                _jnp.asarray(samp),
                _jnp.zeros(args.slots, _jnp.float32))   # all rows masked
        for i in range(args.requests):
            pat = [10 + i, 11 + i, 12 + i]
            eng.add_request(Request(f"s{i}", pat * 8,
                                    max_new_tokens=2 * args.new))
        t0 = time.perf_counter()
        out = eng.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in out)
        rec = {"metric": f"serve_decode_tokens_per_sec_{label}",
               "value": round(toks / dt, 1), "unit": "tokens/s",
               "detail": {"gamma": gamma, "requests": len(out)}}
        if gamma and eng.spec_stats["drafted"]:
            rec["detail"]["accept_rate"] = round(
                eng.spec_stats["accepted"] / eng.spec_stats["drafted"], 3)
        print(json.dumps(rec), flush=True)

    spec(0, "sequential")
    spec(4, "speculative")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="serve-bench")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (off-chip smoke)")
    ap.add_argument("--model", default="llama_tiny")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--prefix", type=int, default=64,
                    help="shared prompt-prefix length (tokens)")
    ap.add_argument("--new", type=int, default=32,
                    help="decode tokens per request")
    ap.add_argument("--slots", type=int, default=8)
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from kuberay_tpu.utils.platform import pin_platform_from_env
        pin_platform_from_env()
    run(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
