// High-throughput tokenized-shard data loader (native runtime component).
//
// Role: the framework's equivalent of the native data path the reference
// ecosystem delegates to Ray's C++ core — feeding the TPU input pipeline
// without Python in the hot loop.  An mmap'd shard of uint32 tokens is
// sliced into [batch, seq_len+1] windows by prefetch threads into a
// bounded ring buffer; the Python side (kuberay_tpu/train/data.py) pulls
// ready batches over a minimal C ABI via ctypes (no pybind11 dependency).
//
// Determinism: batch order is a pure function of (seed, epoch); a
// splitmix64-based index shuffle avoids materializing permutations.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <queue>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Batch {
    std::vector<uint32_t> data;
};

static inline uint64_t splitmix64(uint64_t x) {
    x += 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    return x ^ (x >> 31);
}

struct Loader {
    const uint32_t* tokens = nullptr;
    size_t n_tokens = 0;
    size_t file_bytes = 0;
    int fd = -1;

    int64_t seq_len = 0;
    int64_t batch = 0;
    uint64_t seed = 0;
    bool shuffle = true;

    size_t n_windows = 0;        // windows of (seq_len + 1) tokens
    std::atomic<uint64_t> cursor{0};

    std::queue<Batch> ready;
    std::mutex mu;
    std::condition_variable cv_ready, cv_space;
    size_t max_ready = 8;
    std::atomic<bool> stop{false};
    std::vector<std::thread> workers;

    ~Loader() { shutdown(); }

    void shutdown() {
        stop.store(true);
        cv_space.notify_all();
        cv_ready.notify_all();
        for (auto& t : workers)
            if (t.joinable()) t.join();
        workers.clear();
        if (tokens) { munmap((void*)tokens, file_bytes); tokens = nullptr; }
        if (fd >= 0) { close(fd); fd = -1; }
    }

    size_t window_index(uint64_t i) const {
        uint64_t epoch = i / n_windows;
        uint64_t within = i % n_windows;
        if (!shuffle) return (size_t)within;
        // Feistel-light: bijective-ish scramble within the epoch; collisions
        // across distinct inputs are impossible for power-of-two rounding,
        // so for arbitrary n use hash-then-linear-probe on the index ring.
        uint64_t h = splitmix64(within ^ splitmix64(seed + epoch));
        return (size_t)(h % n_windows);
    }

    void worker_loop() {
        const size_t win = (size_t)seq_len + 1;
        while (!stop.load()) {
            Batch b;
            b.data.resize((size_t)batch * win);
            for (int64_t r = 0; r < batch; ++r) {
                uint64_t i = cursor.fetch_add(1);
                size_t w = window_index(i);
                std::memcpy(b.data.data() + (size_t)r * win,
                            tokens + w * win, win * sizeof(uint32_t));
            }
            std::unique_lock<std::mutex> lk(mu);
            cv_space.wait(lk, [&] { return ready.size() < max_ready || stop.load(); });
            if (stop.load()) return;
            ready.push(std::move(b));
            cv_ready.notify_one();
        }
    }
};

}  // namespace

extern "C" {

// Returns nullptr on failure.
void* dl_open(const char* path, int64_t seq_len, int64_t batch,
              uint64_t seed, int shuffle, int n_threads) {
    if (seq_len <= 0 || batch <= 0) return nullptr;
    int fd = open(path, O_RDONLY);
    if (fd < 0) return nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
    size_t n_tokens = (size_t)st.st_size / sizeof(uint32_t);
    size_t win = (size_t)seq_len + 1;
    if (n_tokens < win) { close(fd); return nullptr; }
    void* map = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (map == MAP_FAILED) { close(fd); return nullptr; }
    madvise(map, (size_t)st.st_size, MADV_WILLNEED);

    auto* L = new Loader();
    L->fd = fd;
    L->file_bytes = (size_t)st.st_size;
    L->tokens = (const uint32_t*)map;
    L->n_tokens = n_tokens;
    L->seq_len = seq_len;
    L->batch = batch;
    L->seed = seed;
    L->shuffle = shuffle != 0;
    L->n_windows = n_tokens / win;
    int nt = n_threads > 0 ? n_threads : 2;
    for (int i = 0; i < nt; ++i)
        L->workers.emplace_back([L] { L->worker_loop(); });
    return L;
}

// Copies one [batch, seq_len+1] uint32 batch into out. Returns 0 on
// success, -1 when the loader is shut down.
int dl_next(void* handle, uint32_t* out) {
    auto* L = (Loader*)handle;
    Batch b;
    {
        std::unique_lock<std::mutex> lk(L->mu);
        L->cv_ready.wait(lk, [&] { return !L->ready.empty() || L->stop.load(); });
        if (L->ready.empty()) return -1;
        b = std::move(L->ready.front());
        L->ready.pop();
        L->cv_space.notify_one();
    }
    std::memcpy(out, b.data.data(), b.data.size() * sizeof(uint32_t));
    return 0;
}

int64_t dl_num_windows(void* handle) {
    return (int64_t)((Loader*)handle)->n_windows;
}

int64_t dl_num_tokens(void* handle) {
    return (int64_t)((Loader*)handle)->n_tokens;
}

void dl_close(void* handle) {
    delete (Loader*)handle;
}

}  // extern "C"
