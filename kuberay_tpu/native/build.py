"""Shared native-build helper: compile a C++ source in this directory to
a shared object, content-addressed by source sha256 (never mtimes), with
atomic publication safe for concurrent builders on shared filesystems.
Consumers: train/data.py (dataloader), native/journal.py (journal)."""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess
import tempfile
import threading
from typing import Optional

NATIVE_DIR = pathlib.Path(__file__).resolve().parent
_build_lock = threading.Lock()


def build_native(src_name: str) -> Optional[pathlib.Path]:
    """Compile ``native/<src_name>`` once; returns the .so path or None
    when no toolchain is available (callers fall back to pure Python).

    The cache key is the sha256 of the source (stored in a sidecar
    file): the .so that executes is always one this process tree
    compiled from the checked-in source (binaries are not committed —
    see .gitignore), and a stale or foreign .so is never loaded."""
    src = NATIVE_DIR / src_name
    so = NATIVE_DIR / "build" / f"lib{src.stem}.so"
    with _build_lock:
        src_sha = hashlib.sha256(src.read_bytes()).hexdigest()
        stamp = so.with_suffix(".src.sha256")
        if (so.exists() and stamp.exists()
                and stamp.read_text().strip() == src_sha):
            return so
        so.parent.mkdir(parents=True, exist_ok=True)
        # Compile to a builder-private temp path, then os.replace() both
        # artifact and stamp atomically: concurrent builders each publish
        # a complete .so — a reader can never load a half-written one.
        # mkstemp (not pid suffixes: two hosts on shared NFS can share a
        # pid) guarantees the temp name is unique across builders.
        fd, tmp = tempfile.mkstemp(dir=so.parent, prefix=f".{so.name}.")
        os.close(fd)
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               str(src), "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
            fd, tmp_stamp = tempfile.mkstemp(dir=so.parent,
                                             prefix=f".{stamp.name}.")
            with os.fdopen(fd, "w") as f:
                f.write(src_sha)
            os.replace(tmp_stamp, stamp)
            return so
        except (subprocess.SubprocessError, FileNotFoundError):
            pathlib.Path(tmp).unlink(missing_ok=True)
            return None
