"""Journal engine bindings: native group-commit writer (journal.cpp) with
a pure-Python fallback of identical semantics and file format.

Frame format (shared by both engines and the replay path):
``[u32 len][u32 crc32(payload)][payload]`` little-endian.  Replay stops
cleanly at the first torn or corrupt frame (crash tail).

``open_journal`` picks the native engine when the toolchain is available
(the .so is compiled from source on first use — never committed) and
falls back to ``PyJournal`` otherwise; both are crash-durable
(fdatasync/fsync before an acknowledged ``flush()`` returns), unlike the
round-1 line-buffered text journal which lost acknowledged state on
machine crash.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional

from kuberay_tpu.native.build import build_native

_lib = None
_lib_tried = False
_lib_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        so = build_native("journal.cpp")
        if so is None:
            return None
        lib = ctypes.CDLL(str(so))
        lib.jrn_open.restype = ctypes.c_void_p
        lib.jrn_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.jrn_append.restype = ctypes.c_int
        lib.jrn_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint32]
        lib.jrn_flush.restype = ctypes.c_int
        lib.jrn_flush.argtypes = [ctypes.c_void_p]
        lib.jrn_close.argtypes = [ctypes.c_void_p]
        lib.jrn_replay.restype = ctypes.c_long
        _CB = ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_uint8),
                               ctypes.c_uint32)
        lib.jrn_replay.argtypes = [ctypes.c_char_p, _CB]
        lib._CB = _CB
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# Live native journals, closed once at interpreter exit (a single hook +
# weak refs: per-instance atexit registrations would pin every compaction-
# era journal object for the process lifetime).
_live_journals = None


def _close_live():
    for j in list(_live_journals or ()):
        j.close()


class NativeJournal:
    """ctypes wrapper over journal.cpp's group-commit engine.

    Thread-safe, and safe against the close/flush race: append/flush
    after close() are no-ops (close drains and syncs pending frames
    first), so a flusher holding a stale handle can never reach freed
    native state."""

    def __init__(self, path: str, sync: bool = True):
        global _live_journals
        lib = _load()
        if lib is None:
            raise RuntimeError("native journal unavailable")
        self._lib = lib
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._inflight = 0
        self._h = lib.jrn_open(path.encode(), 1 if sync else 0)
        if not self._h:
            raise OSError(f"jrn_open failed: {path}")
        if _live_journals is None:
            import atexit
            import weakref
            _live_journals = weakref.WeakSet()
            atexit.register(_close_live)
        _live_journals.add(self)

    def append(self, payload: bytes) -> None:
        with self._mu:
            if self._h:
                self._lib.jrn_append(self._h, payload, len(payload))

    def flush(self) -> None:
        # jrn_flush blocks (group-commit wait, up to 5 s on a disk
        # stall); it must run OUTSIDE _mu so concurrent flushers join
        # the same in-flight batch instead of serializing — the C++
        # side is thread-safe.  The refcount keeps close() from freeing
        # the handle under us.
        with self._mu:
            h = self._h
            if not h:
                return   # closed: close() already drained + synced
            self._inflight += 1
        try:
            rc = self._lib.jrn_flush(h)
        finally:
            with self._mu:
                self._inflight -= 1
                if self._inflight == 0:
                    self._cv.notify_all()
        if rc != 0:
            raise OSError("journal flush timed out (disk stall/error)")

    def close(self) -> None:
        with self._mu:
            while self._inflight:
                self._cv.wait()
            if self._h:
                self._lib.jrn_close(self._h)
                self._h = None


class PyJournal:
    """Pure-Python engine: same frames, fsync on flush()."""

    def __init__(self, path: str, sync: bool = True):
        self._f = open(path, "ab")
        self._sync = sync
        self._lock = threading.Lock()

    def append(self, payload: bytes) -> None:
        frame = struct.pack("<II", len(payload),
                            zlib.crc32(payload)) + payload
        with self._lock:
            if self._f.closed:
                return
            self._f.write(frame)
            # OS-level flush per append (cheap; survives process crash).
            # fsync (machine-crash durability) happens in flush().
            self._f.flush()

    def flush(self) -> None:
        with self._lock:
            if self._f.closed:
                return   # closed: close() already flushed + synced
            self._f.flush()
            if self._sync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                if self._sync:
                    os.fsync(self._f.fileno())
                self._f.close()


def open_journal(path: str, engine: str = "auto", sync: bool = True):
    """engine: auto | native | python."""
    if engine == "native" or (engine == "auto" and native_available()):
        return NativeJournal(path, sync)
    return PyJournal(path, sync)


def replay(path: str, engine: str = "auto") -> Iterator[bytes]:
    """Yield each valid frame payload; stops at a torn/corrupt tail."""
    if not os.path.exists(path):
        return iter(())
    lib = _load() if engine in ("auto", "native") else None
    if lib is not None:
        out: List[bytes] = []

        @lib._CB
        def cb(data, length):
            out.append(ctypes.string_at(data, length))

        if lib.jrn_replay(path.encode(), cb) < 0:
            raise OSError(f"cannot replay {path}")
        return iter(out)
    return _py_replay(path)


def valid_prefix_len(path: str) -> int:
    """Byte offset of the end of the last VALID frame — the truncation
    point after a crash (frames appended after a torn tail would be
    unreachable to replay, so the opener truncates to this first)."""
    end = 0
    try:
        with open(path, "rb") as f:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    return end
                length, crc = struct.unpack("<II", hdr)
                if length > 1 << 30:
                    return end
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    return end
                end += 8 + length
    except OSError:
        return end


def _py_replay(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                return
            length, crc = struct.unpack("<II", hdr)
            if length > 1 << 30:
                return
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return
            yield payload
