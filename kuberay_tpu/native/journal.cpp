// Native journal engine: CRC-framed append-only log with group commit.
//
// The control plane's durability hot path (ObjectStore journal — the
// etcd-lite standalone mode, SURVEY §5.4).  The reference's equivalent
// state stores are native (etcd via kube-apiserver; Ray GCS in C++);
// here the write path is C++ for the same reason: a Python
// write()+fsync() per mutation caps reconcile throughput, while unsynced
// buffered writes (round-1's journal) lose acknowledged state on crash.
//
// Design:
// - Frame: [u32 len][u32 crc32(payload)][payload] little-endian.
// - Appends enqueue into an in-memory buffer; a flusher thread drains it
//   with one write()+fdatasync() per BATCH (group commit): many
//   mutations share one disk sync, so durability costs O(syncs/sec),
//   not O(mutations/sec).
// - jrn_flush() blocks until everything enqueued so far is ON DISK
//   (fdatasync'd) — the store calls it before acknowledging writes that
//   must be durable.
// - Replay validates CRCs and stops at the first bad/truncated frame
//   (a torn tail from a crash is expected, not fatal).
//
// C ABI only (ctypes consumer: kuberay_tpu/native/journal.py).

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Journal {
  int fd = -1;
  std::mutex mu;
  std::condition_variable cv_work;    // flusher wakeup
  std::condition_variable cv_done;    // flush waiters
  std::vector<uint8_t> pending;       // framed, not yet written
  uint64_t enqueued_seq = 0;          // frames enqueued
  uint64_t durable_seq = 0;           // frames fdatasync'd
  bool stop = false;
  bool sync_each_batch = true;
  off_t tear_at = -1;    // torn-write offset still awaiting truncation
  std::thread flusher;

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    while (true) {
      cv_work.wait_for(lk, std::chrono::milliseconds(5), [&] {
        return stop || !pending.empty();
      });
      if (pending.empty()) {
        if (stop) return;
        continue;
      }
      std::vector<uint8_t> batch;
      batch.swap(pending);
      uint64_t seq = enqueued_seq;
      lk.unlock();
      // A tear from an earlier failed batch MUST be cut before anything
      // else is written: frames appended behind a torn frame are
      // unreachable by replay yet would be acked by fdatasync.  Until
      // the truncate succeeds, no write happens and durable_seq stays
      // put, so flush() waiters time out instead of acking lost state.
      if (tear_at >= 0) {
        if (::ftruncate(fd, tear_at) != 0) {
          lk.lock();
          pending.insert(pending.begin(), batch.begin(), batch.end());
          if (stop) return;
          cv_work.wait_for(lk, std::chrono::milliseconds(50),
                           [&] { return stop; });
          continue;
        }
        tear_at = -1;
      }
      // Remember where this batch starts: a partial write must be
      // truncated away before retrying, or the retried (complete)
      // frames would sit BEHIND a torn frame where replay never reaches
      // them — yet fdatasync would ack them as durable.
      off_t batch_start = ::lseek(fd, 0, SEEK_END);
      size_t off = 0;
      while (off < batch.size()) {
        ssize_t n = ::write(fd, batch.data() + off, batch.size() - off);
        if (n < 0 && errno == EINTR) continue;   // signal: retry
        if (n <= 0) break;                       // ENOSPC/EIO
        off += (size_t)n;
      }
      bool ok = off == batch.size();
      if (ok && sync_each_batch) ok = ::fdatasync(fd) == 0;
      if (!ok && batch_start >= 0) {
        // Cut the torn bytes so a successful retry appends at a frame
        // boundary.  If even the truncate fails, record the tear: the
        // loop above refuses to write anything until it is cut, so no
        // later frame can land behind it and be falsely acked.
        if (::ftruncate(fd, batch_start) != 0) tear_at = batch_start;
      }
      lk.lock();
      if (ok) {
        durable_seq = seq;
        cv_done.notify_all();
        if (stop && pending.empty()) return;
      } else {
        // Failed batch: REQUEUE at the front (order preserved) and never
        // advance durable_seq — a later success must not claim these
        // frames were synced.  Back off to avoid hot-spinning on a
        // persistent error.
        pending.insert(pending.begin(), batch.begin(), batch.end());
        if (stop) return;   // shutting down: give up, waiters time out
        cv_work.wait_for(lk, std::chrono::milliseconds(50),
                         [&] { return stop; });
      }
    }
  }
};

}  // namespace

extern "C" {

void* jrn_open(const char* path, int sync_each_batch) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  auto* j = new Journal();
  j->fd = fd;
  j->sync_each_batch = sync_each_batch != 0;
  j->flusher = std::thread([j] { j->run(); });
  return j;
}

int jrn_append(void* h, const uint8_t* data, uint32_t len) {
  auto* j = static_cast<Journal*>(h);
  uint32_t crc = crc32(data, len);
  std::lock_guard<std::mutex> lk(j->mu);
  size_t base = j->pending.size();
  j->pending.resize(base + 8 + len);
  memcpy(j->pending.data() + base, &len, 4);
  memcpy(j->pending.data() + base + 4, &crc, 4);
  memcpy(j->pending.data() + base + 8, data, len);
  j->enqueued_seq++;
  j->cv_work.notify_one();
  return 0;
}

// Block until everything appended so far is durable.  Returns 0 on
// success, -1 on timeout (5 s — disk stall / write error).
int jrn_flush(void* h) {
  auto* j = static_cast<Journal*>(h);
  std::unique_lock<std::mutex> lk(j->mu);
  uint64_t want = j->enqueued_seq;
  j->cv_work.notify_one();
  bool ok = j->cv_done.wait_for(lk, std::chrono::seconds(5), [&] {
    return j->durable_seq >= want;
  });
  return ok ? 0 : -1;
}

void jrn_close(void* h) {
  auto* j = static_cast<Journal*>(h);
  {
    std::lock_guard<std::mutex> lk(j->mu);
    j->stop = true;
    j->cv_work.notify_one();
  }
  j->flusher.join();
  ::close(j->fd);
  delete j;
}

// Replay valid frames through cb; returns frame count, or -1 if the
// file can't be opened.  Stops cleanly at a torn/corrupt tail.
typedef void (*jrn_cb)(const uint8_t*, uint32_t);

long jrn_replay(const char* path, jrn_cb cb) {
  FILE* f = ::fopen(path, "rb");
  if (!f) return -1;
  long count = 0;
  std::vector<uint8_t> buf;
  for (;;) {
    uint32_t hdr[2];
    if (fread(hdr, 1, 8, f) != 8) break;
    uint32_t len = hdr[0], crc = hdr[1];
    if (len > (1u << 30)) break;          // implausible: corrupt header
    buf.resize(len);
    if (fread(buf.data(), 1, len, f) != len) break;   // torn tail
    if (crc32(buf.data(), len) != crc) break;         // corrupt frame
    cb(buf.data(), len);
    count++;
  }
  fclose(f);
  return count;
}

}  // extern "C"
