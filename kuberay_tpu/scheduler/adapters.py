"""External gang-scheduler adapters.

Each stamps exactly the metadata shape its scheduler consumes, following
the reference's plugin set (SURVEY.md §2.1):

- Volcano (volcano_scheduler.go:48-120): volcano PodGroup CR + pod
  annotations ``scheduling.k8s.io/group-name`` + queue, schedulerName.
- YuniKorn (yunikorn_scheduler.go:41 + task groups): app-id/queue labels +
  ``yunikorn.apache.org/task-groups`` JSON annotation; gang via placeholder
  pods is YuniKorn-side.
- KAI (kai_scheduler.go:38-69): schedulerName + ``kai.scheduler/queue``
  label; rejects K8sJobMode (gang deadlock, :47).
- scheduler-plugins (scheduler_plugins.go:48-88):
  ``scheduling.x-k8s.io/v1alpha1`` PodGroup + pod-group label.
"""

from __future__ import annotations

import json
from typing import Any, Dict

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.controlplane.store import NotFound, ObjectStore
from kuberay_tpu.scheduler.interface import total_cluster_demand
from kuberay_tpu.utils import constants as C


class VolcanoAdapter:
    name = "volcano"
    POD_GROUP_ANNOTATION = "scheduling.k8s.io/group-name"
    QUEUE_ANNOTATION = "scheduling.volcano.sh/queue-name"

    def __init__(self, store: ObjectStore):
        self.store = store

    def _pg_name(self, obj):
        return f"volcano-pg-{obj['metadata']['name']}"

    def on_cluster_submission(self, cluster: Dict[str, Any]) -> bool:
        demand = total_cluster_demand(cluster)
        ns = cluster["metadata"].get("namespace", "default")
        pg = {
            "apiVersion": "scheduling.volcano.sh/v1beta1",
            "kind": "PodGroup",
            "metadata": {"name": self._pg_name(cluster), "namespace": ns},
            "spec": {
                "minMember": demand["minMember"],
                "minResources": {C.RESOURCE_TPU: demand["tpuChips"]},
                "queue": cluster.get("spec", {}).get("gangSchedulingQueue", "default"),
            },
            "status": {},
        }
        self.store.ensure(pg)
        return True   # volcano admits asynchronously via the PodGroup

    def on_job_submission(self, job: Dict[str, Any]) -> bool:
        return True

    def add_metadata(self, cluster, pod) -> None:
        ann = pod["metadata"].setdefault("annotations", {})
        ann[self.POD_GROUP_ANNOTATION] = self._pg_name(cluster)
        queue = cluster.get("spec", {}).get("gangSchedulingQueue", "")
        if queue:
            ann[self.QUEUE_ANNOTATION] = queue
        pod["spec"]["schedulerName"] = "volcano"

    def cleanup(self, obj) -> None:
        try:
            self.store.delete("PodGroup", self._pg_name(obj),
                              obj["metadata"].get("namespace", "default"))
        except NotFound:
            pass


class YuniKornAdapter:
    name = "yunikorn"
    APP_ID_LABEL = "applicationId"
    QUEUE_LABEL = "queue"
    TASK_GROUPS_ANNOTATION = "yunikorn.apache.org/task-groups"
    TASK_GROUP_NAME_ANNOTATION = "yunikorn.apache.org/task-group-name"

    def __init__(self, store: ObjectStore):
        self.store = store

    def on_cluster_submission(self, cluster) -> bool:
        return True

    def on_job_submission(self, job) -> bool:
        return True

    def _task_groups(self, cluster: Dict[str, Any]) -> str:
        c = TpuCluster.from_dict(cluster)
        groups = [{"name": "head", "minMember": 1}]
        for g in c.spec.workerGroupSpecs:
            topo = g.slice_topology()
            groups.append({
                "name": f"group-{g.groupName}",
                "minMember": g.replicas * topo.num_hosts,
                "minResource": {C.RESOURCE_TPU: str(topo.chips_per_host)},
            })
        return json.dumps(groups)

    def add_metadata(self, cluster, pod) -> None:
        labels = pod["metadata"].setdefault("labels", {})
        labels[self.APP_ID_LABEL] = cluster["metadata"]["name"]
        queue = cluster.get("spec", {}).get("gangSchedulingQueue", "")
        if queue:
            labels[self.QUEUE_LABEL] = queue
        ann = pod["metadata"].setdefault("annotations", {})
        ann[self.TASK_GROUPS_ANNOTATION] = self._task_groups(cluster)
        node_type = labels.get(C.LABEL_NODE_TYPE, C.NODE_TYPE_WORKER)
        group = labels.get(C.LABEL_GROUP, "")
        ann[self.TASK_GROUP_NAME_ANNOTATION] = (
            "head" if node_type == C.NODE_TYPE_HEAD else f"group-{group}")
        pod["spec"]["schedulerName"] = "yunikorn"

    def cleanup(self, obj) -> None:
        pass


class SchedulerPluginsAdapter:
    """sigs.k8s.io scheduler-plugins coscheduling adapter (ref
    scheduler_plugins.go:31-88): a ``scheduling.x-k8s.io/v1alpha1``
    PodGroup named after the cluster (owner-referenced for GC) plus the
    ``scheduling.x-k8s.io/pod-group`` label on every pod; the
    coscheduling plugin gates binding until minMember pods exist."""

    name = "scheduler-plugins"
    POD_GROUP_LABEL = "scheduling.x-k8s.io/pod-group"

    def __init__(self, store: ObjectStore):
        self.store = store

    def _pg_name(self, obj) -> str:
        # Ref createPodGroup: the PodGroup shares the cluster's name.
        return obj["metadata"]["name"]

    def on_cluster_submission(self, cluster: Dict[str, Any]) -> bool:
        demand = total_cluster_demand(cluster)
        md = cluster["metadata"]
        ns = md.get("namespace", "default")
        pg = {
            "apiVersion": "scheduling.x-k8s.io/v1alpha1",
            "kind": "PodGroup",
            "metadata": {
                "name": self._pg_name(cluster), "namespace": ns,
                # Owner reference -> GC with the cluster (the reference
                # relies on this instead of CleanupOnCompletion).
                "ownerReferences": [{
                    "apiVersion": cluster.get("apiVersion", C.API_VERSION),
                    "kind": cluster.get("kind", C.KIND_CLUSTER),
                    "name": md["name"], "uid": md.get("uid", ""),
                }],
            },
            "spec": {
                "minMember": demand["minMember"],
                "minResources": {C.RESOURCE_TPU: demand["tpuChips"]},
            },
            "status": {},
        }
        self.store.ensure(pg)
        return True    # coscheduling admits at bind time via the PodGroup

    def on_job_submission(self, job: Dict[str, Any]) -> bool:
        return True

    def add_metadata(self, cluster, pod) -> None:
        pod["metadata"].setdefault("labels", {})[self.POD_GROUP_LABEL] = \
            self._pg_name(cluster)
        pod["spec"]["schedulerName"] = "scheduler-plugins-scheduler"

    def cleanup(self, obj) -> None:
        # Owner references handle GC; explicit delete keeps parity with
        # stores lacking cascading GC.
        try:
            self.store.delete("PodGroup", self._pg_name(obj),
                              obj["metadata"].get("namespace", "default"))
        except NotFound:
            pass


class KaiAdapter:
    name = "kai"
    QUEUE_LABEL = "kai.scheduler/queue"

    def __init__(self, store: ObjectStore):
        self.store = store

    def on_cluster_submission(self, cluster) -> bool:
        return True

    def on_job_submission(self, job: Dict[str, Any]) -> bool:
        # K8sJobMode deadlocks the gang (ref kai_scheduler.go:47): the
        # submitter Job waits for the cluster, the gang waits for all pods.
        from kuberay_tpu.api.tpujob import JobSubmissionMode
        mode = job.get("spec", {}).get("submissionMode",
                                       JobSubmissionMode.K8S_JOB)
        return mode != JobSubmissionMode.K8S_JOB

    def add_metadata(self, cluster, pod) -> None:
        # KAI requires the queue label; an unset (or empty — the
        # serialized dataclass default) queue maps to KAI's "default".
        queue = cluster.get("spec", {}).get("gangSchedulingQueue") or "default"
        pod["metadata"].setdefault("labels", {})[self.QUEUE_LABEL] = queue
        pod["spec"]["schedulerName"] = "kai-scheduler"

    def cleanup(self, obj) -> None:
        pass
