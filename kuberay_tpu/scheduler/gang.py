"""Builtin gang scheduler: PodGroup objects + quota / capacity admission.

The slice-atomic equivalent of the reference's Volcano plugin behavior
(volcano_scheduler.go syncPodGroup :155 / calculatePodGroupParams :200)
without the external dependency: a ``PodGroup`` object per TpuCluster
records the all-or-nothing quantum (minMember, TPU chips); admission asks
the hierarchical QuotaManager (``controlplane/quota.py``) when one is
mounted, else the legacy pluggable capacity oracle, so tests (and finite
fleets) stay modelable.  Pods are stamped with the pod-group annotation so
any PodGroup-aware kube scheduler can enforce the gang.

Every verdict is written back to the PodGroup ``status`` (phase, denial
reason, first-admission timestamp) and counted in
``tpu_gang_admission_total{verdict}`` — the observability evidence for
the controllers' hold-off requeue path (analysis rule #6).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from kuberay_tpu.controlplane.quota import (QuotaVerdict, build_demand,
                                            job_pseudo_cluster)
from kuberay_tpu.controlplane.store import Conflict, NotFound, ObjectStore
from kuberay_tpu.builders.common import owner_reference
from kuberay_tpu.utils import constants as C

ANNOTATION_POD_GROUP = "tpu.dev/pod-group"
LABEL_QUEUE = "tpu.dev/queue"

PHASE_ADMITTED = "Admitted"
PHASE_PENDING = "Pending"


class GangScheduler:
    name = "gang"

    def __init__(self, store: ObjectStore,
                 capacity_oracle: Optional[Callable[[Dict[str, Any]],
                                                    Any]] = None,
                 quota=None, metrics=None,
                 clock: Callable[[], float] = time.time):
        self.store = store
        # Admission order: quota manager (the capacity oracle for
        # multi-tenant fleets) > legacy oracle(demand) -> bool > admit-all.
        self.quota = quota
        self.capacity_oracle = capacity_oracle
        self.metrics = metrics
        self._clock = clock

    def _pod_group_name(self, obj: Dict[str, Any]) -> str:
        return f"pg-{obj['metadata']['name']}"

    def _sync_pod_group(self, cluster: Dict[str, Any],
                        demand: Dict[str, Any]) -> None:
        ns = cluster["metadata"].get("namespace", "default")
        name = self._pod_group_name(cluster)
        queue = cluster.get("spec", {}).get("gangSchedulingQueue", "")
        pg = {
            "apiVersion": C.API_VERSION,
            "kind": "PodGroup",
            "metadata": {
                "name": name, "namespace": ns,
                "labels": ({LABEL_QUEUE: queue} if queue else {}),
                "ownerReferences": [owner_reference(
                    cluster.get("kind", C.KIND_CLUSTER),
                    cluster["metadata"]["name"],
                    cluster["metadata"].get("uid", ""))],
            },
            "spec": {
                "minMember": demand["minMember"],
                "minResources": {C.RESOURCE_TPU: demand["tpuChips"]},
            },
            "status": {},
        }
        self.store.ensure(pg)

    def _evaluate(self, demand: Dict[str, Any]) -> QuotaVerdict:
        if self.quota is not None:
            return self.quota.admit(demand)
        if self.capacity_oracle is not None:
            verdict = self.capacity_oracle(demand)
            if isinstance(verdict, QuotaVerdict):
                return verdict
            return QuotaVerdict(bool(verdict),
                                reason="capacity-oracle"
                                if verdict else "capacity-hold")
        return QuotaVerdict(True, reason="unconstrained")

    def _conclude(self, obj: Dict[str, Any],
                  verdict: QuotaVerdict) -> QuotaVerdict:
        """Record the verdict where operators can see it: the PodGroup
        status (phase / reason / first-admission timestamp) and the
        ``tpu_gang_admission_total{verdict}`` counter."""
        if self.metrics is not None:
            self.metrics.gang_admission(
                "admitted" if verdict.admitted else "denied")
        ns = obj["metadata"].get("namespace", "default")
        name = self._pod_group_name(obj)
        pg = self.store.try_get("PodGroup", name, ns)
        if pg is None:
            return verdict
        status = pg.get("status", {}) or {}
        phase = PHASE_ADMITTED if verdict.admitted else PHASE_PENDING
        want = {"phase": phase, "reason": verdict.reason}
        if verdict.admitted and not status.get("admittedAt"):
            want["admittedAt"] = round(self._clock(), 3)
        unchanged = all(status.get(k) == v for k, v in want.items())
        if not unchanged:
            try:
                self.store.patch("PodGroup", name, ns, {"status": want},
                                 subresource="status")
            except (NotFound, Conflict):
                # The group raced away or a concurrent writer won; the
                # next level-triggered admission pass re-stamps it.
                pass
        return verdict

    def on_cluster_submission(self, cluster: Dict[str, Any]) -> QuotaVerdict:
        demand = build_demand(cluster)
        self._sync_pod_group(cluster, demand)
        return self._conclude(cluster, self._evaluate(demand))

    def on_job_submission(self, job: Dict[str, Any]) -> QuotaVerdict:
        # Job-level quota identity wins over what the embedded cluster
        # spec carries (mirrors the controller's spec forwarding).
        pseudo = job_pseudo_cluster(job)
        if pseudo is None:
            return QuotaVerdict(True, reason="no-cluster-spec")
        demand = build_demand(pseudo)
        self._sync_pod_group(pseudo, demand)
        return self._conclude(pseudo, self._evaluate(demand))

    def add_metadata(self, cluster: Dict[str, Any], pod: Dict[str, Any]) -> None:
        pod["metadata"].setdefault("annotations", {})[ANNOTATION_POD_GROUP] = \
            self._pod_group_name(cluster)
        queue = cluster.get("spec", {}).get("gangSchedulingQueue", "")
        if queue:
            pod["metadata"].setdefault("labels", {})[LABEL_QUEUE] = queue

    def cleanup(self, obj: Dict[str, Any]) -> None:
        ns = obj["metadata"].get("namespace", "default")
        try:
            self.store.delete("PodGroup", self._pod_group_name(obj), ns)
        except NotFound:
            pass
        if self.quota is not None:
            self.quota.release(obj)
