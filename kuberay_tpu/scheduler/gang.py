"""Builtin gang scheduler: PodGroup objects + optional capacity oracle.

The slice-atomic equivalent of the reference's Volcano plugin behavior
(volcano_scheduler.go syncPodGroup :155 / calculatePodGroupParams :200)
without the external dependency: a ``PodGroup`` object per TpuCluster
records the all-or-nothing quantum (minMember, TPU chips); admission asks a
pluggable capacity oracle so tests (and a future quota manager) can model
finite fleets.  Pods are stamped with the pod-group annotation so any
PodGroup-aware kube scheduler can enforce the gang.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from kuberay_tpu.controlplane.store import NotFound, ObjectStore
from kuberay_tpu.builders.common import owner_reference
from kuberay_tpu.scheduler.interface import total_cluster_demand
from kuberay_tpu.utils import constants as C

ANNOTATION_POD_GROUP = "tpu.dev/pod-group"
LABEL_QUEUE = "tpu.dev/queue"


class GangScheduler:
    name = "gang"

    def __init__(self, store: ObjectStore,
                 capacity_oracle: Optional[Callable[[Dict[str, Any]], bool]] = None):
        self.store = store
        # oracle(demand) -> True when the fleet can host the whole gang now.
        self.capacity_oracle = capacity_oracle

    def _pod_group_name(self, obj: Dict[str, Any]) -> str:
        return f"pg-{obj['metadata']['name']}"

    def _sync_pod_group(self, cluster: Dict[str, Any]) -> Dict[str, Any]:
        demand = total_cluster_demand(cluster)
        ns = cluster["metadata"].get("namespace", "default")
        name = self._pod_group_name(cluster)
        queue = cluster.get("spec", {}).get("gangSchedulingQueue", "")
        pg = {
            "apiVersion": C.API_VERSION,
            "kind": "PodGroup",
            "metadata": {
                "name": name, "namespace": ns,
                "labels": ({LABEL_QUEUE: queue} if queue else {}),
                "ownerReferences": [owner_reference(
                    cluster.get("kind", C.KIND_CLUSTER),
                    cluster["metadata"]["name"],
                    cluster["metadata"].get("uid", ""))],
            },
            "spec": {
                "minMember": demand["minMember"],
                "minResources": {C.RESOURCE_TPU: demand["tpuChips"]},
            },
            "status": {},
        }
        self.store.ensure(pg)
        return demand

    def on_cluster_submission(self, cluster: Dict[str, Any]) -> bool:
        demand = self._sync_pod_group(cluster)
        if self.capacity_oracle is not None:
            return self.capacity_oracle(demand)
        return True

    def on_job_submission(self, job: Dict[str, Any]) -> bool:
        spec = job.get("spec", {}).get("clusterSpec")
        if not spec:
            return True
        pseudo = {"metadata": job["metadata"], "kind": C.KIND_JOB,
                  "spec": spec}
        demand = total_cluster_demand(pseudo)
        self._sync_pod_group(pseudo)
        if self.capacity_oracle is not None:
            return self.capacity_oracle(demand)
        return True

    def add_metadata(self, cluster: Dict[str, Any], pod: Dict[str, Any]) -> None:
        pod["metadata"].setdefault("annotations", {})[ANNOTATION_POD_GROUP] = \
            self._pod_group_name(cluster)
        queue = cluster.get("spec", {}).get("gangSchedulingQueue", "")
        if queue:
            pod["metadata"].setdefault("labels", {})[LABEL_QUEUE] = queue

    def cleanup(self, obj: Dict[str, Any]) -> None:
        ns = obj["metadata"].get("namespace", "default")
        try:
            self.store.delete("PodGroup", self._pod_group_name(obj), ns)
        except NotFound:
            pass
