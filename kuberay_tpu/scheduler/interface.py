"""Gang-scheduling plugin framework (ref batchscheduler/interface/interface.go:14-47).

A half-scheduled slice has no working ICI ring, so all-or-nothing admission
is core — the builtin gang plugin is always available (not plugin-optional
like the reference, SURVEY.md §7.3); Volcano/YuniKorn/KAI adapters stamp
the metadata those external schedulers consume.

Interface (mirrors DoBatchSchedulingOnSubmission / AddMetadataToChildResource
/ CleanupOnCompletion):
- ``on_cluster_submission(cluster) -> bool``: reserve capacity for the whole
  cluster before any pod exists; False = hold off (requeue).
- ``on_job_submission(job) -> bool``: same, at job granularity.
- ``add_metadata(cluster, pod)``: stamp scheduler-specific labels/annotations.
- ``cleanup(obj)``: release reservations when the CR finishes/deletes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Protocol


class BatchScheduler(Protocol):
    name: str

    def on_cluster_submission(self, cluster: Dict[str, Any]) -> bool: ...
    def on_job_submission(self, job: Dict[str, Any]) -> bool: ...
    def add_metadata(self, cluster: Dict[str, Any], pod: Dict[str, Any]) -> None: ...
    def cleanup(self, obj: Dict[str, Any]) -> None: ...


class SchedulerManager:
    """Selects the configured plugin (ref schedulermanager.go:21)."""

    def __init__(self):
        self._plugins: Dict[str, BatchScheduler] = {}

    def register(self, plugin: BatchScheduler):
        self._plugins[plugin.name] = plugin

    def get(self, name: str) -> Optional[BatchScheduler]:
        if not name:
            return None
        plugin = self._plugins.get(name)
        if plugin is None:
            raise KeyError(
                f"unknown batch scheduler {name!r}; registered: "
                f"{sorted(self._plugins)}")
        return plugin


def total_cluster_demand(cluster: Dict[str, Any]) -> Dict[str, Any]:
    """Pods + TPU chips the whole cluster needs (gang quantum).

    The submitter pod is intentionally excluded, mirroring the reference's
    deadlock avoidance (volcano_scheduler.go:48-120: submitter excluded from
    MinMember so the gang doesn't wait on a pod that waits on the gang).
    """
    from kuberay_tpu.api.tpucluster import TpuCluster

    c = TpuCluster.from_dict(cluster)
    pods = 1  # head
    chips = 0
    for g in c.spec.workerGroupSpecs:
        topo = g.slice_topology()
        pods += g.replicas * topo.num_hosts
        chips += g.replicas * topo.num_chips
    return {"minMember": pods, "tpuChips": chips}
