"""Service builders (ref controllers/ray/common/service.go).

- head service (:37): stable coordinator/dashboard address, selector on
  head labels;
- headless service (:299): peer DNS for multi-host slices, created only
  when a group is multi-host, publishes not-ready addresses so workers can
  resolve each other before readiness (exactly the reference's flag);
- serve service: selects pods with the serve label for inference traffic.
"""

from __future__ import annotations

from typing import Any, Dict

from kuberay_tpu.builders.common import cluster_owner_reference
from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.names import (
    head_service_name,
    headless_service_name,
    serve_service_name,
)


def build_head_service(cluster: TpuCluster) -> Dict[str, Any]:
    name = cluster.metadata.name
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": head_service_name(name),
            "namespace": cluster.metadata.namespace,
            "labels": {C.LABEL_CLUSTER: name,
                       C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD},
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "spec": {
            "type": cluster.spec.headGroupSpec.serviceType,
            "selector": {C.LABEL_CLUSTER: name,
                         C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD},
            "ports": [
                {"name": C.DEFAULT_COORDINATOR_PORT_NAME, "port": C.PORT_COORDINATOR},
                {"name": C.DEFAULT_DASHBOARD_PORT_NAME, "port": C.PORT_DASHBOARD},
                {"name": C.DEFAULT_METRICS_PORT_NAME, "port": C.PORT_METRICS},
                {"name": C.DEFAULT_SERVE_PORT_NAME, "port": C.PORT_SERVE},
            ],
        },
    }


def needs_headless_service(cluster: TpuCluster) -> bool:
    """Only when some group is multi-host (ref raycluster_controller.go:869)."""
    return any(g.slice_topology().is_multi_host
               for g in cluster.spec.workerGroupSpecs)


def build_headless_service(cluster: TpuCluster) -> Dict[str, Any]:
    name = cluster.metadata.name
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": headless_service_name(name),
            "namespace": cluster.metadata.namespace,
            "labels": {C.LABEL_CLUSTER: name},
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "spec": {
            "clusterIP": "None",
            # Workers must resolve peers before they are Ready — the ICI
            # bootstrap happens pre-readiness (ref PublishNotReadyAddresses).
            "publishNotReadyAddresses": True,
            "selector": {C.LABEL_CLUSTER: name,
                         C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER},
            "ports": [
                {"name": C.DEFAULT_COORDINATOR_PORT_NAME, "port": C.PORT_COORDINATOR},
                {"name": "mxla", "port": C.PORT_MXLA},
            ],
        },
    }


def build_serve_service(cluster: TpuCluster,
                        service_name: str = "") -> Dict[str, Any]:
    """Serve traffic service; selector includes the serve label so only
    pods marked ready-for-traffic receive requests (ref serve svc +
    updateHeadPodServeLabel rayservice_controller.go:2065)."""
    name = cluster.metadata.name
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": service_name or serve_service_name(name),
            "namespace": cluster.metadata.namespace,
            "labels": {C.LABEL_CLUSTER: name},
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "spec": {
            "type": "ClusterIP",
            "selector": {C.LABEL_CLUSTER: name, C.LABEL_SERVE: "true"},
            "ports": [{"name": C.DEFAULT_SERVE_PORT_NAME, "port": C.PORT_SERVE}],
        },
    }
