"""Submitter builders for TpuJob (ref controllers/ray/common/job.go).

The submitter is a K8s Job that launches the user's entrypoint against the
cluster coordinator.  The command wrapper is idempotent like the
reference's (job.go:120-125 ``ray job submit --no-wait || ray job logs``):
if a prior attempt already registered the job id with the coordinator, it
re-attaches instead of double-submitting.
"""

from __future__ import annotations

import shlex
from typing import Any, Dict

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.api.tpujob import TpuJob
from kuberay_tpu.builders.common import owner_reference
from kuberay_tpu.builders.pod import coordinator_address
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.names import submitter_job_name


def build_submit_command(job: TpuJob, cluster: TpuCluster) -> str:
    """Idempotent submit wrapper (ref BuildJobSubmitCommand job.go:120-125):
    a duplicate-submission error (job id already registered after a
    submitter retry) is tolerated, then the attach/tail command's exit code
    carries the job outcome either way."""
    addr = coordinator_address(cluster)
    jid = job.status.jobId or job.metadata.name
    submit = (f"python -m kuberay_tpu.runtime.submit --address {addr} "
              f"--job-id {shlex.quote(jid)} --no-wait -- "
              f"{job.spec.entrypoint}")
    attach = (f"python -m kuberay_tpu.runtime.submit --address {addr} "
              f"--job-id {shlex.quote(jid)} --tail-logs")
    return f"({submit} || echo 'submit skipped: already submitted') && exec {attach}"


def build_sidecar_submitter_container(job: TpuJob,
                                      head_image: str) -> Dict[str, Any]:
    """SidecarMode: the submitter container the job controller injects
    into the head pod template of the cluster it creates (ref
    ``common/job.go:95-158`` — submitter rides the head pod, talks to the
    coordinator over localhost, and its terminal container state is the
    job outcome signal the controller watches).

    No shell `|| attach` wrapper here: the submit tool itself waits for
    the colocated coordinator to come up and is idempotent on re-submit
    after a container restart.
    """
    jid = job.status.jobId or job.metadata.name
    addr = f"127.0.0.1:{C.PORT_DASHBOARD}"
    tmpl = (job.spec.submitterConfig.template.to_dict()
            if job.spec.submitterConfig.template else None)
    image = head_image
    if tmpl and (tmpl.get("spec") or {}).get("containers"):
        image = tmpl["spec"]["containers"][0].get("image") or head_image
    submit = (f"python -m kuberay_tpu.runtime.submit --address {addr} "
              f"--job-id {shlex.quote(jid)} --wait-for-coordinator 300 "
              f"--tail-logs -- {job.spec.entrypoint}")
    container = {
        "name": C.SUBMITTER_CONTAINER_NAME,
        "image": image,
        "command": ["/bin/sh", "-c", submit],
        # No container-level restartPolicy: K8s only allows that field on
        # init containers (value "Always").  Termination observability
        # comes from the POD-level restartPolicy "Never" the job
        # controller sets on the head template in SidecarMode — the
        # reference's exact mechanism (rayjob_controller.go:1035).
        "env": [{"name": C.ENV_COORDINATOR_ADDRESS, "value": addr}],
    }
    for k, v in (job.spec.runtimeEnv or {}).items():
        container["env"].append({"name": k, "value": str(v)})
    return container


def build_submitter_job(job: TpuJob, cluster: TpuCluster) -> Dict[str, Any]:
    """K8s Job wrapping the submitter pod (ref createK8sJobIfNeed
    rayjob_controller.go:560)."""
    tmpl = (job.spec.submitterConfig.template.to_dict()
            if job.spec.submitterConfig.template else None)
    image = ""
    if cluster.spec.headGroupSpec.template.spec.containers:
        image = cluster.spec.headGroupSpec.template.spec.containers[0].image
    pod_spec = (tmpl or {}).get("spec") or {
        "containers": [{"name": "submitter", "image": image}],
        "restartPolicy": "Never",
    }
    container = pod_spec["containers"][0]
    container["command"] = ["/bin/sh", "-c", build_submit_command(job, cluster)]
    env = container.setdefault("env", [])
    env.append({"name": C.ENV_COORDINATOR_ADDRESS,
                "value": coordinator_address(cluster)})
    from kuberay_tpu.builders.auth import maybe_add_auth_env
    maybe_add_auth_env(container, cluster)
    for k, v in (job.spec.runtimeEnv or {}).items():
        env.append({"name": k, "value": str(v)})
    pod_spec.setdefault("restartPolicy", "Never")

    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": submitter_job_name(job.metadata.name),
            "namespace": job.metadata.namespace,
            "labels": {
                C.LABEL_ORIGINATED_FROM_CR_NAME: job.metadata.name,
                C.LABEL_ORIGINATED_FROM_CRD: C.KIND_JOB,
                # Scoped informer contract (managercache/cache.go:18):
                # the operator only watches Jobs it created.
                C.LABEL_CREATED_BY: C.CREATED_BY_OPERATOR,
            },
            "ownerReferences": [owner_reference(
                C.KIND_JOB, job.metadata.name, job.metadata.uid)],
        },
        "spec": {
            "backoffLimit": job.spec.submitterConfig.backoffLimit,
            "template": {"spec": pod_spec},
        },
        "status": {},
    }
