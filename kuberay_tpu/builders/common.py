"""Shared builder helpers."""

from __future__ import annotations

from typing import Any, Dict

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.utils import constants as C


def cluster_owner_reference(cluster: TpuCluster) -> Dict[str, Any]:
    """Controller ownerReference pointing at the TpuCluster (drives
    cascading GC of pods/services on cluster deletion)."""
    return {
        "apiVersion": C.API_VERSION,
        "kind": C.KIND_CLUSTER,
        "name": cluster.metadata.name,
        "uid": cluster.metadata.uid,
        "controller": True,
        "blockOwnerDeletion": True,
    }
