"""Shared builder helpers."""

from __future__ import annotations

from typing import Any, Dict

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.utils import constants as C


def owner_reference(kind: str, name: str, uid: str) -> Dict[str, Any]:
    """Controller ownerReference (drives cascading GC on owner deletion)."""
    return {
        "apiVersion": C.API_VERSION,
        "kind": kind,
        "name": name,
        "uid": uid,
        "controller": True,
        "blockOwnerDeletion": True,
    }


def cluster_owner_reference(cluster: TpuCluster) -> Dict[str, Any]:
    return owner_reference(C.KIND_CLUSTER, cluster.metadata.name,
                           cluster.metadata.uid)


def attach_cluster_auth(client, store, cluster) -> None:
    """Decorate a coordinator client with the cluster's auth token (the
    operator authenticates with the same secret the pods consume)."""
    if not getattr(cluster.spec, "enableTokenAuth", False):
        return
    if not hasattr(client, "auth_token"):
        return
    from kuberay_tpu.builders.auth import read_auth_token
    token = read_auth_token(store, cluster.metadata.name,
                            cluster.metadata.namespace)
    if token:
        client.auth_token = token
