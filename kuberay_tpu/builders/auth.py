"""Auth secret builder (ref the operator-managed auth secret consumed by
e2e raycluster_auth_test.go): a per-cluster bearer token minted once,
projected into every container via a secretKeyRef env, enforced by the
coordinator API."""

from __future__ import annotations

import secrets
from typing import Any, Dict

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.builders.common import cluster_owner_reference
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.names import truncate_name

ENV_AUTH_TOKEN = "TPU_AUTH_TOKEN"


def auth_secret_name(cluster_name: str) -> str:
    return truncate_name(f"{cluster_name}-auth")


def build_auth_secret(cluster: TpuCluster) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {
            "name": auth_secret_name(cluster.metadata.name),
            "namespace": cluster.metadata.namespace,
            "labels": {C.LABEL_CLUSTER: cluster.metadata.name},
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "type": "Opaque",
        # stringData: raw value (a real apiserver base64-encodes it into
        # data; raw strings in `data` are rejected as illegal base64).
        # kuberay-lint: disable-next-line=sim-determinism -- the auth token is a cryptographic credential and MUST come from os entropy; sim scenarios never assert on secret bytes
        "stringData": {"token": secrets.token_urlsafe(32)},
    }


def auth_env_entry(cluster_name: str) -> Dict[str, Any]:
    """K8s-shaped env var sourcing the token from the secret."""
    return {
        "name": ENV_AUTH_TOKEN,
        "valueFrom": {"secretKeyRef": {
            "name": auth_secret_name(cluster_name), "key": "token"}},
    }


def maybe_add_auth_env(container: dict, cluster) -> None:
    """Append the secretKeyRef env once, iff the cluster enables auth —
    the single injection path for head/worker/submitter containers."""
    if not getattr(cluster.spec, "enableTokenAuth", False):
        return
    env = container.setdefault("env", [])
    if ENV_AUTH_TOKEN not in {e.get("name") for e in env}:
        env.append(auth_env_entry(cluster.metadata.name))


def read_auth_token(store, cluster_name: str, namespace: str) -> str:
    """Operator-side token lookup (controllers authenticate to the
    coordinator with the same secret the pods consume)."""
    secret = store.try_get("Secret", auth_secret_name(cluster_name), namespace)
    if secret is None:
        return ""
    return (secret.get("stringData", {}).get("token")
            or secret.get("data", {}).get("token", ""))
