"""Pod builders: TpuCluster spec -> pod objects (pure functions).

The TPU-native union of the reference's ``BuildPod``/``DefaultWorkerPodTemplate``
(controllers/ray/common/pod.go:414,639 — env wiring, probes, multi-host
labels at :493-500) and what GKE's external TPU webhook injects today
(SURVEY.md §5.7): ``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``,
``TPU_TOPOLOGY``, node selectors, megascale (multi-slice DCN) coordination
env.  Injection is native here — no webhook in the loop.

Pure: no store access, no clock; fully unit-testable like the reference's
common/ package.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from kuberay_tpu.builders.common import cluster_owner_reference
from kuberay_tpu.api.tpucluster import TpuCluster, WorkerGroupSpec
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.names import (
    head_pod_name,
    head_service_name,
    headless_service_name,
    slice_name,
    worker_pod_name,
)


def _base_labels(cluster: TpuCluster, node_type: str) -> Dict[str, str]:
    return {
        C.LABEL_CLUSTER: cluster.metadata.name,
        C.LABEL_NODE_TYPE: node_type,
        C.LABEL_IDENTIFIER: f"{cluster.metadata.name}-{node_type}",
        C.LABEL_CREATED_BY: C.CREATED_BY_OPERATOR,
    }


def _set_env(container: Dict[str, Any], env: Dict[str, str]) -> None:
    """Add env vars, user-provided values win (ref setContainerEnvVars)."""
    existing = {e["name"] for e in container.setdefault("env", [])}
    for k, v in env.items():
        if k not in existing:
            container["env"].append({"name": k, "value": v})


def _probes_enabled() -> bool:
    """Ref getEnableProbesInjection (pod.go:406): on unless the env
    knob opts out."""
    import os
    return os.environ.get("ENABLE_PROBES_INJECTION",
                          "true").lower() not in ("false", "0")


def _inject_probes(container: Dict[str, Any], node_type: str,
                   originated_from_crd: str = "",
                   host_idx: int = 0) -> None:
    """Readiness/liveness probes (ref initLivenessAndReadinessProbe
    pod.go:539): user-set probes always win.

    - head: HTTP GET /api/healthz on the coordinator's dashboard port
      (the GCS-health analogue — the coordinator IS our GCS role);
    - worker: exec probe reaching the head's healthz over the injected
      TPU_COORDINATOR_ADDRESS (``ray health-check`` analogue: healthy =
      connected to the head);
    - serve workers (TpuService-owned): readiness ALSO requires the
      local serve server's /healthz, which returns 503 once the lockstep
      group degrades — the kubelet-visible half of whole-slice
      replacement (serve/group_health.py).
    """
    if not _probes_enabled():
        return
    if node_type == C.NODE_TYPE_HEAD:
        action = {"httpGet": {"path": "/api/healthz",
                              "port": C.PORT_DASHBOARD}}
        ready = {**action}
    else:
        check_head = (
            "python -c \"import urllib.request,os;"
            "h=os.environ['TPU_COORDINATOR_ADDRESS'].split(':')[0];"
            f"urllib.request.urlopen(f'http://{{h}}:{C.PORT_DASHBOARD}"
            "/api/healthz', timeout=3)\"")
        action = {"exec": {"command": ["sh", "-c", check_head]}}
        ready = {**action}
        # Only host 0 of a serve slice runs the HTTP frontend
        # (serve/server.py: followers replay collectives and serve
        # nothing locally) — probing PORT_SERVE on a follower would pin
        # it NotReady forever.
        if originated_from_crd == C.KIND_SERVICE and host_idx == 0:
            check_serve = (
                "python -c \"import urllib.request;"
                f"urllib.request.urlopen('http://localhost:{C.PORT_SERVE}"
                "/healthz', timeout=3)\"")
            ready = {"exec": {"command": [
                "sh", "-c", f"{check_head} && {check_serve}"]}}
    container.setdefault("livenessProbe", {
        **action, "initialDelaySeconds": 30, "periodSeconds": 5,
        "timeoutSeconds": 5, "failureThreshold": 120})
    container.setdefault("readinessProbe", {
        **ready, "initialDelaySeconds": 10, "periodSeconds": 5,
        "timeoutSeconds": 5, "failureThreshold": 10})


def coordinator_address(cluster: TpuCluster) -> str:
    ns = cluster.metadata.namespace
    return (f"{head_service_name(cluster.metadata.name)}.{ns}.svc:"
            f"{C.PORT_COORDINATOR}")


def slice_hostnames(cluster: TpuCluster, group: WorkerGroupSpec,
                    slice_idx: int) -> List[str]:
    """Stable per-host DNS names via the headless service (ref
    BuildHeadlessServiceForRayCluster service.go:299 peer DNS)."""
    topo = group.slice_topology()
    svc = headless_service_name(cluster.metadata.name)
    ns = cluster.metadata.namespace
    return [
        f"{worker_pod_name(cluster.metadata.name, group.groupName, slice_idx, h)}"
        f".{svc}.{ns}.svc"
        for h in range(topo.num_hosts)
    ]


def build_head_pod(cluster: TpuCluster,
                   config_env: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Head pod: coordinator + dashboard + (optional) autoscaler sidecar."""
    name = cluster.metadata.name
    tmpl = cluster.spec.headGroupSpec.template.to_dict()
    pod_spec = copy.deepcopy(tmpl.get("spec", {}))
    containers = pod_spec.setdefault("containers", [{}])
    head = containers[0]
    head.setdefault("name", "tpu-head")

    env = {
        C.ENV_CLUSTER_NAME: name,
        C.ENV_COORDINATOR_ADDRESS: coordinator_address(cluster),
        C.ENV_FQ_HEAD_IP: f"{head_service_name(name)}.{cluster.metadata.namespace}.svc",
        C.ENV_NUM_PROCESSES: "1",
        C.ENV_PROCESS_ID: "0",
    }
    if cluster.spec.headStateOptions is not None:
        hso = cluster.spec.headStateOptions
        if hso.backend == "external":
            env["TPU_HEAD_EXTERNAL_STORAGE_ADDRESS"] = hso.externalStorageAddress
            env["TPU_HEAD_EXTERNAL_STORAGE_NAMESPACE"] = (
                hso.externalStorageNamespace or cluster.metadata.uid)
    _set_env(head, {**(config_env or {}), **env})
    from kuberay_tpu.builders.auth import maybe_add_auth_env
    maybe_add_auth_env(head, cluster)

    ports = {p.get("name") for p in head.setdefault("ports", [])}
    for pname, pnum in [
        (C.DEFAULT_COORDINATOR_PORT_NAME, C.PORT_COORDINATOR),
        (C.DEFAULT_DASHBOARD_PORT_NAME, C.PORT_DASHBOARD),
        (C.DEFAULT_METRICS_PORT_NAME, C.PORT_METRICS),
        (C.DEFAULT_SERVE_PORT_NAME, C.PORT_SERVE),
    ]:
        if pname not in ports:
            head["ports"].append({"name": pname, "containerPort": pnum})

    if cluster.spec.enableInTreeAutoscaling:
        containers.append(build_autoscaler_container(cluster))

    if cluster.spec.schedulerName and not pod_spec.get("schedulerName"):
        pod_spec["schedulerName"] = cluster.spec.schedulerName

    _inject_probes(head, C.NODE_TYPE_HEAD)

    labels = {**tmpl.get("metadata", {}).get("labels", {}),
              **_base_labels(cluster, C.NODE_TYPE_HEAD)}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": head_pod_name(name),
            "namespace": cluster.metadata.namespace,
            "labels": labels,
            "annotations": dict(tmpl.get("metadata", {}).get("annotations", {})),
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "spec": pod_spec,
    }


def build_autoscaler_container(cluster: TpuCluster) -> Dict[str, Any]:
    """Autoscaler sidecar (ref BuildAutoscalerContainer common/pod.go:736):
    watches job/queue state and patches worker-group replicas in slice
    units."""
    opts = cluster.spec.autoscalerOptions
    image = (opts.image if opts and opts.image
             else cluster.spec.headGroupSpec.template.spec.containers[0].image
             if cluster.spec.headGroupSpec.template.spec.containers else "")
    return {
        "name": "autoscaler",
        "image": image,
        "command": ["python", "-m", "kuberay_tpu.autoscaler.sidecar"],
        "args": ["--cluster", cluster.metadata.name,
                 "--namespace", cluster.metadata.namespace],
        "env": [{"name": "TPU_AUTOSCALER_IDLE_TIMEOUT",
                 "value": str(opts.idleTimeoutSeconds if opts else 60)},
                {"name": "TPU_AUTOSCALER_MODE",
                 "value": (opts.upscalingMode if opts else "Default")}],
    }


def build_worker_pod(cluster: TpuCluster, group: WorkerGroupSpec,
                     slice_idx: int, host_idx: int,
                     config_env: Optional[Dict[str, str]] = None,
                     num_slices_in_job: int = 1,
                     megascale_slice_id: int = 0) -> Dict[str, Any]:
    """One host of one slice.

    Identity model (TPU-native version of ref pod.go:493-500 labels):
    - labels: slice-name / slice-index / host-index (atomicity bookkeeping)
    - env: TPU_WORKER_ID = host_idx, TPU_WORKER_HOSTNAMES = all peers in
      ring order via headless DNS, TPU_TOPOLOGY, coordinator address;
      megascale env for multi-slice (DCN) jobs.
    """
    name = cluster.metadata.name
    topo = group.slice_topology()
    tmpl = group.template.to_dict()
    pod_spec = copy.deepcopy(tmpl.get("spec", {}))
    containers = pod_spec.setdefault("containers", [{}])
    worker = containers[0]
    worker.setdefault("name", "tpu-worker")

    sname = slice_name(name, group.groupName, slice_idx)
    pod_name = worker_pod_name(name, group.groupName, slice_idx, host_idx)

    # TPU resource request (ref addWellKnownAcceleratorResources pod.go:1106
    # maps accelerators; here google.com/tpu is first-class).
    res = worker.setdefault("resources", {})
    for kind in ("requests", "limits"):
        res.setdefault(kind, {})
        res[kind].setdefault(C.RESOURCE_TPU, str(topo.chips_per_host))

    env = {
        C.ENV_CLUSTER_NAME: name,
        C.ENV_COORDINATOR_ADDRESS: coordinator_address(cluster),
        C.ENV_FQ_HEAD_IP: f"{head_service_name(name)}.{cluster.metadata.namespace}.svc",
        C.ENV_TPU_WORKER_ID: str(host_idx),
        C.ENV_TPU_WORKER_HOSTNAMES: ",".join(
            slice_hostnames(cluster, group, slice_idx)),
        C.ENV_TPU_TOPOLOGY: topo.topology_str,
        C.ENV_TPU_ACCELERATOR_TYPE: topo.short_name,
        C.ENV_TPU_CHIPS_PER_HOST_BOUNDS: "x".join(
            str(b) for b in topo.host_block_dims()),
        C.ENV_NUM_PROCESSES: str(topo.num_hosts),
        C.ENV_PROCESS_ID: str(host_idx),
    }
    if num_slices_in_job > 1:
        env[C.ENV_MEGASCALE_COORDINATOR_ADDRESS] = coordinator_address(cluster)
        env[C.ENV_MEGASCALE_NUM_SLICES] = str(num_slices_in_job)
        env[C.ENV_MEGASCALE_SLICE_ID] = str(megascale_slice_id)
    _set_env(worker, {**(config_env or {}), **env})
    from kuberay_tpu.builders.auth import maybe_add_auth_env
    maybe_add_auth_env(worker, cluster)

    # Node placement: GKE TPU node-pool selectors
    # (ref kubectl-plugin constant.go:13-19 + TPU samples).
    sel = pod_spec.setdefault("nodeSelector", {})
    sel.setdefault(C.NODE_SELECTOR_GKE_ACCELERATOR, topo.generation.gke_accelerator)
    sel.setdefault(C.NODE_SELECTOR_GKE_TOPOLOGY, topo.topology_str)

    # Hostname + subdomain give each host the stable headless-service DNS
    # name TPU_WORKER_HOSTNAMES references.
    pod_spec["hostname"] = pod_name
    pod_spec["subdomain"] = headless_service_name(name)

    if cluster.spec.schedulerName and not pod_spec.get("schedulerName"):
        pod_spec["schedulerName"] = cluster.spec.schedulerName

    _inject_probes(worker, C.NODE_TYPE_WORKER,
                   (cluster.metadata.labels or {}).get(
                       C.LABEL_ORIGINATED_FROM_CRD, ""),
                   host_idx=host_idx)

    labels = {
        **tmpl.get("metadata", {}).get("labels", {}),
        **_base_labels(cluster, C.NODE_TYPE_WORKER),
        C.LABEL_GROUP: group.groupName,
        C.LABEL_SLICE_NAME: sname,
        C.LABEL_SLICE_INDEX: str(slice_idx),
        C.LABEL_HOST_INDEX: str(host_idx),
        # Workers serve by default; the serve controller flips head pods
        # only (serve Services select on this label).
        C.LABEL_SERVE: "true",
    }
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod_name,
            "namespace": cluster.metadata.namespace,
            "labels": labels,
            "annotations": dict(tmpl.get("metadata", {}).get("annotations", {})),
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "spec": pod_spec,
    }


def build_slice_pods(cluster: TpuCluster, group: WorkerGroupSpec,
                     slice_idx: int, **kw) -> List[Dict[str, Any]]:
    """All pods of one slice — the atomic creation unit."""
    topo = group.slice_topology()
    return [build_worker_pod(cluster, group, slice_idx, h, **kw)
            for h in range(topo.num_hosts)]
