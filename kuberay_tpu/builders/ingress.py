"""Ingress builder (ref controllers/ray/common/ingress.go + openshift.go).

Exposes the head's dashboard/serve endpoints through a cluster ingress
when ``headGroupSpec.enableIngress`` is set.  One builder emits the
standard ``networking.k8s.io/v1`` shape; the OpenShift Route variant is a
projection of the same inputs (the reference keeps two files; here one
module, two emitters).
"""

from __future__ import annotations

from typing import Any, Dict

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.builders.common import cluster_owner_reference
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.names import head_service_name, truncate_name


def build_head_ingress(cluster: TpuCluster,
                       ingress_class: str = "",
                       host: str = "") -> Dict[str, Any]:
    name = cluster.metadata.name
    svc = head_service_name(name)
    rule: Dict[str, Any] = {
        "http": {"paths": [
            {"path": f"/{name}", "pathType": "Prefix",
             "backend": {"service": {
                 "name": svc, "port": {"number": C.PORT_DASHBOARD}}}},
            {"path": f"/{name}/serve", "pathType": "Prefix",
             "backend": {"service": {
                 "name": svc, "port": {"number": C.PORT_SERVE}}}},
        ]},
    }
    if host:
        rule["host"] = host
    spec: Dict[str, Any] = {"rules": [rule]}
    if ingress_class:
        spec["ingressClassName"] = ingress_class
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "Ingress",
        "metadata": {
            "name": truncate_name(f"{name}-head-ingress"),
            "namespace": cluster.metadata.namespace,
            "labels": {C.LABEL_CLUSTER: name},
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "spec": spec,
    }


def build_head_route(cluster: TpuCluster) -> Dict[str, Any]:
    """OpenShift Route projection of the same endpoint (ref
    openshift.go:19 BuildRouteForHeadService: weight-100 Service target
    on the dashboard port, WildcardPolicy None, cluster annotations
    copied through as the user's route-customization channel)."""
    name = cluster.metadata.name
    return {
        "apiVersion": "route.openshift.io/v1",
        "kind": "Route",
        "metadata": {
            "name": truncate_name(f"{name}-head-route"),
            "namespace": cluster.metadata.namespace,
            "labels": {C.LABEL_CLUSTER: name},
            "annotations": dict(cluster.metadata.annotations or {}),
            "ownerReferences": [cluster_owner_reference(cluster)],
        },
        "spec": {
            "to": {"kind": "Service", "name": head_service_name(name),
                   "weight": 100},
            "port": {"targetPort": C.DEFAULT_DASHBOARD_PORT_NAME},
            "wildcardPolicy": "None",
        },
    }
