"""Rotary position embeddings (RoPE), Llama-3 style.

Pure jnp: RoPE is elementwise and fuses into the surrounding matmuls under
XLA; a Pallas kernel would add nothing (HBM-bound either way).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, theta: float = 500000.0,
                     dtype=jnp.float32) -> tuple[jax.Array, jax.Array]:
    """Precompute cos/sin tables: [max_len, head_dim//2]."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array | None = None) -> jax.Array:
    """Apply RoPE.  x: [..., seq, heads, head_dim]; cos/sin: [max_len, hd//2].

    ``positions``: optional [..., seq] absolute positions (for decode-time
    KV-cache stepping); defaults to arange(seq).
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq]                      # [seq, hd//2]
        s = sin[:seq]
        # broadcast over heads: [seq, 1, hd//2]
        c = c[:, None, :]
        s = s[:, None, :]
    else:
        c = jnp.take(cos, positions, axis=0)[..., :, None, :]
        s = jnp.take(sin, positions, axis=0)[..., :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
