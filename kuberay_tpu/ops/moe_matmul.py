"""Grouped (ragged) expert matmuls for MoE layers.

The dropless decode path in ``models/mixtral.py`` computes EVERY expert
for every token and zero-weights the unchosen ones — E/top_k times the
necessary FLOPs (8x7B top-2: 4x).  The TPU-native fix is the
megablocks-style grouped GEMM, expressed with ``jax.lax.ragged_dot``
(XLA's native ragged matmul, which Mosaic lowers onto the MXU with one
tiled pass over the concatenated token groups):

1. replicate each token once per chosen expert ((T, K) assignment pairs),
2. sort the TK rows by expert id (static shapes — argsort, no host sync),
3. one ragged_dot per weight tensor over contiguous expert groups,
4. unsort and combine with the routing weights.

Sorting costs O(TK log TK) on the VPU but the matmuls drop from E·T to
K·T rows — the win is (E/K)x FFN FLOPs whenever T ≳ a few rows per
expert, i.e. every realistic decode batch.

Reference counterpart: none (KubeRay ships no compute); role analogue is
vLLM's fused_moe grouped GEMM.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp


def grouped_moe_ffn(xt: jax.Array,
                    w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array,
                    topi: jax.Array, topw: jax.Array) -> jax.Array:
    """SwiGLU expert FFN over the tokens' top-k experts only.

    xt:     [T, d]   tokens (any float dtype; compute keeps xt.dtype)
    w_gate: [E, d, f]   w_up: [E, d, f]   w_down: [E, f, d]
    topi:   [T, K] int — chosen expert ids
    topw:   [T, K] float — combine weights (already normalized/masked)
    returns [T, d]
    """
    T, d = xt.shape
    E = w_gate.shape[0]
    K = topi.shape[1]
    TK = T * K

    flat_expert = topi.reshape(TK)                  # row r -> expert id
    order = jnp.argsort(flat_expert)                # stable: ties by row
    # Row r of the replicated input is token r // K.
    token_of_row = order // K
    x_sorted = jnp.take(xt, token_of_row, axis=0)   # [TK, d]
    group_sizes = jnp.bincount(flat_expert, length=E)

    gated = jax.nn.silu(jax.lax.ragged_dot(x_sorted, w_gate, group_sizes)) \
        * jax.lax.ragged_dot(x_sorted, w_up, group_sizes)
    out_sorted = jax.lax.ragged_dot(gated, w_down, group_sizes)  # [TK, d]

    # Unsort: scatter rows back to (token, k) order, weight, sum over k.
    unsorted = jnp.zeros((TK, d), out_sorted.dtype).at[order].set(out_sorted)
    per_k = unsorted.reshape(T, K, d)
    return jnp.einsum("tk,tkd->td", topw.astype(per_k.dtype), per_k)


def dropless_reference(xt: jax.Array,
                       w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array,
                       topi: jax.Array, topw: jax.Array) -> jax.Array:
    """All-experts reference (the pre-grouped dropless math): every expert
    runs on every token; unchosen experts get zero combine weight.  Used
    for numeric validation and as the fallback when a backend lacks
    ragged_dot."""
    T, _ = xt.shape
    E = w_gate.shape[0]
    weights = jnp.zeros((T, E), xt.dtype).at[
        jnp.arange(T)[:, None], topi].set(topw.astype(xt.dtype))
    gated = jax.nn.silu(jnp.einsum("td,edf->tef", xt, w_gate)) \
        * jnp.einsum("td,edf->tef", xt, w_up)
    all_out = jnp.einsum("tef,efd->ted", gated, w_down)
    return jnp.einsum("te,ted->td", weights, all_out)


def moe_ffn_flops(T: int, d: int, f: int, n_experts: int, top_k: int
                  ) -> Dict[str, float]:
    """FLOP accounting for the two strategies (3 matmuls each)."""
    per_row = 3 * 2 * d * f
    return {"grouped": float(T * top_k * per_row),
            "dropless": float(T * n_experts * per_row)}
