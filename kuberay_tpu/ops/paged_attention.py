"""Pallas paged decode attention: block-table-native cache reads.

The paged serve engine (serve/paged_engine.py) keeps one flat physical
block pool per layer and a per-request table of physical block ids.
Round 1 reused the dense decode kernel by GATHERING each request's live
blocks into a contiguous ``[B, K]`` view every step — correct, but it
copies the whole logical KV per generated token.  This kernel consumes
the block table directly: the grid walks each request's LOGICAL blocks
and the kv BlockSpec index map resolves them to PHYSICAL pool pages via
scalar-prefetched tables, so pages stream HBM->VMEM exactly once, with
no materialized gather, and — as in ops/decode_attention.py — pages past
a request's live length are never fetched at all (index clamp) and do no
compute (grid-level ``pl.when``).

Pool layout is head-major ``[Hkv, num_blocks*block_size, D]`` so one
page of one kv head is a contiguous ``block_size*D`` run: the indirect
page fetch is a single dense DMA and the block tile is ``(block_size,
D)`` — the natural mosaic shape — rather than a strided head-pick from
a ``[P, Hkv, D]`` pool.

Capability analogue: vLLM's PagedAttention CUDA kernel (the reference
serves LLMs via RayService + vLLM, e.g.
ray-operator/config/samples/vllm/ray-service.vllm-tpu-v6e-singlehost.yaml);
rebuilt here as a Pallas TPU kernel over a jittable static-shape pool.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def flat_indices(tables, block_size: int):
    """[B, max_blocks] tables -> [B, K] flat pool positions.  THE one
    logical->physical position map for gathered views (values and the
    int8 scale pools must resolve identically)."""
    B, nblk = tables.shape
    return (tables[:, :, None] * block_size +
            jnp.arange(block_size)[None, None, :]).reshape(
        B, nblk * block_size)


def gather_view(pool, tables, block_size: int):
    """[Hkv, P, D] pool + [B, max_blocks] tables -> [B, K, Hkv, D]
    contiguous per-request view (the round-1 materialized path; kept as
    the prefill view builder and the XLA fallback)."""
    flat = flat_indices(tables, block_size)
    # [Hkv, B, K, D] -> [B, K, Hkv, D]
    return jnp.take(pool, flat, axis=1).transpose(1, 2, 0, 3)


def paged_decode_attention_xla(q, pk, pv, lens, tables, block_size: int,
                               scale: Optional[float] = None):
    """Fallback: gather the logical view, run masked dense attention.
    q: [S, Hq, D]; pk/pv: [Hkv, P, D]; tables: [S, max_blocks]."""
    from kuberay_tpu.ops.decode_attention import decode_attention_xla
    ck = gather_view(pk, tables, block_size)
    cv = gather_view(pv, tables, block_size)
    return decode_attention_xla(q, ck, cv, lens, scale)


def _paged_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale, bs, nblk, num_kv_heads,
                  group):
    slot = pl.program_id(0)
    j = pl.program_id(1)          # logical block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live = lens_ref[slot]

    @pl.when(j * bs < live)
    def _compute():
        cols = j * bs + jax.lax.broadcasted_iota(
            jnp.int32, (group, bs), 1)
        # Static unroll over kv heads: one (Hkv, bs, D) page block serves
        # every head, so each physical page streams from HBM exactly once
        # per decode step (a per-head grid would cut the DMA to bs*D and
        # multiply the grid — measured grid-step overhead dominates at
        # serving block sizes).
        for h in range(num_kv_heads):
            rows = slice(h * group, (h + 1) * group)
            q = q_ref[0, rows, :]                 # [group, D]
            k = k_ref[h, 0, :, :]                 # [bs, D]
            v = v_ref[h, 0, :, :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [group, bs]
            s = jnp.where(cols < live, s, _NEG_INF)
            m_prev = m_scr[rows, :1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            corr = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)
            l_cur = corr * l_scr[rows, :1] + jnp.sum(p, axis=-1,
                                                     keepdims=True)
            pv_ = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_scr[rows, :] = acc_scr[rows, :] * corr + pv_
            m_scr[rows, :] = jnp.broadcast_to(m_cur, (group, 128))
            l_scr[rows, :] = jnp.broadcast_to(l_cur, (group, 128))

    @pl.when(j == nblk - 1)
    def _finalize():
        l = jnp.where(l_scr[:, :1] == 0.0, 1.0, l_scr[:, :1])
        o_ref[0, :, :] = (acc_scr[:, :] / l).astype(o_ref.dtype)


def paged_decode_attention_pallas(q, pk, pv, lens, tables, block_size: int,
                                  scale: Optional[float] = None,
                                  interpret: bool = False):
    """q: [S, Hq, D]; pk/pv: [Hkv, P, D] head-major pool;
    tables: [S, max_blocks] physical block ids; lens: [S]."""
    S, Hq, D = q.shape
    Hkv, P, _ = pk.shape
    bs = block_size
    assert P % bs == 0
    num_blocks = P // bs
    nblk = tables.shape[1]
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    # Contiguous page view of the head-major pool (free reshape).
    pk4 = pk.reshape(Hkv, num_blocks, bs, D)
    pv4 = pv.reshape(Hkv, num_blocks, bs, D)

    def kv_index(s, j, tables, lens):
        # Indirection + DMA skip in one map: resolve the LOGICAL block j
        # to its PHYSICAL page, clamping past-live blocks to the last
        # live one (a cheap re-read the compute branch ignores) so dead
        # pages never stream from HBM.
        last_live = jnp.maximum((lens[s] - 1) // bs, 0)
        jl = jnp.minimum(j, last_live)
        return (0, tables[s, jl], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, nblk),
        in_specs=[
            pl.BlockSpec((1, Hq, D),
                         lambda s, j, tables, lens: (s, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Hkv, 1, bs, D), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Hkv, 1, bs, D), kv_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, Hq, D),
                               lambda s, j, tables, lens: (s, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, D), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, scale=scale, bs=bs,
                               nblk=nblk, num_kv_heads=Hkv, group=group)
    # Bytes: worst case streams every table entry's page once per slot.
    cost = pl.CostEstimate(
        flops=4 * S * Hq * nblk * bs * D,
        bytes_accessed=(q.size + 2 * S * Hkv * nblk * bs * D)
        * q.dtype.itemsize,
        transcendentals=S * Hq * nblk * bs)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hq, D), q.dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(tables.astype(jnp.int32), lens.astype(jnp.int32), q, pk4, pv4)


def _check_paged_kernel() -> bool:
    """First-use on-chip self-check for the auto path (see
    decode_attention._auto_impl — round 2's interpret-passes-but-wrong-
    on-silicon lesson)."""
    S, Hq, Hkv, D, bs, nblk, P = 4, 8, 4, 128, 16, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(19), 4)
    q = jax.random.normal(ks[0], (S, Hq, D), jnp.bfloat16)
    pk = jax.random.normal(ks[1], (Hkv, P * bs, D), jnp.bfloat16)
    pv = jax.random.normal(ks[2], (Hkv, P * bs, D), jnp.bfloat16)
    tables = jax.random.randint(ks[3], (S, nblk), 0, P)
    lens = jnp.array([1, 17, 40, 64], jnp.int32)
    from kuberay_tpu.ops.decode_attention import kernels_match
    return kernels_match(
        paged_decode_attention_pallas(q, pk, pv, lens, tables, bs),
        paged_decode_attention_xla(q, pk, pv, lens, tables, bs))


def paged_decode_attention(q, pk, pv, lens, tables, block_size: int,
                           scale: Optional[float] = None,
                           impl: str = "auto"):
    """Dispatching paged decode.  impl: auto|pallas|xla|pallas_interpret."""
    if impl == "auto":
        from kuberay_tpu.ops.decode_attention import _auto_impl
        impl = _auto_impl("paged_decode", _check_paged_kernel)
    if impl == "xla":
        return paged_decode_attention_xla(q, pk, pv, lens, tables,
                                          block_size, scale)
    return paged_decode_attention_pallas(
        q, pk, pv, lens, tables, block_size, scale,
        interpret=impl == "pallas_interpret")
