"""Pallas decode attention: one-token queries against the serving cache.

The serving hot loop (serve/kv_cache.py) runs attention of a [slots, 1]
query batch against a [slots, max_len] KV cache every generated token.
The XLA path materializes full-length scores with masks; this kernel
streams the cache in blocks with online softmax and — the real win —
SKIPS blocks beyond each slot's live length (per-slot lengths arrive via
scalar prefetch, so the skip is a grid-level branch, not a mask): a slot
at position 100 of a 2048-token cache reads ~1/20th of it.

Layout: q [S, Hq, D]; cache [S, max_len, Hkv, D]; lens [S].  GQA grid is
(slot, kv_head, kv_block) with the head group computed together
([group, D] accumulators).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def decode_attention_xla(q, ck, cv, lens, scale: Optional[float] = None):
    """Reference/fallback.  q: [S, Hq, D]; ck/cv: [S, max, Hkv, D]."""
    S, Hq, D = q.shape
    Hkv = ck.shape[2]
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    kk = jnp.repeat(ck, group, axis=2) if group > 1 else ck
    vv = jnp.repeat(cv, group, axis=2) if group > 1 else cv
    s = jnp.einsum("shd,smhd->shm", q, kk,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(ck.shape[1])[None, None, :]
    s = jnp.where(cols < lens[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shm,smhd->shd", p.astype(vv.dtype), vv,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr,
                   *, scale, bkv, num_kv, num_kv_heads, group):
    slot = pl.program_id(0)
    j = pl.program_id(1)          # kv block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Grid-level skip: whole blocks beyond this slot's live length do no
    # MXU work at all (the point of the kernel).
    live = lens_ref[slot]

    @pl.when(j * bkv < live)
    def _compute():
        cols = j * bkv + jax.lax.broadcasted_iota(
            jnp.int32, (group, bkv), 1)
        # Static unroll over kv heads: each head's q group attends to its
        # head slice of the block.  One [bkv, Hkv, D] stream serves every
        # head, so the cache is read exactly once per decode step (the
        # per-head-grid layout would re-stream it Hkv times — and its
        # size-1 head block violates the TPU (8,128) tiling rule anyway).
        for h in range(num_kv_heads):
            rows = slice(h * group, (h + 1) * group)
            q = q_ref[0, rows, :]                # [group, D]
            k = k_ref[0, :, h, :]                # [bkv, D]
            v = v_ref[0, :, h, :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [group, bkv]
            s = jnp.where(cols < live, s, _NEG_INF)
            m_prev = m_scr[rows, :1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            corr = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)
            l_cur = corr * l_scr[rows, :1] + jnp.sum(p, axis=-1,
                                                     keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_scr[rows, :] = acc_scr[rows, :] * corr + pv
            m_scr[rows, :] = jnp.broadcast_to(m_cur, (group, 128))
            l_scr[rows, :] = jnp.broadcast_to(l_cur, (group, 128))

    @pl.when(j == num_kv - 1)
    def _finalize():
        l = jnp.where(l_scr[:, :1] == 0.0, 1.0, l_scr[:, :1])
        o_ref[0, :, :] = (acc_scr[:, :] / l).astype(o_ref.dtype)


def decode_attention_pallas(q, ck, cv, lens, scale: Optional[float] = None,
                            bkv: int = 1024, interpret: bool = False):
    # bkv=1024 measured on TPU v5e (B=64, K=2048, 8/4 heads): 6.8 ms vs
    # 7.4 (bkv=512) / 26.6 (bkv=256) / 8.4 XLA; bkv=2048 exceeds VMEM.
    S, Hq, D = q.shape
    max_len = ck.shape[1]
    Hkv = ck.shape[2]
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    while max_len % bkv != 0 and bkv > 8:
        bkv //= 2
    if max_len % bkv != 0:
        return decode_attention_xla(q, ck, cv, lens, scale)
    nkv = max_len // bkv

    def kv_index(s, j, lens):
        # DMA skip: blocks beyond the slot's live length never stream from
        # HBM — clamp to the last live block (a cheap re-read the compute
        # branch ignores).  This, not the pl.when, is the bandwidth win.
        last_live = jnp.maximum((lens[s] - 1) // bkv, 0)
        return (s, jnp.minimum(j, last_live), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, nkv),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda s, j, lens: (s, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bkv, Hkv, D), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bkv, Hkv, D), kv_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda s, j, lens: (s, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, D), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, scale=scale, bkv=bkv,
                               num_kv=nkv, num_kv_heads=Hkv, group=group)

    cost = pl.CostEstimate(
        flops=4 * S * Hq * max_len * D,
        bytes_accessed=(ck.size + cv.size + q.size) * q.dtype.itemsize,
        transcendentals=S * Hq * max_len)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hq, D), q.dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(lens.astype(jnp.int32), q, ck, cv)


def decode_attention(q, ck, cv, lens, scale: Optional[float] = None,
                     impl: str = "auto"):
    """Dispatching decode attention.  impl: auto|pallas|xla|pallas_interpret."""
    if impl == "auto":
        try:
            on_tpu = jax.default_backend() == "tpu"
        except Exception:
            on_tpu = False
        impl = "pallas" if on_tpu else "xla"
    if impl == "xla":
        return decode_attention_xla(q, ck, cv, lens, scale)
    return decode_attention_pallas(q, ck, cv, lens, scale,
                                   interpret=impl == "pallas_interpret")
