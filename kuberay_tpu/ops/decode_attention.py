"""Pallas decode attention: one-token queries against the serving cache.

The serving hot loop (serve/kv_cache.py) runs attention of a [slots, 1]
query batch against a [slots, max_len] KV cache every generated token.
The XLA path materializes full-length scores with masks; this kernel
streams the cache in blocks with online softmax and — the real win —
SKIPS blocks beyond each slot's live length (per-slot lengths arrive via
scalar prefetch, so the skip is a grid-level branch, not a mask): a slot
at position 100 of a 2048-token cache reads ~1/20th of it.

Layout: q [S, Hq, D]; cache [S, max_len, Hkv, D]; lens [S].  GQA grid is
(slot, kv_head, kv_block) with the head group computed together
([group, D] accumulators).

One kernel serves both cache dtypes: bf16, and the int8-quantized cache
(per-position scales in [S, Hkv, M] layout — positions on lanes) where
scales fold into the score columns (s *= ks) and probability rows
(p *= vs), so K/V are never dequantized to [bkv, D] and the HBM stream
is ~half the bf16 kernel's.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# First-use on-chip numeric self-checks for the auto path, keyed by
# kernel kind.  Round 2's lesson: a kernel that passes interpret mode can
# still be WRONG on real silicon (the r1 decode kernel's Hkv-axis tiling
# violation).  "auto" therefore runs the Pallas kernel once against the
# XLA reference on tiny shapes the first time a process uses it on TPU;
# a mismatch (or a lowering failure) permanently falls back to XLA for
# that process and logs the reason — wrong numerics can never ship
# silently.  Explicit impl="pallas" bypasses the check (benchmarks,
# capture scripts).
_AUTO_VERDICTS: dict = {}


def _auto_impl(kind: str, check) -> str:
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    if not on_tpu:
        return "xla"
    verdict = _AUTO_VERDICTS.get(kind)
    if verdict is None:
        try:
            # Resolution happens at TRACE time (the serve engine jits
            # the step that reaches this dispatch): force the check to
            # EXECUTE eagerly on the device instead of being staged into
            # the enclosing trace — traced, its float() would raise and
            # masquerade as a kernel failure.
            with jax.ensure_compile_time_eval():
                verdict = bool(check())
            reason = "numeric mismatch vs XLA reference"
        except Exception as e:  # lowering/compile failure on this chip
            verdict = False
            reason = f"{type(e).__name__}: {e}"
        _AUTO_VERDICTS[kind] = verdict
        if not verdict:
            import sys
            print(f"kuberay-tpu: {kind} Pallas kernel failed its on-chip "
                  f"self-check ({reason[:200]}); auto path falls back to "
                  f"XLA for this process", file=sys.stderr, flush=True)
    return "pallas" if verdict else "xla"


def kernels_match(a, b, tol: float = 5e-2) -> bool:
    """Shared self-check comparison: f32-upcast max-abs diff under tol."""
    return float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                 - b.astype(jnp.float32)))) < tol


def _check_inputs(seed: int):
    S, M, Hq, Hkv, D = 4, 256, 8, 4, 128
    ks_ = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks_[0], (S, Hq, D), jnp.bfloat16)
    ck = jax.random.normal(ks_[1], (S, M, Hkv, D), jnp.bfloat16)
    cv = jax.random.normal(ks_[2], (S, M, Hkv, D), jnp.bfloat16)
    return q, ck, cv, jnp.array([1, 100, 200, 256], jnp.int32)


def _check_decode_kernel() -> bool:
    q, ck, cv, lens = _check_inputs(17)
    return kernels_match(decode_attention_pallas(q, ck, cv, lens),
                         decode_attention_xla(q, ck, cv, lens))


def _check_quant_decode_kernel() -> bool:
    from kuberay_tpu.serve.kv_cache import quantize_kv
    q, ck, cv, lens = _check_inputs(18)
    kq, kss = quantize_kv(ck)
    vq, vss = quantize_kv(cv)
    kss = jnp.moveaxis(kss[..., 0], -1, 1)
    vss = jnp.moveaxis(vss[..., 0], -1, 1)
    return kernels_match(
        decode_attention_quant_pallas(q, kq, kss, vq, vss, lens),
        decode_attention_quant_xla(q, kq, kss, vq, vss, lens))


def _resolve_impl(impl: str, kind: str = "decode") -> str:
    if impl != "auto":
        return impl
    checks = {"decode": _check_decode_kernel,
              "decode_quant": _check_quant_decode_kernel}
    return _auto_impl(kind, checks[kind])


def dequant_lanes(x8, s, dtype):
    """Dequantize the lane-major scale layout: x8 [..., M, H, D] int8,
    s [..., H, M] f32 -> [..., M, H, D] in ``dtype``."""
    return (x8.astype(jnp.float32)
            * jnp.swapaxes(s, -2, -1)[..., None]).astype(dtype)


def decode_attention_xla(q, ck, cv, lens, scale: Optional[float] = None):
    """Reference/fallback.  q: [S, Hq, D]; ck/cv: [S, max, Hkv, D]."""
    S, Hq, D = q.shape
    Hkv = ck.shape[2]
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    kk = jnp.repeat(ck, group, axis=2) if group > 1 else ck
    vv = jnp.repeat(cv, group, axis=2) if group > 1 else cv
    s = jnp.einsum("shd,smhd->shm", q, kk,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(ck.shape[1])[None, None, :]
    s = jnp.where(cols < lens[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shm,smhd->shd", p.astype(vv.dtype), vv,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def decode_attention_quant_xla(q, kq, ks, vq, vs, lens,
                               scale: Optional[float] = None):
    """Reference/fallback for the int8 cache: dequantize then dense.
    kq/vq: [S, M, Hkv, D] int8; ks/vs: [S, Hkv, M] f32."""
    return decode_attention_xla(q, dequant_lanes(kq, ks, q.dtype),
                                dequant_lanes(vq, vs, q.dtype), lens, scale)


def _decode_kernel(lens_ref, q_ref, k_ref, *rest,
                   scale, bkv, num_kv, num_kv_heads, group, quant):
    """Shared bf16/int8 decode kernel body.  rest is (v_ref, o_ref,
    scratches) for bf16, or (ks_ref, v_ref, vs_ref, o_ref, scratches)
    for the quantized cache."""
    if quant:
        ks_ref, v_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        v_ref, o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    slot = pl.program_id(0)
    j = pl.program_id(1)          # kv block (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Grid-level skip: whole blocks beyond this slot's live length do no
    # MXU work at all (the point of the kernel).
    live = lens_ref[slot]

    @pl.when(j * bkv < live)
    def _compute():
        cols = j * bkv + jax.lax.broadcasted_iota(
            jnp.int32, (group, bkv), 1)
        # Static unroll over kv heads: each head's q group attends to its
        # head slice of the block.  One [bkv, Hkv, D] stream serves every
        # head, so the cache is read exactly once per decode step (the
        # per-head-grid layout would re-stream it Hkv times — and its
        # size-1 head block violates the TPU (8,128) tiling rule anyway).
        for h in range(num_kv_heads):
            rows = slice(h * group, (h + 1) * group)
            q = q_ref[0, rows, :]                # [group, D]
            k = k_ref[0, :, h, :]                # [bkv, D]
            v = v_ref[0, :, h, :]
            if quant:
                # int8 values <= 127 are exact in the query dtype; the
                # per-position dequant scale folds into the score columns
                # and probability rows instead of touching [bkv, D].
                k = k.astype(q.dtype)
                v = v.astype(q.dtype)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)           # [group, bkv]
            if quant:
                s = s * (ks_ref[0, h, :][None, :] * scale)
            else:
                s = s * scale
            s = jnp.where(cols < live, s, _NEG_INF)
            m_prev = m_scr[rows, :1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            corr = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)
            l_cur = corr * l_scr[rows, :1] + jnp.sum(p, axis=-1,
                                                     keepdims=True)
            if quant:
                p = p * vs_ref[0, h, :][None, :]
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_scr[rows, :] = acc_scr[rows, :] * corr + pv
            m_scr[rows, :] = jnp.broadcast_to(m_cur, (group, 128))
            l_scr[rows, :] = jnp.broadcast_to(l_cur, (group, 128))

    @pl.when(j == num_kv - 1)
    def _finalize():
        l = jnp.where(l_scr[:, :1] == 0.0, 1.0, l_scr[:, :1])
        o_ref[0, :, :] = (acc_scr[:, :] / l).astype(o_ref.dtype)


def _pallas_decode(q, lens, kv_args, scale, bkv, interpret, quant,
                   bytes_accessed):
    """Shared pallas_call builder for both cache dtypes."""
    S, Hq, D = q.shape
    first_kv = kv_args[0]
    max_len = first_kv.shape[1]
    Hkv = first_kv.shape[2]
    group = Hq // Hkv
    nkv = max_len // bkv

    def kv_index(s, j, lens):
        # DMA skip: blocks beyond the slot's live length never stream from
        # HBM — clamp to the last live block (a cheap re-read the compute
        # branch ignores).  This, not the pl.when, is the bandwidth win.
        last_live = jnp.maximum((lens[s] - 1) // bkv, 0)
        return (s, jnp.minimum(j, last_live), 0, 0)

    def scale_index(s, j, lens):
        last_live = jnp.maximum((lens[s] - 1) // bkv, 0)
        return (s, 0, jnp.minimum(j, last_live))

    kv_spec = pl.BlockSpec((1, bkv, Hkv, D), kv_index,
                           memory_space=pltpu.VMEM)
    s_spec = pl.BlockSpec((1, Hkv, bkv), scale_index,
                          memory_space=pltpu.VMEM)
    in_specs = [pl.BlockSpec((1, Hq, D), lambda s, j, lens: (s, 0, 0),
                             memory_space=pltpu.VMEM)]
    in_specs += [kv_spec, s_spec, kv_spec, s_spec] if quant \
        else [kv_spec, kv_spec]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, nkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq, D), lambda s, j, lens: (s, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, D), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, scale=scale, bkv=bkv,
                               num_kv=nkv, num_kv_heads=Hkv, group=group,
                               quant=quant)
    cost = pl.CostEstimate(
        flops=4 * S * Hq * max_len * D,
        bytes_accessed=bytes_accessed,
        transcendentals=S * Hq * max_len)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hq, D), q.dtype),
        cost_estimate=cost,
        interpret=interpret,
    )(lens.astype(jnp.int32), q, *kv_args)


def _fit_bkv(max_len: int, bkv: int) -> int:
    while max_len % bkv != 0 and bkv > 8:
        bkv //= 2
    return bkv


def decode_attention_pallas(q, ck, cv, lens, scale: Optional[float] = None,
                            bkv: int = 1024, interpret: bool = False):
    # bkv=1024 measured on TPU v5e (B=64, K=2048, 8/4 heads): 6.8 ms vs
    # 7.4 (bkv=512) / 26.6 (bkv=256) / 8.4 XLA; bkv=2048 exceeds VMEM.
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    bkv = _fit_bkv(ck.shape[1], bkv)
    if ck.shape[1] % bkv != 0:
        return decode_attention_xla(q, ck, cv, lens, scale)
    return _pallas_decode(
        q, lens, (ck, cv), scale, bkv, interpret, quant=False,
        bytes_accessed=(ck.size + cv.size + q.size) * q.dtype.itemsize)


def decode_attention_quant_pallas(q, kq, ks, vq, vs, lens,
                                  scale: Optional[float] = None,
                                  bkv: int = 1024, interpret: bool = False):
    """int8-cache decode attention: streams HALF the HBM bytes of the
    bf16 kernel (int8 payload + one f32 scale per position-head)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    bkv = _fit_bkv(kq.shape[1], bkv)
    if kq.shape[1] % bkv != 0:
        return decode_attention_quant_xla(q, kq, ks, vq, vs, lens, scale)
    return _pallas_decode(
        q, lens, (kq, ks, vq, vs), scale, bkv, interpret, quant=True,
        bytes_accessed=kq.size + vq.size + (ks.size + vs.size) * 4
        + q.size * q.dtype.itemsize)


def decode_attention(q, ck, cv, lens, scale: Optional[float] = None,
                     impl: str = "auto"):
    """Dispatching decode attention.  impl: auto|pallas|xla|pallas_interpret."""
    impl = _resolve_impl(impl, "decode")
    if impl == "xla":
        return decode_attention_xla(q, ck, cv, lens, scale)
    return decode_attention_pallas(q, ck, cv, lens, scale,
                                   interpret=impl == "pallas_interpret")


def decode_attention_quant(q, kq, ks, vq, vs, lens,
                           scale: Optional[float] = None,
                           impl: str = "auto"):
    """Dispatching int8-cache decode attention."""
    impl = _resolve_impl(impl, "decode_quant")
    if impl == "xla":
        return decode_attention_quant_xla(q, kq, ks, vq, vs, lens, scale)
    return decode_attention_quant_pallas(
        q, kq, ks, vq, vs, lens, scale,
        interpret=impl == "pallas_interpret")
