"""Flash attention for TPU: Pallas kernels with an XLA fallback.

Memory-efficient attention (never materializes the [S, S] score matrix in
HBM): online-softmax forward saving per-row LSE, and a two-kernel backward
(dKV sweep, dQ sweep) recomputing P from Q/K/LSE — the standard
flash-attention-2 decomposition, laid out for the MXU:

- grid (batch, q_head, q_block, kv_block) with VMEM scratch accumulators
  carried across the innermost (sequential) kv grid dimension;
- all matmuls f32-accumulated via ``preferred_element_type``;
- causal blocks that are entirely masked are skipped (no MXU work);
- GQA folds the q-head -> kv-head mapping into the k/v BlockSpec index
  maps, so grouped heads stream the same K/V blocks.

Layout convention: [batch, heads, seq, head_dim] inside the kernels.
Public API takes [batch, seq, heads, head_dim] (model layout) and
transposes at the boundary.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # avoids NaN from (-inf) - (-inf) in online softmax


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# --------------------------------------------------------------------------
# XLA reference / fallback
# --------------------------------------------------------------------------

def attention_xla(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Reference attention.  q: [B,Sq,Hq,D], k/v: [B,Skv,Hkv,D] (GQA ok)."""
    B, Sq, Hq, D = q.shape
    _, Skv, Hkv, _ = k.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    group = Hq // Hkv
    kk = jnp.repeat(k, group, axis=2) if group > 1 else k
    vv = jnp.repeat(v, group, axis=2) if group > 1 else v
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kk,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = jnp.arange(Sq)[:, None] + (Skv - Sq)
        ki = jnp.arange(Skv)[None, :]
        s = jnp.where(ki <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vv.dtype), vv,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, causal, bq, bkv, num_kv, offset):
    i = pl.program_id(2)          # q block index
    j = pl.program_id(3)          # kv block index (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip kv blocks strictly above the diagonal band.  With
    # Skv > Sq (KV-cache decode) queries sit at the END of the key axis:
    # query row r attends keys <= r + offset, offset = Skv - Sq (matching
    # attention_xla).
    visible = (j * bkv <= i * bq + bq - 1 + offset) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0, :, :]                     # [bq, D]
        k = k_ref[0, 0, :, :]                     # [bkv, D]
        v = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bkv]
        if causal:
            rows = i * bq + offset + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            cols = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_scr[:, :1]                     # [bq, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_cur)            # [bq, 1]
        p = jnp.exp(s - m_cur)                    # [bq, bkv]
        l_cur = corr * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # [bq, D]
        acc_scr[:, :] = acc_scr[:, :] * corr + pv
        m_scr[:, :] = jnp.broadcast_to(m_cur, m_scr.shape)
        l_scr[:, :] = jnp.broadcast_to(l_cur, l_scr.shape)

    @pl.when(j == num_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        # Fully-masked rows (can't happen with causal self-attn) guard:
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scr[:, :] / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m_scr[:, :1] + jnp.log(l)


def _flash_fwd(q, k, v, scale, causal, bq, bkv, interpret):
    """q: [B,Hq,Sq,D]; k/v: [B,Hkv,Skv,D] -> (out [B,Hq,Sq,D], lse [B,Hq,Sq,1])."""
    B, Hq, Sq, D = q.shape
    _, Hkv, Skv, _ = k.shape
    group = Hq // Hkv
    nq, nkv = Sq // bq, Skv // bkv
    grid = (B, Hq, nq, nkv)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bkv=bkv, num_kv=nkv,
        offset=Skv - Sq)
    flops_per_step = 4 * bq * bkv * D          # qk^T + pv, f32 MACs x2
    cost = pl.CostEstimate(
        flops=B * Hq * nq * nkv * flops_per_step,
        bytes_accessed=(q.size + 2 * k.size + q.size) * q.dtype.itemsize,
        transcendentals=B * Hq * Sq * Skv)       # exp in the softmax
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        cost_estimate=cost,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, i, j: (b, h // group, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, i, j: (b, h // group, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, Sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------------------
# Pallas backward (flash-attention-2 style, two sweeps)
# --------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, bq, bkv, num_q, offset):
    j = pl.program_id(2)          # kv block
    i = pl.program_id(3)          # q block (innermost)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    visible = (j * bkv <= i * bq + bq - 1 + offset) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0, :, :]                    # [bq, D]
        k = k_ref[0, 0, :, :]                    # [bkv, D]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]                  # [bq, D]
        lse = lse_ref[0, 0, :, :]                # [bq, 1]
        delta = delta_ref[0, 0, :, :]            # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bkv]
        if causal:
            rows = i * bq + offset + \
                jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            cols = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse)                     # [bq, bkv]
        # dV += P^T @ dO
        dv_scr[:, :] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dP = dO @ V^T ; dS = P * (dP - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale            # [bq, bkv]
        # dK += dS^T @ Q
        dk_scr[:, :] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == num_q - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scr[:, :].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:, :].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, scale, causal, bq, bkv, num_kv, offset):
    i = pl.program_id(2)          # q block
    j = pl.program_id(3)          # kv block (innermost)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    visible = (j * bkv <= i * bq + bq - 1 + offset) if causal else True

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = i * bq + offset + \
                jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            cols = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale            # [bq, bkv]
        dq_scr[:, :] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_kv - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scr[:, :].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, scale, causal, bq, bkv, interpret):
    B, Hq, Sq, D = q.shape
    _, Hkv, Skv, _ = k.shape
    group = Hq // Hkv
    nq, nkv = Sq // bq, Skv // bkv
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)      # [B,Hq,Sq,1]

    in_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),               # q
        pl.BlockSpec((1, 1, bkv, D), lambda b, h, j, i: (b, h // group, j, 0),
                     memory_space=pltpu.VMEM),               # k
        pl.BlockSpec((1, 1, bkv, D), lambda b, h, j, i: (b, h // group, j, 0),
                     memory_space=pltpu.VMEM),               # v
        pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),               # do
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),               # lse
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),               # delta
    ]
    # dKV sweep: per-q-head gradients, summed over the GQA group afterwards.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bkv=bkv, num_q=nq, offset=Skv - Sq),
        grid=(B, Hq, nkv, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, Skv, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hq, Skv, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, D), jnp.float32),
            pltpu.VMEM((bkv, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    if group > 1:
        dk = dk.reshape(B, Hkv, group, Skv, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, group, Skv, D).sum(axis=2)

    dq_spec_q = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bkv, D), lambda b, h, i, j: (b, h // group, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bkv, D), lambda b, h, i, j: (b, h // group, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bkv=bkv, num_kv=nkv, offset=Skv - Sq),
        grid=(B, Hq, nq, nkv),
        in_specs=dq_spec_q,
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, Hq, Sq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# Public API
# --------------------------------------------------------------------------

# Block-size targets, overridable for on-chip tuning sweeps
# (tools/tpu_capture.py): largest power-of-two divisor <= target wins.
_BQ_TARGET = int(os.environ.get("TPU_FLASH_BQ", "512"))
_BKV_TARGET = int(os.environ.get("TPU_FLASH_BKV", "512"))


def _pick_block(seq: int, target: int = 512) -> int:
    """Largest power-of-two block <= target that divides seq (min 8)."""
    b = min(target, seq)
    while seq % b != 0 and b > 8:
        b //= 2
    return max(b, 1)


def _blocks(q, k):
    return (_pick_block(q.shape[2], _BQ_TARGET),
            _pick_block(k.shape[2], _BKV_TARGET))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, interpret):
    bq, bkv = _blocks(q, k)
    out, _ = _flash_fwd(q, k, v, scale, causal, bq, bkv, interpret)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, interpret):
    bq, bkv = _blocks(q, k)
    out, lse = _flash_fwd(q, k, v, scale, causal, bq, bkv, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, interpret, res, do):
    q, k, v, out, lse = res
    bq, bkv = _blocks(q, k)
    return _flash_bwd(q, k, v, out, lse, do, scale, causal, bq, bkv,
                      interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    impl: str = "auto") -> jax.Array:
    """Flash attention.  q: [B,Sq,Hq,D]; k/v: [B,Skv,Hkv,D]; GQA via Hq>Hkv.

    ``impl``: 'auto' (Pallas on TPU, XLA elsewhere), 'pallas', 'xla',
    'pallas_interpret' (for CPU tests of the kernel itself).
    """
    B, Sq, Hq, D = q.shape
    _, Skv, Hkv, _ = k.shape
    if Hq % Hkv != 0:
        raise ValueError(f"q heads {Hq} must be a multiple of kv heads {Hkv}")
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "xla"
    if impl != "xla":
        # Pallas grids require block sizes that tile the sequence exactly;
        # ragged lengths fall back to the XLA path rather than silently
        # leaving trailing rows unwritten.
        if Sq % _pick_block(Sq) != 0 or Skv % _pick_block(Skv) != 0:
            impl = "xla"
    if impl == "xla":
        return attention_xla(q, k, v, causal, scale)
    interpret = impl == "pallas_interpret"
    # -> [B,H,S,D] kernel layout
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, scale, causal, interpret)
    return out.transpose(0, 2, 1, 3)
