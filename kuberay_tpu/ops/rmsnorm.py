"""Fused RMSNorm: Pallas TPU kernel with an XLA fallback.

The kernel fuses the mean-square reduction, rsqrt, and scale multiply in
VMEM — one HBM read + one write per element instead of the several a naive
composition can incur when XLA doesn't fuse across the reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def rmsnorm_xla(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Reference implementation; also the CPU/GPU fallback."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "block_rows"))
def rmsnorm_pallas(x: jax.Array, weight: jax.Array, eps: float = 1e-5,
                   block_rows: int = 256) -> jax.Array:
    """Row-blocked fused RMSNorm.  x: [..., d]; weight: [d]."""
    orig_shape = x.shape
    d = x.shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    # Row count must tile; fall back for ragged shapes.
    if rows % block_rows != 0:
        return rmsnorm_xla(x, weight, eps)
    grid = (rows // block_rows,)
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(x2, weight)
    return out.reshape(orig_shape)


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Backend-dispatching RMSNorm (differentiable everywhere: the Pallas
    path is forward-only fused; gradients flow through the XLA definition
    via custom_vjp recompute)."""
    return _rmsnorm(x, weight, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm(x, weight, eps):
    return _rmsnorm_fwd_impl(x, weight, eps)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _rmsnorm_fwd_impl(x, weight, eps):
    if _on_tpu():
        return rmsnorm_pallas(x, weight, eps)
    return rmsnorm_xla(x, weight, eps)


def _rmsnorm_fwd(x, weight, eps):
    return _rmsnorm_fwd_impl(x, weight, eps), (x, weight)


def _rmsnorm_bwd(eps, res, g):
    x, weight = res
    _, vjp = jax.vjp(lambda xx, ww: rmsnorm_xla(xx, ww, eps), x, weight)
    return vjp(g)


_rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)
