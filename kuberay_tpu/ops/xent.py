"""Chunked softmax cross-entropy: the loss without the logits.

``loss_fn``'s naive path materializes [B, S, V] f32 logits — at Llama-3
scale (V=128k, B·S=256k tokens) that is ~134 GB unsharded, the single
largest activation in training.  This op never materializes more than
[T, chunk] logits:

- forward: online logsumexp over vocab chunks (one running (m, l) pair
  per token — the flash-attention trick applied to the vocab axis),
  plus the target's logit and the running argmax;
- backward (custom_vjp): recompute each chunk's logits from the saved
  (x, head) residuals and contract immediately into dx / dhead —
  softmax rows never exist all at once either.

A vocab that doesn't divide the chunk gets one static tail segment (the
remainder) instead of a silently collapsed chunk size — llama3's
V=128256 with chunk 16384 runs 7 full chunks + one 13568-wide tail, not
501 tiny matmuls.  Matmuls keep the model dtype as operands with f32
accumulation (``preferred_element_type``), matching the dense einsum's
MXU rate; only the tiny running statistics live in f32.

Cost: the head matmul runs twice (fwd + recompute in bwd) — the same
FLOPs-for-memory trade as jax.checkpoint, applied where it pays most.

Reference counterpart: none (KubeRay ships no compute); role analogues
are fused/chunked CE in large-vocab training stacks.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

_NEG = -1e30


def _dot_f32(a, b):
    """Matmul with native-dtype operands and f32 accumulation."""
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_xent(x, head, targets, chunk: int = 8192):
    """x: [T, d] hidden states; head: [d, V]; targets: [T] int32.
    Returns (nll [T], logz [T], pred [T]) — pred is argmax (no grad).
    """
    nll, logz, pred, _ = _forward(x, head, targets, chunk)
    return nll, logz, pred


def _forward(x, head, targets, chunk):
    T, d = x.shape
    V = head.shape[1]
    C = min(chunk, V)
    nc, tail = V // C, V % C

    def update(carry, logits, col0):
        m, l, tl, bv, bi = carry
        cols = col0 + jnp.arange(logits.shape[1])[None, :]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        l = l * jnp.exp(m - m_new) + \
            jnp.sum(jnp.exp(logits - m_new[:, None]), axis=-1)
        tl = tl + jnp.sum(jnp.where(cols == targets[:, None], logits, 0.0),
                          axis=-1)
        cv = jnp.max(logits, axis=-1)
        ci = col0 + jnp.argmax(logits, axis=-1).astype(jnp.int32)
        take = cv > bv
        return (m_new, l, tl, jnp.where(take, cv, bv),
                jnp.where(take, ci, bi))

    def body(i, carry):
        hc = jax.lax.dynamic_slice_in_dim(head, i * C, C, axis=1)
        return update(carry, _dot_f32(x, hc), i * C)

    carry = (jnp.full((T,), _NEG, jnp.float32), jnp.zeros((T,), jnp.float32),
             jnp.zeros((T,), jnp.float32), jnp.full((T,), _NEG, jnp.float32),
             jnp.zeros((T,), jnp.int32))
    carry = jax.lax.fori_loop(0, nc, body, carry)
    if tail:
        carry = update(carry, _dot_f32(x, head[:, nc * C:]), nc * C)
    m, l, tl, _, pred = carry
    logz = m + jnp.log(l)
    return logz - tl, logz, pred, (x, head, targets, logz)


def _fwd(x, head, targets, chunk):
    nll, logz, pred, res = _forward(x, head, targets, chunk)
    return (nll, logz, pred), res


def _bwd(chunk, res, cts):
    g_nll, g_logz, _ = cts                        # pred carries no grad
    x, head, targets, logz = res
    T, d = x.shape
    V = head.shape[1]
    C = min(chunk, V)
    nc, tail = V // C, V % C
    # d(nll)/dlogits = softmax - onehot ; d(logz)/dlogits = softmax.
    gp = (g_nll + g_logz).astype(jnp.float32)     # softmax coefficient

    def dchunk(hc, col0):
        logits = _dot_f32(x, hc)
        p = jnp.exp(logits - logz[:, None])       # softmax rows, this chunk
        cols = col0 + jnp.arange(logits.shape[1])[None, :]
        dlog = gp[:, None] * p - jnp.where(
            cols == targets[:, None], g_nll[:, None], 0.0)
        dlog = dlog.astype(x.dtype)               # bf16 operands, f32 acc
        dxc = _dot_f32(dlog, hc.T)
        dhc = _dot_f32(x.T, dlog)
        return dxc, dhc

    def body(i, carry):
        dx, dhead = carry
        hc = jax.lax.dynamic_slice_in_dim(head, i * C, C, axis=1)
        dxc, dhc = dchunk(hc, i * C)
        dhead = jax.lax.dynamic_update_slice_in_dim(
            dhead, dhc.astype(dhead.dtype), i * C, axis=1)
        return dx + dxc, dhead

    dx0 = jnp.zeros((T, d), jnp.float32)
    dh0 = jnp.zeros((d, V), jnp.float32)
    dx, dhead = jax.lax.fori_loop(0, nc, body, (dx0, dh0))
    if tail:
        dxc, dhc = dchunk(head[:, nc * C:], nc * C)
        dx = dx + dxc
        dhead = dhead.at[:, nc * C:].set(dhc.astype(dhead.dtype))
    return dx.astype(x.dtype), dhead.astype(head.dtype), None


chunked_xent.defvjp(_fwd, _bwd)


def chunked_softmax_xent_loss(x, head, targets, mask=None,
                              z_loss: float = 1e-4, chunk: int = 8192
                              ) -> Tuple[jax.Array, dict]:
    """Drop-in for the tail of loss_fn: hidden states + head -> masked
    mean loss and metrics, without a [T, V] intermediate."""
    T = x.shape[0]
    nll, logz, pred = chunked_xent(x, head, targets, chunk)
    zl = z_loss * jnp.square(logz)
    if mask is None:
        mask = jnp.ones((T,), jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = ((nll + zl) * mask).sum() / denom
    metrics = {
        "loss": (nll * mask).sum() / denom,
        "z_loss": (zl * mask).sum() / denom,
        "accuracy": ((pred == targets) * mask).sum() / denom,
    }
    return loss, metrics
