"""Device mesh construction and named-axis conventions.

The scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
collectives.  Axis names used across the framework:

- ``dp``:   pure data parallel (gradient all-reduce over DCN between slices)
- ``fsdp``: data parallel with sharded params/optimizer (ZeRO-3 style;
            all-gather params, reduce-scatter grads — rides ICI)
- ``tp``:   tensor parallel (activation collectives every layer — innermost,
            fastest ICI axis)
- ``sp``:   sequence/context parallel for ring attention (ICI neighbors)
- ``ep``:   expert parallel for MoE (all-to-all)
- ``pp``:   pipeline parallel (stage-per-slice, ppermute activation hops)

A TpuCluster worker group maps to this as: slices = dp axis, hosts within a
slice = fsdp/sp, chips within a host = tp (SURVEY.md §2.3 table).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape.  Axis size -1 means 'absorb remaining devices'."""

    dp: int = 1
    pp: int = 1
    fsdp: int = -1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    AXES = ("dp", "pp", "fsdp", "tp", "sp", "ep")

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in self.AXES}
        wildcard = [a for a, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one -1 axis, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.resolve(len(devices))
        shape = tuple(sizes[a] for a in self.AXES)
        arr = np.array(devices).reshape(shape)
        return Mesh(arr, self.AXES)


def make_mesh(n_devices: Optional[int] = None, **axes) -> Mesh:
    """Convenience: ``make_mesh(tp=4)`` uses all devices, fsdp absorbing."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return MeshSpec(**axes).build(devices)


def shard(mesh: Mesh, *axes) -> NamedSharding:
    """NamedSharding helper: ``shard(mesh, 'fsdp', None, 'tp')``."""
    return NamedSharding(mesh, P(*axes))


def logical_to_sharding(rules: Dict[str, Tuple], mesh: Mesh,
                        logical_axes) -> NamedSharding:
    """Map a tuple of logical axis names to a NamedSharding via rules.

    ``rules`` maps logical axis name -> mesh axis (or None / tuple of mesh
    axes).  Unknown logical names shard as None (replicated).
    """
    spec = tuple(rules.get(a) for a in logical_axes)
    return NamedSharding(mesh, P(*spec))


# Default logical->mesh rules for transformer params/activations.
# Conventions: "embed" = d_model, "heads" = attention heads, "mlp" = d_ff,
# "vocab" = vocabulary, "layers" = stacked layer dim, "batch" = batch,
# "seq" = sequence.
DEFAULT_RULES: Dict[str, object] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",      # ZeRO-3: shard params along d_model over fsdp
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,
    "expert": "ep",
    "head_dim": None,
    "norm": None,
}
