"""Device mesh construction and named-axis conventions.

The scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
collectives.  Axis names used across the framework:

- ``dp``:   pure data parallel (gradient all-reduce over DCN between slices)
- ``fsdp``: data parallel with sharded params/optimizer (ZeRO-3 style;
            all-gather params, reduce-scatter grads — rides ICI)
- ``tp``:   tensor parallel (activation collectives every layer — innermost,
            fastest ICI axis)
- ``sp``:   sequence/context parallel for ring attention (ICI neighbors)
- ``ep``:   expert parallel for MoE (all-to-all)
- ``pp``:   pipeline parallel (stage-per-slice, ppermute activation hops)

A TpuCluster worker group maps to this as: slices = dp axis, hosts within a
slice = fsdp/sp, chips within a host = tp (SURVEY.md §2.3 table).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape.  Axis size -1 means 'absorb remaining devices'."""

    dp: int = 1
    pp: int = 1
    fsdp: int = -1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    AXES = ("dp", "pp", "fsdp", "tp", "sp", "ep")

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in self.AXES}
        wildcard = [a for a, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one -1 axis, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.resolve(len(devices))
        shape = tuple(sizes[a] for a in self.AXES)
        arr = np.array(devices).reshape(shape)
        return Mesh(arr, self.AXES)

    def build_multislice(self,
                         devices: Optional[Sequence[jax.Device]] = None,
                         num_slices: Optional[int] = None,
                         dcn_axes: Sequence[str] = ("dp",)) -> Mesh:
        """Hybrid ICI/DCN mesh for multi-slice (megascale) training.

        The named ``dcn_axes`` (default: pure data parallelism) vary
        ACROSS slices — their collectives ride the slow DCN links — and
        every other axis lives WITHIN a slice, so fsdp all-gathers, tp
        matmul collectives, ring-attention ppermutes, and MoE all-to-alls
        ride ICI (the scaling-book layout).  Slice membership comes from
        ``device.slice_index`` when the platform provides it (real
        multi-slice TPU), else from contiguous device order (the
        ``jax.distributed`` host ordering the operator's
        ``TPU_WORKER_ID`` contract guarantees; also the virtual-mesh
        test path).

        The product of the dcn axis sizes must equal ``num_slices`` (or
        the detected slice count).
        """
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.resolve(len(devices))
        for a in dcn_axes:
            if a not in self.AXES:
                raise ValueError(f"unknown dcn axis {a!r}")

        by_slice = {}
        if all(getattr(d, "slice_index", None) is not None for d in devices):
            for d in devices:
                by_slice.setdefault(d.slice_index, []).append(d)
        if len(by_slice) > 1:
            # Platform knows the real slice structure (multi-slice TPU).
            detected = len(by_slice)
            if num_slices is not None and num_slices != detected:
                raise ValueError(
                    f"num_slices={num_slices} but platform reports "
                    f"{detected} slices")
            num_slices = detected
            group_sizes = {len(g) for g in by_slice.values()}
            if len(group_sizes) != 1:
                # Uneven groups would reshape "cleanly" into a mesh whose
                # ICI axes straddle DCN — refuse instead.
                raise ValueError(
                    f"slices have unequal device counts "
                    f"{sorted(len(g) for g in by_slice.values())}; pass a "
                    f"device subset with equal per-slice counts")
        else:
            # Single- or no-slice_index platforms (CPU virtual mesh, one
            # process per slice over DCN): slice = contiguous device
            # range in process order, which the operator's TPU_WORKER_ID
            # / MEGASCALE_SLICE_ID contract makes slice order.
            by_slice = {}
            if not num_slices:
                raise ValueError("num_slices required when devices carry "
                                 "no slice_index")
            per = len(devices) // num_slices
            if per * num_slices != len(devices):
                raise ValueError(f"{len(devices)} devices do not divide "
                                 f"into {num_slices} slices")
            by_slice = {i: devices[i * per:(i + 1) * per]
                        for i in range(num_slices)}

        dcn_size = math.prod(sizes[a] for a in dcn_axes)
        if dcn_size != num_slices:
            raise ValueError(
                f"dcn axes {tuple(dcn_axes)} have total size {dcn_size}, "
                f"but there are {num_slices} slices — the cross-slice "
                f"axes must exactly cover the slices")

        # Lay out [slice, within-slice], then split into per-axis dims
        # with dcn axes leading, and transpose back to AXES order.
        ordered = [d for i in sorted(by_slice) for d in by_slice[i]]
        dcn_in_order = [a for a in self.AXES if a in dcn_axes]
        ici_in_order = [a for a in self.AXES if a not in dcn_axes]
        arr = np.array(ordered).reshape(
            [sizes[a] for a in dcn_in_order] +
            [sizes[a] for a in ici_in_order])
        perm = [(dcn_in_order + ici_in_order).index(a) for a in self.AXES]
        return Mesh(arr.transpose(perm), self.AXES)


def make_mesh(n_devices: Optional[int] = None, **axes) -> Mesh:
    """Convenience: ``make_mesh(tp=4)`` uses all devices, fsdp absorbing."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return MeshSpec(**axes).build(devices)


def shard(mesh: Mesh, *axes) -> NamedSharding:
    """NamedSharding helper: ``shard(mesh, 'fsdp', None, 'tp')``."""
    return NamedSharding(mesh, P(*axes))


def logical_to_sharding(rules: Dict[str, Tuple], mesh: Mesh,
                        logical_axes) -> NamedSharding:
    """Map a tuple of logical axis names to a NamedSharding via rules.

    ``rules`` maps logical axis name -> mesh axis (or None / tuple of mesh
    axes).  Unknown logical names shard as None (replicated).
    """
    spec = tuple(rules.get(a) for a in logical_axes)
    return NamedSharding(mesh, P(*spec))


# Default logical->mesh rules for transformer params/activations.
# Conventions: "embed" = d_model, "heads" = attention heads, "mlp" = d_ff,
# "vocab" = vocabulary, "layers" = stacked layer dim, "batch" = batch,
# "seq" = sequence.
DEFAULT_RULES: Dict[str, object] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",      # ZeRO-3: shard params along d_model over fsdp
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,
    "expert": "ep",
    "head_dim": None,
    "norm": None,
}
