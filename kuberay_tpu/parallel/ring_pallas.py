"""Pallas ring attention: RDMA-overlapped sequence parallelism.

The shard_map+ppermute ring (parallel/ring.py) is correct but exposes
the neighbor exchange to XLA as a collective between scan steps; this
kernel instead drives the ICI directly with
``pltpu.make_async_remote_copy`` so the NEXT step's K/V block streams to
the right neighbor WHILE the current block's attention runs on the MXU
(NOTES round-1 item 4 / VERDICT round-1 next-step 8).

Protocol per device (SPMD, ring of n over the ``sp`` axis):
- K/V live in a double-buffered VMEM scratch ``[2, B, Skv, Hkv, D]``.
- Step i computes on slot ``i % 2`` while an RDMA pushes that same block
  to the right neighbor's slot ``(i+1) % 2``.
- Flow control is a capacity TOKEN flowing right->left: after a device
  finishes computing on a slot it RDMAs a tiny token to its LEFT
  neighbor, and the sender waits for a token before overwriting a slot
  remotely.  Without it a fast sender could clobber a slot the slow
  receiver is still reading (the ppermute version gets this ordering
  from XLA for free; here it is explicit).  A token DMA rather than a
  remote semaphore_signal so the same kernel runs under interpret mode
  (which implements remote DMA but not remote signals).
- Send semaphores are waited before the capacity signal releases our
  own source slot, so in-flight sends never race incoming writes.

Numerics are identical to the ppermute ring: same blockwise online
softmax, f32 accumulators, GQA expanded after the exchange (the wire
carries Hkv-sized blocks).  The backward pass reuses the ppermute
implementation via custom_vjp — gradients flow through the well-tested
path while the forward gets the overlap.

Works in interpret mode on the virtual CPU mesh (tests) and compiled on
real slices.  VMEM budget guard: callers should fall back to the
ppermute ring when ``2*kv_bytes + q/acc`` exceeds ~Mi budget (see
``fits_vmem``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30

# Conservative per-core VMEM budget for the kernel's working set.
_VMEM_BUDGET_BYTES = 96 * 1024 * 1024


def fits_vmem(B, Sq, Skv, Hq, Hkv, D, itemsize=2) -> bool:
    kv = 2 * 2 * B * Skv * Hkv * D * itemsize      # 2 tensors x 2 slots
    q = B * Sq * Hq * D * itemsize
    acc = B * Sq * Hq * D * 4                      # f32 value
    out = B * Sq * Hq * D * itemsize
    scores = B * Sq * Skv * 4                      # transient per (b,h)
    return (kv + q + acc + out + scores) < _VMEM_BUDGET_BYTES


def _mask(s, q_off, k_off):
    Sq, Skv = s.shape
    rows = q_off + lax.broadcasted_iota(jnp.int32, (Sq, Skv), 0)
    cols = k_off + lax.broadcasted_iota(jnp.int32, (Sq, Skv), 1)
    return jnp.where(cols <= rows, s, _NEG_INF)


def _ring_kernel(q_ref, k_ref, v_ref, o_ref, kbuf, vbuf, token,
                 send_k, send_v, recv_k, recv_v, cap_send, cap_recv,
                 *, axis_name, n, scale, causal, batch, heads_kv, group,
                 scalar_ids):
    my = lax.axis_index(axis_name)
    if scalar_ids:
        # Interpreter path: discharge rules support only scalar device
        # ids on a single-axis mesh (ring.py guarantees that).
        right_id = (my + 1) % n
        left_id = (my - 1) % n
    else:
        # Compiled path: MESH coordinate dicts — unspecified axes default
        # to our own coordinates, so the ring stays inside this
        # (dp, tp, ...) slice of a multi-axis mesh.
        right_id = {axis_name: (my + 1) % n}
        left_id = {axis_name: (my - 1) % n}

    B, Sq, Hq, D = q_ref.shape
    Skv = k_ref.shape[1]
    q_off = my * Sq

    # Seed slot 0 with the local shard (local DMA, immediate wait).
    cp_k = pltpu.make_async_copy(k_ref, kbuf.at[0], recv_k.at[0])
    cp_v = pltpu.make_async_copy(v_ref, vbuf.at[0], recv_v.at[0])
    cp_k.start()
    cp_v.start()
    cp_k.wait()
    cp_v.wait()

    q = q_ref[...]
    m = jnp.full((B, Hq, Sq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hq, Sq, 1), jnp.float32)
    acc = jnp.zeros((B, Sq, Hq, D), jnp.float32)

    for i in range(n):
        slot, nxt = i % 2, (i + 1) % 2

        rdma_k = rdma_v = None
        if i < n - 1:
            if i >= 1:
                # Right neighbor must be done computing on its slot
                # `nxt` (its step i-1) before we overwrite it: wait for
                # its capacity token to land.
                pltpu.make_async_copy(token, token, cap_recv).wait()
            rdma_k = pltpu.make_async_remote_copy(
                src_ref=kbuf.at[slot], dst_ref=kbuf.at[nxt],
                send_sem=send_k, recv_sem=recv_k.at[nxt],
                device_id=right_id,
                device_id_type=pltpu.DeviceIdType.MESH)
            rdma_v = pltpu.make_async_remote_copy(
                src_ref=vbuf.at[slot], dst_ref=vbuf.at[nxt],
                send_sem=send_v, recv_sem=recv_v.at[nxt],
                device_id=right_id,
                device_id_type=pltpu.DeviceIdType.MESH)
            rdma_k.start()
            rdma_v.start()

        # ---- compute on `slot` while the RDMA streams ----------------
        src = (my - i) % n                    # whose block we hold
        k_off = src * Skv
        for b in range(batch):
            for h in range(heads_kv):
                kb = kbuf[slot, b, :, h, :]               # [Skv, D]
                vb = vbuf[slot, b, :, h, :]
                for g in range(group):
                    hq = h * group + g
                    q2 = q[b, :, hq, :]                    # [Sq, D]
                    s = lax.dot_general(
                        q2, kb, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
                    if causal:
                        s = _mask(s, q_off, k_off)
                    bm = jnp.max(s, axis=-1, keepdims=True)   # [Sq,1]
                    p = jnp.exp(s - bm)
                    p = jnp.where(bm <= _NEG_INF / 2, 0.0, p)
                    bl = jnp.sum(p, axis=-1, keepdims=True)
                    pv = lax.dot_general(
                        p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)   # [Sq,D]
                    m_prev = m[b, hq]                          # [Sq,1]
                    m_new = jnp.maximum(m_prev, bm)
                    c_old = jnp.exp(m_prev - m_new)
                    c_new = jnp.exp(bm - m_new)
                    acc = acc.at[b, :, hq, :].set(
                        acc[b, :, hq, :] * c_old + pv * c_new)
                    l = l.at[b, hq].set(l[b, hq] * c_old + bl * c_new)
                    m = m.at[b, hq].set(m_new)

        if i < n - 1:
            # Source slot must be fully sent before we hand it back to
            # the left neighbor (its next send writes into it).
            rdma_k.wait_send()
            rdma_v.wait_send()
            if i < n - 2:
                tok = pltpu.make_async_remote_copy(
                    src_ref=token, dst_ref=token,
                    send_sem=cap_send, recv_sem=cap_recv,
                    device_id=left_id,
                    device_id_type=pltpu.DeviceIdType.MESH)
                tok.start()
                tok.wait_send()
            # Arrival of the next block (written by our left neighbor).
            pltpu.make_async_copy(kbuf.at[nxt], kbuf.at[nxt],
                                  recv_k.at[nxt]).wait()
            pltpu.make_async_copy(vbuf.at[nxt], vbuf.at[nxt],
                                  recv_v.at[nxt]).wait()

    l = jnp.where(l == 0.0, 1.0, l)
    # [B,Hq,Sq,1] -> [B,Sq,Hq,1]
    o_ref[...] = (acc / l.transpose(0, 2, 1, 3)).astype(o_ref.dtype)


def _ring_attention_fwd_sharded(q, k, v, *, axis_name, n, scale, causal,
                                interpret):
    B, Sq, Hq, D = q.shape
    Hkv = k.shape[2]
    kernel = functools.partial(
        _ring_kernel, axis_name=axis_name, n=n, scale=scale,
        causal=causal, batch=B, heads_kv=Hkv, group=Hq // Hkv,
        scalar_ids=interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2,) + k.shape, k.dtype),
            pltpu.VMEM((2,) + v.shape, v.dtype),
            pltpu.VMEM((8, 128), jnp.int32),    # capacity token
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),        # cap_send
            pltpu.SemaphoreType.DMA(()),        # cap_recv
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=7),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_attention_rdma(q, k, v, mesh: Mesh, axis_name: str = "sp",
                        causal: bool = True,
                        interpret: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """RDMA-overlapped ring attention; drop-in for
    parallel.ring.ring_attention (same sharding contract: S over
    ``axis_name``).

    Backward re-derives gradients through the ppermute ring's VJP from
    the saved (q, k, v): one recomputed forward plus the backward —
    the same cost shape as flash-attention backward or a remat policy
    (which training configs apply to attention anyway); a fused RDMA
    backward kernel is future work."""
    return _rdma_fwd_only(q, k, v, mesh, axis_name, causal, interpret,
                          scale)


def _rdma_fwd_only(q, k, v, mesh, axis_name, causal, interpret, scale=None):
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    n = mesh.shape[axis_name]
    fn = functools.partial(
        _ring_attention_fwd_sharded, axis_name=axis_name, n=n,
        scale=scale, causal=causal, interpret=interpret)
    spec = P(None, axis_name, None, None)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def _rdma_vjp_fwd(q, k, v, mesh, axis_name, causal, interpret, scale):
    out = _rdma_fwd_only(q, k, v, mesh, axis_name, causal, interpret, scale)
    return out, (q, k, v)


def _rdma_vjp_bwd(mesh, axis_name, causal, interpret, scale, res, g):
    from kuberay_tpu.parallel.ring import ring_attention as ppermute_ring
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: ppermute_ring(q, k, v, mesh, axis_name=axis_name,
                                      causal=causal, scale=scale), q, k, v)
    return vjp(g)


ring_attention_rdma.defvjp(_rdma_vjp_fwd, _rdma_vjp_bwd)
