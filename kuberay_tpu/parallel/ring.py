"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context attention where the sequence is sharded across devices and
K/V blocks rotate around the ICI ring (``lax.ppermute``), overlapping
compute with neighbor exchange — blockwise attention with online-softmax
combination, so no device ever materializes the full sequence
(SURVEY.md §2.3 SP/CP row; the reference delegates this entirely to user
code — here it is a first-class framework op).

The control plane contributes the physical half of the contract: stable
host ring order (topology.host_ring_order) and ``tpu.dev/host-index``
identity so the logical ``sp`` axis maps onto ICI neighbors.

Differentiable end-to-end (ppermute transposes to the reverse rotation).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attention(q, k, v, scale, q_offset, k_offset, causal):
    """Partial attention of a local q shard against ONE k/v block.

    q: [B, Sq, Hq, D]; k/v: [B, Skv, Hkv, D].  GQA is expanded HERE, after
    the ring exchange, so the ppermute carries only the Hkv-sized tensors
    (group x less ICI traffic).  Returns (pv [B,Sq,Hq,D] f32,
    m [B,Sq,Hq,1], l [B,Sq,Hq,1]) — unnormalized numerator, block max,
    block sum, for online combination.
    """
    group = q.shape[2] // k.shape[2]
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        rows = q_offset + jnp.arange(q.shape[1])[:, None]
        cols = k_offset + jnp.arange(k.shape[1])[None, :]
        s = jnp.where(cols <= rows, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                     # [B,H,Sq,1]
    # Guard fully-masked blocks: exp(-inf - -inf) -> use finite sentinel.
    p = jnp.exp(s - m)
    p = jnp.where(m <= _NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    # -> [B,Sq,H,1] layout for m/l
    return pv, m.transpose(0, 2, 1, 3), l.transpose(0, 2, 1, 3)


def _ring_attention_sharded(q, k, v, *, axis_name, scale, causal):
    """Runs INSIDE shard_map: q/k/v are local sequence shards."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, S_local, Hq, D = q.shape
    # K/V stay at Hkv heads in the ring carry; GQA expands per-block.
    q_offset = my * S_local

    def step(carry, i):
        kk, vv, m, l, acc = carry
        # Block i arrived from shard (my - i) mod n.
        src = (my - i) % n
        pv, bm, bl = _block_attention(q, kk, vv, scale, q_offset,
                                      src * S_local, causal)
        m_new = jnp.maximum(m, bm)
        corr_old = jnp.exp(m - m_new)
        corr_new = jnp.exp(bm - m_new)
        acc = acc * corr_old + pv * corr_new
        l = l * corr_old + bl * corr_new
        # Rotate k/v to the next neighbor (ICI ring).
        perm = [(j, (j + 1) % n) for j in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (kk, vv, m_new, l, acc), None

    m0 = jnp.full((B, S_local, Hq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, S_local, Hq, 1), jnp.float32)
    acc0 = jnp.zeros((B, S_local, Hq, D), jnp.float32)
    (_, _, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True,
                   scale: Optional[float] = None,
                   impl: str = "ppermute") -> jax.Array:
    """Sequence-parallel attention.  Global q/k/v: [B, S, H, D] with S
    sharded over ``axis_name``; output sharded the same way.

    ``impl``: 'ppermute' (XLA collective-permute ring, any shape) |
    'rdma' (Pallas make_async_remote_copy ring overlapping the neighbor
    exchange with block compute — parallel/ring_pallas.py; falls back to
    ppermute when the working set exceeds the VMEM budget) |
    'rdma_interpret' (same kernel, interpreter — virtual-mesh tests).
    """
    if impl.startswith("rdma"):
        from kuberay_tpu.parallel import ring_pallas
        n = mesh.shape[axis_name]
        B, S, Hq, D_ = q.shape
        interpret = impl == "rdma_interpret"
        # The interpreter's remote-DMA discharge supports only
        # single-axis meshes; compiled Mosaic handles the general case.
        multi_axis = len(mesh.axis_names) > 1
        # The kernel fully unrolls ring steps x (B, Hkv, group); cap the
        # unroll so huge rings fall back instead of exploding the Mosaic
        # program (a gridded kernel is future work).
        unroll = n * B * k.shape[2] * (Hq // k.shape[2])
        if (interpret and multi_axis) or unroll > 512 or \
                not ring_pallas.fits_vmem(
                    B, S // n, S // n, Hq, k.shape[2], D_,
                    q.dtype.itemsize):
            impl = "ppermute"
        else:
            return ring_pallas.ring_attention_rdma(
                q, k, v, mesh, axis_name, causal, interpret, scale)
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    fn = functools.partial(_ring_attention_sharded, axis_name=axis_name,
                           scale=scale, causal=causal)
    spec = P(None, axis_name, None, None)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
