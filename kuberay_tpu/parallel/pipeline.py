"""Pipeline parallelism: GPipe-style stage pipelining over the ``pp`` axis.

The SURVEY §2.3 PP row: the reference delegates pipeline placement to Ray
placement groups; here PP is a framework op.  TPU-first shape:

- stages = contiguous layer blocks; the stacked layer params
  ([n_layers, ...]) shard over ``pp`` along the layer axis, so each device
  holds exactly its stage's weights;
- microbatches stream through the stages with ``lax.ppermute``
  point-to-point activation transfers (ICI neighbors when the mesh is laid
  out along the ring, which topology.host_ring_order guarantees);
- the classic GPipe schedule: n_micro + n_stages - 1 ticks, the bubble
  shrinking as n_micro grows; everything is a single ``lax.scan`` under
  ``shard_map`` — one compiled program, no per-tick dispatch.

Differentiable end-to-end (scan + ppermute transpose cleanly), so the same
op serves training; the orchestration contract it needs from the control
plane is stage-per-slice placement with stable ring order (host-index
labels + megascale slice ids).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_sharded(stage_params, x_micro, *, layer_fn, axis_name,
                      n_stages):
    """Runs INSIDE shard_map.

    stage_params: this stage's layer stack [L/P, ...] (leading dim local).
    x_micro: [n_micro, mb, ...] full microbatch set (replicated input).
    Returns [n_micro, mb, ...] outputs (identical on every stage).
    """
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]

    def apply_stage(x):
        def body(h, lp):
            return layer_fn(h, lp), None
        out, _ = lax.scan(body, x, stage_params)
        return out

    zero = jnp.zeros_like(x_micro[0])

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 ingests microbatch t; others consume what arrived.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_in = jnp.where(stage == 0, x_micro[mb_idx], recv)
        # Active window: stage s processes microbatch t-s.
        active = jnp.logical_and(t - stage >= 0, t - stage < n_micro)
        y = jnp.where(active, apply_stage(x_in), zero)
        # Last stage banks its result at slot t-(P-1).
        out_slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        bank = jnp.logical_and(active, stage == n_stages - 1)
        outputs = jnp.where(
            bank,
            outputs.at[out_slot].set(y),
            outputs)
        # Hand activations to the next stage (ICI neighbor hop).
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        recv = lax.ppermute(y, axis_name, perm)
        return (recv, outputs), None

    outputs0 = jnp.zeros_like(x_micro)
    (_, outputs), _ = lax.scan(
        tick, (zero, outputs0), jnp.arange(n_micro + n_stages - 1))
    # Only the last stage holds real outputs; share them with every stage
    # (masked psum == broadcast from last stage).
    outputs = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def pipeline_apply(layer_fn: Callable, stacked_params: Any,
                   x: jax.Array, mesh: Mesh, axis_name: str = "pp",
                   n_microbatches: int = None) -> jax.Array:
    """Apply a stack of layers as a pipeline over ``axis_name``.

    layer_fn(h, layer_params) -> h  (one layer; same signature the models'
    scan bodies use).  stacked_params: pytree with leading [n_layers] dim,
    n_layers divisible by the pp axis size.  x: [batch, ...] activations;
    batch divisible by n_microbatches.
    """
    n_stages = mesh.shape[axis_name]
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % n_stages != 0:
        raise ValueError(f"{n_layers} layers not divisible into "
                         f"{n_stages} stages")
    n_micro = n_microbatches or n_stages
    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible into "
                         f"{n_micro} microbatches")
    x_micro = x.reshape(n_micro, batch // n_micro, *x.shape[1:])

    # Params shard over pp along the layer axis; activations replicate.
    param_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = functools.partial(_pipeline_sharded, layer_fn=layer_fn,
                           axis_name=axis_name, n_stages=n_stages)
    out = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x_micro)
    return out.reshape(batch, *x.shape[1:])
