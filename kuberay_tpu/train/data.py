"""Training data pipeline: native C++ loader with a NumPy fallback.

Token shards are flat little-endian uint32 files (the framework's on-disk
format; see tools for conversion).  The native loader
(native/dataloader.cpp) mmaps the shard and prefetches batches on C++
threads — the input pipeline never blocks the device step.  When no C++
toolchain is available the NumPy fallback provides identical batches
(same seed -> same order) at lower throughput.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
from typing import Dict, Iterator, Optional

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SRC = _NATIVE_DIR / "dataloader.cpp"


def _build_native():
    """Compile via the shared content-addressed builder (native/build.py)."""
    from kuberay_tpu.native.build import build_native
    return build_native("dataloader.cpp")


def _load_native():
    so = _build_native()
    if so is None:
        return None
    lib = ctypes.CDLL(str(so))
    lib.dl_open.restype = ctypes.c_void_p
    lib.dl_open.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                            ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    lib.dl_next.restype = ctypes.c_int
    lib.dl_next.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_uint32)]
    lib.dl_num_windows.restype = ctypes.c_int64
    lib.dl_num_windows.argtypes = [ctypes.c_void_p]
    lib.dl_num_tokens.restype = ctypes.c_int64
    lib.dl_num_tokens.argtypes = [ctypes.c_void_p]
    lib.dl_close.argtypes = [ctypes.c_void_p]
    return lib


_native_lib = None
_native_tried = False


def native_available() -> bool:
    global _native_lib, _native_tried
    if not _native_tried:
        _native_tried = True
        _native_lib = _load_native()
    return _native_lib is not None


class TokenShardLoader:
    """Iterates {"tokens", "targets"} batches from a token shard file."""

    def __init__(self, path: str, seq_len: int, batch: int,
                 seed: int = 0, shuffle: bool = True,
                 prefer_native: bool = True, n_threads: int = 1):
        """``n_threads=1`` (default) keeps batch order a pure function of
        (seed, epoch) — identical to the NumPy fallback.  Higher thread
        counts trade that determinism for prefetch throughput (rows are
        drawn from a shared atomic cursor in racy order)."""
        self.path = str(path)
        self.seq_len = seq_len
        self.batch = batch
        self.seed = seed
        self.shuffle = shuffle
        self._handle = None
        self._lib = None
        if prefer_native and native_available():
            self._lib = _native_lib
            self._handle = self._lib.dl_open(
                self.path.encode(), seq_len, batch,
                ctypes.c_uint64(seed), int(shuffle), n_threads)
            if not self._handle:
                self._lib = None
        if self._lib is None:
            self._tokens = np.memmap(self.path, dtype=np.uint32, mode="r")
            win = seq_len + 1
            self._n_windows = len(self._tokens) // win
            if self._n_windows < 1:
                raise ValueError(
                    f"shard {path} smaller than one window ({win} tokens)")
            self._cursor = 0

    @property
    def backend(self) -> str:
        return "native" if self._handle else "numpy"

    @property
    def num_windows(self) -> int:
        if self._handle:
            return int(self._lib.dl_num_windows(self._handle))
        return self._n_windows

    @staticmethod
    def _splitmix64(x: np.uint64) -> np.uint64:
        with np.errstate(over="ignore"):
            x = np.uint64(x) + np.uint64(0x9E3779B97F4A7C15)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            return x ^ (x >> np.uint64(31))

    def _numpy_batch(self) -> np.ndarray:
        win = self.seq_len + 1
        out = np.empty((self.batch, win), dtype=np.uint32)
        for r in range(self.batch):
            i = self._cursor
            self._cursor += 1
            epoch, within = divmod(i, self._n_windows)
            if self.shuffle:
                h = self._splitmix64(np.uint64(within) ^ self._splitmix64(
                    np.uint64(self.seed + epoch)))
                within = int(h % np.uint64(self._n_windows))
            out[r] = self._tokens[within * win:(within + 1) * win]
        return out

    def next(self) -> Dict[str, np.ndarray]:
        win = self.seq_len + 1
        if self._handle:
            buf = np.empty((self.batch, win), dtype=np.uint32)
            rc = self._lib.dl_next(
                self._handle,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
            if rc != 0:
                raise RuntimeError("native loader shut down")
            raw = buf
        else:
            raw = self._numpy_batch()
        tokens = raw[:, :-1].astype(np.int32)
        targets = raw[:, 1:].astype(np.int32)
        return {"tokens": tokens, "targets": targets}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.next()

    def close(self):
        if self._handle:
            self._lib.dl_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def write_token_shard(path: str, tokens: np.ndarray) -> None:
    """Write a uint32 token shard (the on-disk format)."""
    np.asarray(tokens, dtype=np.uint32).tofile(path)


def synthetic_shard(path: str, n_tokens: int, vocab: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    write_token_shard(path, rng.integers(0, vocab, n_tokens, dtype=np.uint32))
