"""In-pod training launcher: the consumer of the operator's env contract.

What a worker container actually runs.  Reads the identity the builders
injected (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / TPU_TOPOLOGY /
coordinator address / megascale vars — builders/pod.py), initializes
``jax.distributed``, builds the mesh, and runs the training loop.  The
reference's equivalent contract is RAY_ADDRESS + `ray start` inside the
container plus GKE's TPU webhook env (SURVEY.md §5.7/§5.8) — here it is
one first-party module:

    python -m kuberay_tpu.train.launcher --model llama_1b --steps 1000 \
        --data /data/shard.bin
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time
from typing import Optional


@dataclasses.dataclass
class WorkerIdentity:
    """Parsed slice identity (pure; unit-testable without hardware)."""

    worker_id: int
    num_workers: int
    hostnames: list
    topology: str
    coordinator: str          # jax.distributed coordinator address
    num_slices: int = 1
    slice_id: int = 0

    @classmethod
    def from_env(cls, env=None) -> "WorkerIdentity":
        from kuberay_tpu.utils import constants as C
        e = env or os.environ
        hostnames = [h for h in e.get(C.ENV_TPU_WORKER_HOSTNAMES, "").split(",")
                     if h]
        num = int(e.get(C.ENV_NUM_PROCESSES, len(hostnames) or 1))
        # jax.distributed coordinator = worker 0 (stable DNS via headless
        # service), on the MXLA port; single-host falls back to local.
        coord = hostnames[0] + f":{C.PORT_MXLA}" if hostnames else ""
        return cls(
            worker_id=int(e.get(C.ENV_TPU_WORKER_ID, "0")),
            num_workers=num,
            hostnames=hostnames,
            topology=e.get(C.ENV_TPU_TOPOLOGY, ""),
            coordinator=coord,
            num_slices=int(e.get(C.ENV_MEGASCALE_NUM_SLICES, "1")),
            slice_id=int(e.get(C.ENV_MEGASCALE_SLICE_ID, "0")),
        )

    @property
    def is_distributed(self) -> bool:
        return self.num_workers > 1 or self.num_slices > 1

    @property
    def global_process_id(self) -> int:
        return self.slice_id * self.num_workers + self.worker_id

    @property
    def global_process_count(self) -> int:
        return self.num_slices * self.num_workers


def initialize_distributed(ident: Optional[WorkerIdentity] = None):
    """jax.distributed bootstrap from the injected env (no-op single-host)."""
    ident = ident or WorkerIdentity.from_env()
    if not ident.is_distributed:
        return ident
    import jax
    jax.distributed.initialize(
        coordinator_address=ident.coordinator,
        num_processes=ident.global_process_count,
        process_id=ident.global_process_id)
    return ident


def build_mesh(tp: Optional[int] = None, sp: int = 1, ep: int = 1,
               num_slices: Optional[int] = None):
    """Single mesh over all devices.  Under multi-slice (the operator's
    MEGASCALE env contract, builders/pod.py:194-196) the mesh goes hybrid:
    pure data parallelism crosses slices on DCN, everything else stays on
    the slice's ICI (MeshSpec.build_multislice).  ``num_slices`` comes
    from WorkerIdentity (the single parser of the env contract); the env
    fallback serves direct library callers."""
    import jax
    from kuberay_tpu.parallel.mesh import MeshSpec
    n = len(jax.devices())
    tp = tp or min(n, jax.local_device_count())
    if num_slices is None:
        num_slices = WorkerIdentity.from_env().num_slices
    if num_slices > 1:
        return MeshSpec(dp=num_slices, fsdp=-1, tp=tp, sp=sp,
                        ep=ep).build_multislice(num_slices=num_slices)
    return MeshSpec(dp=1, fsdp=-1, tp=tp, sp=sp, ep=ep).build()


def _start_metrics_server(port: int):
    """Prometheus /metrics endpoint for the training process (worker 0).
    Returns (registry, server); never fatal — a busy port just logs."""
    from kuberay_tpu.utils.httpjson import JsonHandler, serve_background
    from kuberay_tpu.utils.metrics import MetricsRegistry
    from http.server import ThreadingHTTPServer
    reg = MetricsRegistry()
    for name, help_text in (
            ("tpu_train_step", "Last completed optimizer step"),
            ("tpu_train_loss", "Training loss at the last log interval"),
            ("tpu_train_tokens_per_sec", "Global training throughput"),
            ("tpu_train_step_seconds", "Mean step wall time"),
            ("tpu_train_mfu", "Model flops utilization vs chip peak")):
        reg.describe(name, help_text)

    class Handler(JsonHandler):
        def do_GET(self):
            if self.path == "/metrics":
                return self._send_text(200, reg.render(),
                                       "text/plain; version=0.0.4")
            return self._send_text(404, "unknown path")

    try:
        srv = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    except OSError as e:
        print(f"train metrics server disabled: {e}", flush=True)
        return reg, None
    serve_background(srv, "train-metrics")
    return reg, srv


def train(args) -> int:
    from kuberay_tpu.utils.platform import pin_platform_from_env
    pin_platform_from_env()
    import jax
    import jax.numpy as jnp
    from kuberay_tpu.models import llama
    from kuberay_tpu.train.train_step import (
        TrainConfig, make_sharded_train_fns)
    from kuberay_tpu.train.data import TokenShardLoader, synthetic_shard
    from kuberay_tpu.train import checkpoint as ckpt

    ident = initialize_distributed()
    cfg = llama.CONFIGS[args.model]
    mesh = build_mesh(tp=args.tp, sp=args.sp, num_slices=ident.num_slices)
    tc = TrainConfig(learning_rate=args.lr,
                     warmup_steps=min(args.warmup, max(1, args.steps // 10)),
                     decay_steps=args.steps,
                     param_dtype=args.param_dtype, mu_dtype=args.mu_dtype,
                     grad_accum=args.grad_accum)
    init, step_fn, shardings = make_sharded_train_fns(cfg, tc, mesh)

    state = None
    if args.checkpoint_dir:
        state = ckpt.restore_latest(args.checkpoint_dir, init,
                                    jax.random.PRNGKey(args.seed), shardings)
    if state is None:
        state = init(jax.random.PRNGKey(args.seed))

    if args.data:
        loader = TokenShardLoader(args.data, args.seq_len, args.batch,
                                  seed=args.seed)
    else:
        # Every worker generates its own local synthetic shard (/tmp is
        # per-host); pid suffix avoids races between co-located processes.
        path = f"/tmp/tpu-synthetic-shard-{ident.worker_id}-{os.getpid()}.bin"
        synthetic_shard(path, 2_000_000, cfg.vocab_size, args.seed)
        loader = TokenShardLoader(path, args.seq_len, args.batch,
                                  seed=args.seed)

    # Step-event reporting: EVERY worker posts per-step heartbeats to
    # the colocated coordinator (the straggler microscope's feed,
    # obs/steps.py — cross-host skew needs every host's step times, not
    # just the lead's); the lead additionally posts the train_step
    # summary at each log interval (the task/profile event stream the
    # history server replays, ref eventserver.go:838).  Off when no
    # coordinator address was injected; never fatal.
    from kuberay_tpu.utils import constants as C
    event_client = None
    if os.environ.get(C.ENV_COORDINATOR_ADDRESS):
        from kuberay_tpu.runtime.coordinator_client import (
            CoordinatorClient, dashboard_url)
        event_client = CoordinatorClient(
            dashboard_url(os.environ[C.ENV_COORDINATOR_ADDRESS]),
            timeout=2.0)
    job_id = os.environ.get("TPU_JOB_ID", "train")

    # Prometheus exposition on worker 0 (feeds the train Grafana
    # dashboard, ref config/grafana/train_grafana_dashboard.json):
    # TPU_TRAIN_METRICS_PORT=0 disables; default PORT_METRICS.
    prom, prom_srv = None, None
    mport = int(os.environ.get("TPU_TRAIN_METRICS_PORT", C.PORT_METRICS))
    if ident.worker_id == 0 and ident.slice_id == 0 and mport > 0:
        prom, prom_srv = _start_metrics_server(mport)
    n_params = sum(
        int(__import__("numpy").prod(x.shape))
        for x in jax.tree.leaves(state["params"]))
    peak_tflops = float(os.environ.get("TPU_PEAK_TFLOPS", "0"))
    if not peak_tflops:
        gen = os.environ.get(C.ENV_TPU_ACCELERATOR_TYPE, "")
        if gen:
            try:
                # get_generation resolves aliases (v5litepod, trillium,
                # ...) that GKE-injected env may carry.
                from kuberay_tpu.topology import get_generation
                peak_tflops = get_generation(
                    gen.split("-")[0]).bf16_tflops_per_chip
            except Exception:
                peak_tflops = 0.0

    # H2D/compute overlap: the NEXT batch is device_put while the
    # CURRENT step runs on device (dispatch is async, device_put is
    # non-blocking) — the input pipeline never serializes with the MXU.
    batch_sharding = getattr(step_fn, "batch_sharding", None)

    def put(raw):
        b = {"tokens": raw["tokens"], "targets": raw["targets"]}
        if batch_sharding is not None:
            return jax.device_put(b, batch_sharding)
        return {k: jnp.asarray(v) for k, v in b.items()}

    # Async checkpointing: one manager for the whole run; save_async
    # snapshots to host and writes in the background while training
    # continues (module-level ckpt.save would stall the step loop).
    writer = ckpt.CheckpointWriter(args.checkpoint_dir) \
        if args.checkpoint_dir else None
    try:
        return _train_loop(args, ident, state, step_fn, loader, put,
                           writer, prom, peak_tflops, n_params,
                           event_client, job_id)
    finally:
        if writer is not None:
            # Drain in-flight async writes on EVERY exit path — an
            # exception mid-loop must not abandon a half-committed
            # checkpoint (the crash case async checkpointing exists for).
            writer.close()


def _train_loop(args, ident, state, step_fn, loader, put, writer, prom,
                peak_tflops, n_params, event_client, job_id) -> int:
    import time
    import jax
    last_saved = -1
    is_lead = ident.worker_id == 0 and ident.slice_id == 0
    # Heartbeat identity + cadence: "s<slice>w<worker>" names the host
    # fleet-wide; durations buffer locally and batch-post every
    # --heartbeat-every steps (default: the log interval) so telemetry
    # adds one HTTP round-trip per interval, not per step.
    host = f"s{ident.slice_id}w{ident.worker_id}"
    hb_every = getattr(args, "heartbeat_every", 0) or args.log_every
    hb_buf = []                       # (step, wall seconds) per step

    start_step = int(state["step"])
    t0 = time.time()
    step_t0 = t0
    next_batch = put(loader.next()) if start_step < args.steps else None
    for i in range(start_step, args.steps):
        batch = next_batch
        state, metrics = step_fn(state, batch)
        if i + 1 < args.steps:
            next_batch = put(loader.next())   # overlaps the device step
        if event_client is not None:
            now = time.time()
            hb_buf.append((i + 1, now - step_t0))
            step_t0 = now
            if (i + 1) % hb_every == 0:
                # One device sync per batch: how long this host waits on
                # the step's collectives to finish, attributed to the
                # batch's last step (syncing every step would serialize
                # the async dispatch pipeline telemetry exists to watch).
                tw = time.time()
                jax.block_until_ready(metrics["loss"])
                wait = time.time() - tw
                tokens = float(args.batch * args.seq_len)
                beats = [{
                    "type": "step", "name": "step_heartbeat",
                    "job_id": job_id, "host": host,
                    "args": {"step": s, "dur_s": round(d, 6),
                             "tokens": tokens,
                             "collective_wait_s": 0.0},
                } for s, d in hb_buf]
                beats[-1]["args"]["collective_wait_s"] = round(wait, 6)
                beats[-1]["args"]["n_params"] = n_params
                beats[-1]["args"]["device_count"] = jax.device_count()
                if peak_tflops > 0:
                    beats[-1]["args"]["peak_tflops"] = peak_tflops
                try:
                    event_client.post_events(beats)
                except Exception:
                    event_client = None    # coordinator gone: stop trying
                hb_buf = []
                step_t0 = time.time()     # exclude the sync from step 1
        if (i + 1) % args.log_every == 0 and ident.worker_id == 0:
            loss = float(metrics["loss"])
            dt = time.time() - t0
            tok_s = args.batch * args.seq_len * args.log_every / dt
            print(f"step {i + 1} loss {loss:.4f} tok/s {tok_s:.0f}",
                  flush=True)
            if prom is not None:
                prom.set_gauge("tpu_train_step", float(i + 1))
                prom.set_gauge("tpu_train_loss", loss)
                prom.set_gauge("tpu_train_tokens_per_sec", tok_s)
                prom.set_gauge("tpu_train_step_seconds",
                               dt / args.log_every)
                if peak_tflops > 0:
                    # MFU = achieved flops / peak: 6N flops per token
                    # (fwd+bwd dense), per chip.
                    achieved = 6.0 * n_params * tok_s / 1e12 / max(
                        1, jax.device_count())
                    prom.set_gauge("tpu_train_mfu",
                                   achieved / peak_tflops)
            if is_lead and event_client is not None:
                try:
                    event_client.post_events([{
                        "type": "step", "name": "train_step",
                        "job_id": job_id, "ts": time.time() - dt,
                        "dur": dt,
                        "args": {"step": i + 1, "loss": loss,
                                 "tokens_per_sec": round(tok_s, 1)}}])
                except Exception:
                    event_client = None    # coordinator gone: stop trying
            t0 = time.time()
        if writer is not None and (i + 1) % args.checkpoint_every == 0:
            writer.save_async(state, i + 1)
            last_saved = i + 1
    if writer is not None:
        # Final save unless the last periodic save already covered it or
        # the run resumed at-or-past the final step (saving then would
        # label later-step state with an earlier step number).
        if last_saved != args.steps and start_step < args.steps:
            writer.save_async(state, args.steps)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpu-train-launcher")
    ap.add_argument("--model", default="llama_1b")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--warmup", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--grad-accum", type=int, default=1,
                    help="microbatches per optimizer step (grads summed "
                         "under lax.scan; batch must divide by it)")
    ap.add_argument("--param-dtype", default="",
                    help="master-weight dtype (e.g. float32 with a bf16 "
                         "model); default: model compute dtype")
    ap.add_argument("--mu-dtype", default="",
                    help="Adam first-moment dtype (bfloat16 halves that "
                         "optimizer slice)")
    ap.add_argument("--tp", type=int, default=None)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--data", default="", help="token shard path")
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--checkpoint-every", type=int, default=500)
    ap.add_argument("--log-every", type=int, default=10)
    ap.add_argument("--heartbeat-every", type=int, default=0,
                    help="steps per step-heartbeat batch to the "
                         "coordinator (straggler microscope); 0 = the "
                         "log interval")
    args = ap.parse_args(argv)
    for flag in ("param_dtype", "mu_dtype"):
        val = getattr(args, flag)
        if val:
            # Validate against what the runtime will actually do: must
            # be a dtype JAX knows AND floating (int/bool would silently
            # truncate weights to garbage).
            try:
                import jax.numpy as _jnp
                ok = _jnp.issubdtype(_jnp.dtype(val), _jnp.floating)
            except TypeError:
                ok = False
            if not ok:
                ap.error(f"--{flag.replace('_', '-')}: {val!r} is not a "
                         f"floating dtype (use e.g. float32, bfloat16)")
    return train(args)


if __name__ == "__main__":
    sys.exit(main())
