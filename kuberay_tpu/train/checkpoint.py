"""Checkpoint/resume via Orbax (SURVEY.md §5.4).

The reference delegates application checkpointing entirely to user code;
here it is first-class: sharded async-capable saves of the full train
state (params + optimizer + step), restore onto a (possibly different)
mesh via target shardings, and retention pruning.  Control-plane
resume-after-restart stays free (CR status in the store), exactly like
the reference's level-triggered design.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

import jax


def _manager(directory: str, keep: int = 3):
    import orbax.checkpoint as ocp
    options = ocp.CheckpointManagerOptions(max_to_keep=keep, create=True)
    return ocp.CheckpointManager(os.path.abspath(directory), options=options)


def save(directory: str, state: Dict[str, Any], step: int,
         keep: int = 3) -> None:
    import orbax.checkpoint as ocp
    mgr = _manager(directory, keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()


class CheckpointWriter:
    """Async checkpointing for the training loop.

    ``save()`` (module-level) builds and tears down a CheckpointManager
    per call AND blocks until bytes are on disk — fine for tests and
    one-shot final saves, but inside a step loop it stalls the device
    for the whole serialize+write.  This writer holds ONE manager and
    uses Orbax's async path: ``save_async`` returns once device arrays
    are snapshotted to host (so the next step may donate/overwrite
    them), and the write itself overlaps subsequent compute — the
    standard large-model TPU training overlap.  Orbax serializes
    overlapping saves internally (a new save waits for the previous
    commit), so callers just fire-and-forget per interval and call
    ``close()`` (or ``wait()``) before exiting.
    """

    def __init__(self, directory: str, keep: int = 3):
        self._mgr = _manager(directory, keep)

    def save_async(self, state: Dict[str, Any], step: int) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(directory: str, step: int, abstract_state) -> Dict[str, Any]:
    """``abstract_state``: jax.ShapeDtypeStruct tree (with shardings) of the
    target state — restores laid out directly on the mesh."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory)
    out = mgr.restore(step, args=ocp.args.StandardRestore(abstract_state))
    mgr.close()
    return out


def restore_latest(directory: str, init_fn: Callable, init_key,
                   shardings=None) -> Optional[Dict[str, Any]]:
    """Restore the newest checkpoint, shaped like ``init_fn(init_key)``;
    None when no checkpoint exists."""
    step = latest_step(directory)
    if step is None:
        return None
    abstract = jax.eval_shape(init_fn, init_key)
    if shardings is not None:
        abstract = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, shardings)
    return restore(directory, step, abstract)
