"""Checkpoint/resume via Orbax (SURVEY.md §5.4).

The reference delegates application checkpointing entirely to user code;
here it is first-class: sharded async-capable saves of the full train
state (params + optimizer + step), restore onto a (possibly different)
mesh via target shardings, and retention pruning.  Control-plane
resume-after-restart stays free (CR status in the store), exactly like
the reference's level-triggered design.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

import jax


def _manager(directory: str, keep: int = 3):
    import orbax.checkpoint as ocp
    options = ocp.CheckpointManagerOptions(max_to_keep=keep, create=True)
    return ocp.CheckpointManager(os.path.abspath(directory), options=options)


def save(directory: str, state: Dict[str, Any], step: int,
         keep: int = 3) -> None:
    import orbax.checkpoint as ocp
    mgr = _manager(directory, keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()


class CheckpointWriter:
    """Async checkpointing for the training loop.

    ``save()`` (module-level) builds and tears down a CheckpointManager
    per call AND blocks until bytes are on disk — fine for tests and
    one-shot final saves, but inside a step loop it stalls the device
    for the whole serialize+write.  This writer holds ONE manager and
    uses Orbax's async path: ``save_async`` returns once device arrays
    are snapshotted to host (so the next step may donate/overwrite
    them), and the write itself overlaps subsequent compute — the
    standard large-model TPU training overlap.  Orbax serializes
    overlapping saves internally (a new save waits for the previous
    commit), so callers just fire-and-forget per interval and call
    ``close()`` (or ``wait()``) before exiting.

    Background failures are sticky: a commit that dies on the write
    thread only surfaces at the next manager interaction, so a loop
    whose FINAL save fails would otherwise exit "cleanly" with a
    missing checkpoint.  The first failure observed is stored and
    re-raised by ``wait()`` and ``close()`` (which still closes the
    manager), and ``save_async`` refuses to start a new save on top of
    an unacknowledged failure.
    """

    def __init__(self, directory: str, keep: int = 3):
        self._mgr = _manager(directory, keep)
        self._error: Optional[BaseException] = None

    def save_async(self, state: Dict[str, Any], step: int) -> None:
        if self._error is not None:
            raise self._error
        import orbax.checkpoint as ocp
        try:
            self._mgr.save(step, args=ocp.args.StandardSave(state))
        except Exception as e:
            # Orbax raises the PREVIOUS save's background failure here;
            # keep it so wait()/close() see it too.
            self._error = e
            raise

    def wait(self) -> None:
        try:
            self._mgr.wait_until_finished()
        except Exception as e:
            if self._error is None:
                self._error = e
        if self._error is not None:
            raise self._error

    def close(self) -> None:
        try:
            self._mgr.wait_until_finished()
        except Exception as e:
            if self._error is None:
                self._error = e
        finally:
            self._mgr.close()
        if self._error is not None:
            raise self._error

    def __enter__(self) -> "CheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(directory: str, step: int, abstract_state) -> Dict[str, Any]:
    """``abstract_state``: jax.ShapeDtypeStruct tree (with shardings) of the
    target state — restores laid out directly on the mesh."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory)
    out = mgr.restore(step, args=ocp.args.StandardRestore(abstract_state))
    mgr.close()
    return out


def load_params_for_serving(directory: str, step: Optional[int] = None,
                            shardings=None, dtype=None):
    """Restore just the model params from a TRAIN checkpoint for the
    serve path (the train-to-serve handoff: the serving process needs
    weights, not optimizer state).

    Restores without an abstract tree (host numpy in the saved
    structure — host RAM holds the full state briefly, which dwarfs any
    chip), extracts ``state["params"]``, optionally casts and lays the
    result out on a serve mesh via ``shardings`` (a params-shaped tree
    of NamedShardings).  Returns None when no checkpoint exists."""
    # Validate the step against what exists BEFORE touching orbax state:
    # an explicit missing step must return None (clean caller error),
    # not a raw orbax traceback — and a typo'd directory must not be
    # created as a side effect (the manager runs with create=True).
    if not os.path.isdir(directory):
        return None
    import orbax.checkpoint as ocp
    # ONE manager for step resolution + restore (each construction
    # rescans the directory — on the realistic /ckpt network mount that
    # latency multiplies per serve-pod start).
    mgr = _manager(directory)
    try:
        steps = set(mgr.all_steps())
        if not steps:
            return None
        if step is None:
            step = max(steps)
        elif step not in steps:
            return None
        # No abstract target: restores host numpy in the saved
        # structure (safe here — we only extract the params subtree and
        # re-lay it out below; the train path keeps using the targeted
        # restore()).
        state = mgr.restore(step, args=ocp.args.StandardRestore())
    finally:
        mgr.close()
    params = state["params"]
    if dtype is not None:
        # Cast ON HOST (numpy + ml_dtypes): casting via jnp would place
        # every leaf on the default device, making one chip briefly hold
        # the whole model and defeating a sharded tp restore.
        import numpy as _np
        np_dtype = _np.dtype(dtype) if dtype != jax.numpy.bfloat16 \
            else __import__("ml_dtypes").bfloat16
        params = jax.tree.map(
            lambda x: _np.asarray(x).astype(np_dtype), params)
    if shardings is not None:
        # Sharded device_put from host: each device receives only its
        # shard — the full model never lands on a single chip.
        params = jax.device_put(params, shardings)
    else:
        params = jax.tree.map(jax.numpy.asarray, params)
    return params


def restore_latest(directory: str, init_fn: Callable, init_key,
                   shardings=None) -> Optional[Dict[str, Any]]:
    """Restore the newest checkpoint, shaped like ``init_fn(init_key)``;
    None when no checkpoint exists."""
    step = latest_step(directory)
    if step is None:
        return None
    abstract = jax.eval_shape(init_fn, init_key)
    if shardings is not None:
        abstract = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, shardings)
    return restore(directory, step, abstract)
