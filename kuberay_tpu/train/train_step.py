"""pjit train step: sharded init, AdamW, bf16 compute, donated state.

The multi-chip path BASELINE config #3 exercises: params/optimizer sharded
by the logical rules (parallel/mesh.py), batch split over (dp, fsdp), XLA
inserts the all-gathers/reduce-scatters over ICI.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kuberay_tpu.models import llama
from kuberay_tpu.parallel.mesh import DEFAULT_RULES, logical_to_sharding


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    decay_steps: int = 10000
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    z_loss: float = 1e-4
    # Mixed-precision knobs.  param_dtype: master-weight dtype ("" = the
    # model config's compute dtype).  The classic TPU recipe is fp32
    # masters + bf16 compute: the step casts params to cfg.dtype for the
    # forward, so gradients and Adam statistics come back in
    # param_dtype.  mu_dtype: Adam first-moment dtype ("" = param
    # dtype); "bfloat16" halves that slice of optimizer HBM.
    param_dtype: str = ""
    mu_dtype: str = ""
    # Gradient accumulation: >1 splits each batch into that many
    # microbatches, sums grads over a lax.scan, and applies ONE optimizer
    # update — large effective batches without the activation memory
    # (composes with remat; batch size must divide by it).
    grad_accum: int = 1


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    # Short runs: warmup can never consume the whole schedule (optax
    # requires decay_steps > warmup_steps).
    warmup = min(tc.warmup_steps, max(0, tc.decay_steps - 1))
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=tc.learning_rate,
        warmup_steps=warmup, decay_steps=max(tc.decay_steps, warmup + 1),
        end_value=tc.learning_rate * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(schedule, b1=tc.beta1, b2=tc.beta2,
                    weight_decay=tc.weight_decay,
                    mu_dtype=jnp.dtype(tc.mu_dtype) if tc.mu_dtype
                    else None),
    )


def _cast_floating(tree, dtype):
    """Cast every floating leaf (integer/bool leaves untouched)."""
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


def init_train_state(cfg: llama.LlamaConfig, optimizer, key,
                     param_dtype: str = "") -> Dict[str, Any]:
    params = llama.init_params(cfg, key)
    if param_dtype:
        params = _cast_floating(params, param_dtype)
    return {
        "step": jnp.zeros((), jnp.int32),
        "params": params,
        "opt_state": optimizer.init(params),
    }


def _compute_cast(cfg, tc: TrainConfig, params):
    """Master weights -> compute dtype for the forward (no-op when they
    already match; XLA elides the identity convert)."""
    if not tc.param_dtype or jnp.dtype(tc.param_dtype) == jnp.dtype(cfg.dtype):
        return params
    return _cast_floating(params, cfg.dtype)


# --------------------------------------------------------------------------
# Sharding of the train state
# --------------------------------------------------------------------------

def param_shardings(cfg: llama.LlamaConfig, mesh: Mesh,
                    rules: Optional[Dict[str, Any]] = None):
    rules = rules or DEFAULT_RULES
    axes = llama.param_axes(cfg)
    return jax.tree.map(
        lambda a: logical_to_sharding(rules, mesh, a), axes,
        is_leaf=lambda x: isinstance(x, tuple))


def _shard_opt_like_params(opt_state, param_sh, mesh: Mesh):
    """Optimizer-state shardings: components tree-isomorphic to params
    (adam mu/nu) inherit param shardings; everything else replicates."""
    pdef = jax.tree.structure(param_sh)
    p_leaves = jax.tree.leaves(param_sh)
    repl = NamedSharding(mesh, P())

    def map_component(comp):
        cdef = jax.tree.structure(comp)
        if cdef == pdef:
            return jax.tree.unflatten(cdef, p_leaves)
        return jax.tree.map(lambda _: repl, comp)

    def walk(node):
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(map_component(f) for f in node))
        if isinstance(node, tuple):
            return type(node)(walk(c) for c in node)
        return map_component(node)

    return walk(opt_state)


def state_shardings(cfg: llama.LlamaConfig, optimizer, mesh: Mesh,
                    rules: Optional[Dict[str, Any]] = None):
    p_sh = param_shardings(cfg, mesh, rules)
    abstract = jax.eval_shape(
        lambda: optax.GradientTransformation(optimizer.init, optimizer.update
                                             ).init(
            jax.eval_shape(functools.partial(llama.init_params, cfg),
                           jax.random.PRNGKey(0))))
    return {
        "step": NamedSharding(mesh, P()),
        "params": p_sh,
        "opt_state": _shard_opt_like_params(abstract, p_sh, mesh),
    }


# --------------------------------------------------------------------------
# Steps
# --------------------------------------------------------------------------

def _value_and_grad_accum(loss_fn: Callable, params, batch,
                          accum: int):
    """value_and_grad, optionally accumulated over ``accum`` microbatches
    (one fwd+bwd per microbatch under lax.scan, grads summed then
    averaged — numerically the mean-loss gradient since every microbatch
    holds batch/accum rows).  ``loss_fn(params, batch) -> (loss, aux)``.
    """
    if accum <= 1:
        return jax.value_and_grad(loss_fn, has_aux=True)(params, batch)

    def split(x):
        assert x.shape[0] % accum == 0, \
            f"batch {x.shape[0]} not divisible by grad_accum {accum}"
        # INTERLEAVED split ([B] -> [B/A, A] -> scan axis A): microbatch
        # k takes rows k, k+A, k+2A...  Keeping the (sharded) batch axis
        # leading preserves its (dp, fsdp) layout — the contiguous
        # [A, B/A] reshape would split the sharded dim and force an
        # involuntary reshard per step.  Row partition is irrelevant to
        # the weighted-mean math.
        return jnp.moveaxis(
            x.reshape(x.shape[0] // accum, accum, *x.shape[1:]), 1, 0)

    micro = jax.tree.map(split, batch)

    def wcount(mb):
        # Microbatch weight = its REAL token count, so a masked batch
        # reproduces the full-batch masked mean (equal-weight averaging
        # would overweight sparse microbatches' tokens).
        m = mb.get("mask")
        if m is not None:
            return m.astype(jnp.float32).sum()
        return jnp.float32(mb["tokens"].shape[0] * mb["tokens"].shape[1])

    def body(carry, mb):
        gsum, lsum, wsum = carry
        (l, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(params, mb)
        w = wcount(mb)
        # Accumulate in f32: bf16 sums would round away small
        # per-microbatch contributions at large accum.
        gsum = jax.tree.map(
            lambda s, x: s + x.astype(jnp.float32) * w, gsum, g)
        return (gsum, lsum + l * w, wsum + w), (aux, w)

    zeros = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (gsum, lsum, wsum), (auxs, ws) = jax.lax.scan(
        body, (zeros, jnp.float32(0), jnp.float32(0)), micro)
    grads = jax.tree.map(
        lambda s, p: (s / wsum).astype(p.dtype), gsum, params)
    # Aux metrics get the SAME token weighting as the gradients — an
    # equal-weight mean would misreport loss/accuracy under skewed masks.
    aux = jax.tree.map(
        lambda a: jnp.tensordot(ws, a, axes=(0, 0)) / wsum, auxs)
    return (lsum / wsum, aux), grads


def make_train_step(cfg: llama.LlamaConfig, tc: TrainConfig,
                    optimizer) -> Callable:
    """Unsharded (single-device / auto-sharded) jitted train step."""

    def step(state, batch):
        def loss(params, b):
            return llama.loss_fn(cfg, _compute_cast(cfg, tc, params),
                                 b["tokens"],
                                 b["targets"], b.get("mask"),
                                 tc.z_loss)
        (l, metrics), grads = _value_and_grad_accum(
            loss, state["params"], batch, tc.grad_accum)
        updates, new_opt = optimizer.update(grads, state["opt_state"],
                                            state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        metrics["grad_norm"] = optax.global_norm(grads)
        metrics["total_loss"] = l
        return {
            "step": state["step"] + 1,
            "params": new_params,
            "opt_state": new_opt,
        }, metrics

    return jax.jit(step, donate_argnums=(0,))


def make_sharded_train_fns(cfg: llama.LlamaConfig, tc: TrainConfig,
                           mesh: Mesh,
                           rules: Optional[Dict[str, Any]] = None):
    """Returns (sharded_init, sharded_step, state_shardings).

    ``sharded_init(key)`` materializes the state already laid out on the
    mesh (no host-memory spike); ``sharded_step(state, batch)`` is the
    donated pjit train step.  Batch arrays shard over (dp, fsdp).
    """
    optimizer = make_optimizer(tc)
    sh = state_shardings(cfg, optimizer, mesh, rules)
    # Sequence axis shards over sp (long-context); batch over (dp, fsdp).
    batch_sh = NamedSharding(mesh, P(("dp", "fsdp"),
                                     "sp" if mesh.shape.get("sp", 1) > 1
                                     else None))

    init = jax.jit(
        functools.partial(init_train_state, cfg, optimizer,
                          param_dtype=tc.param_dtype),
        out_shardings=sh)

    def step(state, batch):
        def loss(params, b):
            # mask is threaded through (not dropped) so the loss agrees
            # with _value_and_grad_accum's token-count microbatch
            # weighting when a masked batch reaches the sharded path.
            return llama.loss_fn(cfg, _compute_cast(cfg, tc, params),
                                 b["tokens"],
                                 b["targets"], b.get("mask"), tc.z_loss,
                                 mesh=mesh)
        (l, metrics), grads = _value_and_grad_accum(
            loss, state["params"], batch, tc.grad_accum)
        updates, new_opt = optimizer.update(grads, state["opt_state"],
                                            state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        metrics["grad_norm"] = optax.global_norm(grads)
        metrics["total_loss"] = l
        return {
            "step": state["step"] + 1,
            "params": new_params,
            "opt_state": new_opt,
        }, metrics

    # batch_sh is a pytree-prefix sharding: every batch leaf (tokens,
    # targets, optional mask — all [B, S]) shards over (dp/fsdp, sp).
    step_jit = jax.jit(
        step,
        in_shardings=(sh, batch_sh),
        out_shardings=(sh, None),
        donate_argnums=(0,))
    # Exposed so the launcher can device_put the NEXT batch while the
    # current step runs (H2D/compute overlap); an attribute keeps the
    # 3-tuple return contract for existing callers.
    step_jit.batch_sharding = batch_sh
    return init, step_jit, sh
