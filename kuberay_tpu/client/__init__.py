"""Python SDK (the python-client analogue, ref clients/python-client):
typed CR APIs with wait-helpers + a builder/director for cluster specs.

    from kuberay_tpu.client import (ApiClient, TpuClusterApi, TpuJobApi,
                                    TpuServiceApi, ClusterBuilder, Director)

    api = ApiClient("http://operator:8765")
    clusters = TpuClusterApi(api)
    clusters.create(Director().build_small_cluster("demo"))
    clusters.wait_until_ready("demo", timeout=300)
"""

from kuberay_tpu.cli.client import ApiClient, ApiError
from kuberay_tpu.client.apis import (
    ComputeTemplateApi,
    TpuClusterApi,
    TpuJobApi,
    TpuServiceApi,
    WaitTimeout,
)
from kuberay_tpu.client.builder import ClusterBuilder, Director, utils

__all__ = ["ApiClient", "ApiError", "ComputeTemplateApi", "TpuClusterApi",
           "TpuJobApi", "TpuServiceApi", "WaitTimeout", "ClusterBuilder",
           "Director", "utils"]
