"""Cluster builder + director presets (ref kuberay_cluster_builder.py
ClusterBuilder/Director:48-310 and kuberay_cluster_utils.py
ClusterUtils:21-425, re-shaped for TPU slices: worker groups are sized
in SLICES of a (tpuVersion, topology) pair, not replica counts, and the
presets step through real TPU slice shapes instead of cpu/memory
tiers)."""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from kuberay_tpu.topology import SliceTopology


class ClusterBuilder:
    """Fluent spec builder; ``build()`` returns a TpuCluster dict that
    passes utils/validation.py (topologies are validated eagerly via
    topology.SliceTopology so mistakes fail at build time, not at
    admission)."""

    def __init__(self):
        self._meta: Dict[str, Any] = {}
        self._head: Optional[Dict[str, Any]] = None
        self._groups: List[Dict[str, Any]] = []
        self._spec_extras: Dict[str, Any] = {}
        self._autoscale_band: Optional[tuple] = None

    def with_meta(self, name: str, namespace: str = "default",
                  labels: Optional[Dict[str, str]] = None,
                  annotations: Optional[Dict[str, str]] = None
                  ) -> "ClusterBuilder":
        self._meta = {"name": name, "namespace": namespace}
        if labels:
            self._meta["labels"] = dict(labels)
        if annotations:
            self._meta["annotations"] = dict(annotations)
        return self

    def with_head(self, image: str = "tpu-runtime:latest",
                  cpu: str = "2", memory: str = "4Gi",
                  env: Optional[Dict[str, str]] = None,
                  enable_ingress: bool = False) -> "ClusterBuilder":
        container = {
            "name": "head", "image": image,
            "resources": {"requests": {"cpu": cpu, "memory": memory},
                          "limits": {"cpu": cpu, "memory": memory}},
        }
        if env:
            container["env"] = [{"name": k, "value": v}
                                for k, v in sorted(env.items())]
        self._head = {"template": {"spec": {"containers": [container]}}}
        if enable_ingress:
            self._head["enableIngress"] = True
        return self

    def with_worker_group(self, group_name: str = "workers",
                          tpu_version: str = "v5e", topology: str = "2x4",
                          num_slices: int = 1,
                          image: str = "tpu-runtime:latest",
                          env: Optional[Dict[str, str]] = None,
                          compute_template: str = "",
                          ) -> "ClusterBuilder":
        """Add a worker group.  ``compute_template`` names a ComputeTemplate
        CR (or builtin preset) that the operator resolves server-side; when
        set, tpu_version/topology are ignored (the template is
        authoritative for the slice shape)."""
        if not compute_template:
            SliceTopology.create(tpu_version, topology)   # validate eagerly
        container = {"name": "worker", "image": image}
        if env:
            container["env"] = [{"name": k, "value": v}
                                for k, v in sorted(env.items())]
        group: Dict[str, Any] = {
            "groupName": group_name,
            "replicas": num_slices,
            "maxReplicas": max(num_slices, 1),
            "template": {"spec": {"containers": [container]}},
        }
        if compute_template:
            group["computeTemplate"] = compute_template
        else:
            group["accelerator"] = tpu_version
            group["topology"] = topology
        self._groups.append(group)
        return self

    def with_suspend(self, suspend: bool = True) -> "ClusterBuilder":
        self._spec_extras["suspend"] = suspend
        return self

    def with_autoscaling(self, min_slices: int, max_slices: int,
                         idle_timeout_seconds: int = 60,
                         upscaling_mode: str = "Default"
                         ) -> "ClusterBuilder":
        """Enable the in-tree slice autoscaler.  The min/max band applies
        to every worker group at ``build()`` time (per-group bands are
        group-spec fields; the options object holds behavior knobs only),
        so call order relative to with_worker_group doesn't matter."""
        self._spec_extras["enableInTreeAutoscaling"] = True
        self._spec_extras["autoscalerOptions"] = {
            "idleTimeoutSeconds": idle_timeout_seconds,
            "upscalingMode": upscaling_mode}
        self._autoscale_band = (min_slices, max_slices)
        return self

    def build(self) -> Dict[str, Any]:
        if not self._meta.get("name"):
            raise ValueError("with_meta(name=...) is required")
        if self._head is None:
            self.with_head()
        if getattr(self, "_autoscale_band", None):
            lo, hi = self._autoscale_band
            for g in self._groups:
                g["minReplicas"] = lo
                g["maxReplicas"] = hi
                g["replicas"] = min(max(g.get("replicas", 1), lo), hi)
        spec: Dict[str, Any] = {"headGroupSpec": self._head}
        if self._groups:
            spec["workerGroupSpecs"] = self._groups
        spec.update(self._spec_extras)
        return {"apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
                "metadata": dict(self._meta), "spec": spec}


class Director:
    """Size presets (ref Director.build_{basic,small,medium,large}_cluster,
    kuberay_cluster_builder.py:195-310).  TPU sizing ladder:

      basic   head only (no TPU slices — control/dev pod)
      small   1 slice  of v5e 2x4   (8 chips, single host)
      medium  1 slice  of v5e 4x8   (32 chips, 4 hosts)
      large   4 slices of v6e 4x8   (128 chips, 16 hosts)
    """

    def build_basic_cluster(self, name: str, namespace: str = "default",
                            labels: Optional[dict] = None) -> dict:
        return (ClusterBuilder()
                .with_meta(name, namespace, labels)
                .with_head()
                .build())

    def build_small_cluster(self, name: str, namespace: str = "default",
                            labels: Optional[dict] = None) -> dict:
        return (ClusterBuilder()
                .with_meta(name, namespace, labels)
                .with_head()
                .with_worker_group("workers", "v5e", "2x4", 1)
                .build())

    def build_medium_cluster(self, name: str, namespace: str = "default",
                             labels: Optional[dict] = None) -> dict:
        return (ClusterBuilder()
                .with_meta(name, namespace, labels)
                .with_head(cpu="4", memory="8Gi")
                .with_worker_group("workers", "v5e", "4x8", 1)
                .build())

    def build_large_cluster(self, name: str, namespace: str = "default",
                            labels: Optional[dict] = None) -> dict:
        return (ClusterBuilder()
                .with_meta(name, namespace, labels)
                .with_head(cpu="8", memory="16Gi")
                .with_worker_group("workers", "v6e", "4x8", 4)
                .build())

    def build_job(self, name: str, entrypoint: str,
                  cluster_spec: Optional[dict] = None,
                  namespace: str = "default",
                  shutdown_after_finish: bool = True,
                  backoff_limit: int = 0,
                  deadline_seconds: int = 0,
                  submission_mode: str = "") -> dict:
        """TpuJob wrapper around a cluster spec (the RayJob analogue).
        ``submission_mode``: "" (operator default: K8sJobMode submitter) |
        HTTPMode | SidecarMode."""
        if cluster_spec is None:
            cluster_spec = self.build_small_cluster(name, namespace)["spec"]
        spec: Dict[str, Any] = {
            "entrypoint": entrypoint,
            "clusterSpec": cluster_spec,
            "shutdownAfterJobFinishes": shutdown_after_finish,
        }
        if submission_mode:
            spec["submissionMode"] = submission_mode
        if backoff_limit:
            spec["backoffLimit"] = backoff_limit
        if deadline_seconds:
            spec["activeDeadlineSeconds"] = deadline_seconds
        return {"apiVersion": "tpu.dev/v1", "kind": "TpuJob",
                "metadata": {"name": name, "namespace": namespace},
                "spec": spec}

    def build_service(self, name: str, serve_config: dict,
                      cluster_spec: Optional[dict] = None,
                      namespace: str = "default") -> dict:
        if cluster_spec is None:
            cluster_spec = self.build_small_cluster(name, namespace)["spec"]
        return {"apiVersion": "tpu.dev/v1", "kind": "TpuService",
                "metadata": {"name": name, "namespace": namespace},
                "spec": {"serveConfigV2": serve_config,
                         "clusterSpec": cluster_spec}}


class utils:
    """Spec-surgery helpers (ref ClusterUtils, kuberay_cluster_utils.py:
    21-425) as static functions over plain dicts."""

    @staticmethod
    def update_worker_group_slices(cluster: dict, group_name: str,
                                   num_slices: int) -> dict:
        out = copy.deepcopy(cluster)
        for g in out["spec"].get("workerGroupSpecs", []):
            if g.get("groupName") == group_name:
                g.pop("numSlices", None)   # stale alias must not shadow
                g["replicas"] = num_slices
                if g.get("maxReplicas", 1) < num_slices:
                    g["maxReplicas"] = num_slices
                if g.get("minReplicas", 0) > num_slices:
                    g["minReplicas"] = num_slices
                return out
        raise KeyError(f"worker group {group_name!r} not found")

    @staticmethod
    def duplicate_worker_group(cluster: dict, group_name: str,
                               new_name: str) -> dict:
        """ref duplicate_worker_group (kuberay_cluster_utils.py:384)."""
        out = copy.deepcopy(cluster)
        groups = out["spec"].get("workerGroupSpecs", [])
        if any(g.get("groupName") == new_name for g in groups):
            raise ValueError(f"group {new_name!r} already exists")
        for g in groups:
            if g.get("groupName") == group_name:
                dup = copy.deepcopy(g)
                dup["groupName"] = new_name
                groups.append(dup)
                return out
        raise KeyError(f"worker group {group_name!r} not found")

    @staticmethod
    def delete_worker_group(cluster: dict, group_name: str) -> dict:
        """ref delete_worker_group (kuberay_cluster_utils.py:425)."""
        out = copy.deepcopy(cluster)
        groups = out["spec"].get("workerGroupSpecs", [])
        kept = [g for g in groups if g.get("groupName") != group_name]
        if len(kept) == len(groups):
            raise KeyError(f"worker group {group_name!r} not found")
        out["spec"]["workerGroupSpecs"] = kept
        return out
