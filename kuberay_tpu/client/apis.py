"""Typed CR APIs with wait-helpers (ref kuberay_cluster_api.py
RayClusterApi:52-282 and kuberay_job_api.py RayjobApi:58-368, rebuilt
over this repo's REST apiserver instead of the K8s CustomObjectsApi)."""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from kuberay_tpu.cli.client import ApiClient, ApiError
from kuberay_tpu.utils import constants as C


class WaitTimeout(TimeoutError):
    """A wait-helper ran out of time; carries the last observed status."""

    def __init__(self, message: str, last_status: Optional[dict] = None):
        super().__init__(message)
        self.last_status = last_status or {}


class _KindApi:
    kind = ""

    def __init__(self, client: Optional[ApiClient] = None):
        self.client = client or ApiClient()

    # CRUD ------------------------------------------------------------

    def create(self, body: Dict[str, Any],
               namespace: str = "default") -> Dict[str, Any]:
        body = dict(body)
        body.setdefault("apiVersion", "tpu.dev/v1")
        body.setdefault("kind", self.kind)
        md = body.setdefault("metadata", {})
        md.setdefault("namespace", namespace)
        return self.client.create(body)

    def get(self, name: str, namespace: str = "default") -> Dict[str, Any]:
        return self.client.get(self.kind, name, namespace)

    def try_get(self, name: str,
                namespace: str = "default") -> Optional[Dict[str, Any]]:
        try:
            return self.get(name, namespace)
        except ApiError as e:
            if e.code == 404:
                return None
            raise

    def list(self, namespace: str = "default",
             label_selector: str = "") -> List[Dict[str, Any]]:
        return self.client.list(self.kind, namespace, label_selector)

    def update(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.client.update(body)

    def patch(self, name: str, body: Any, namespace: str = "default", *,
              patch_type: str = "merge",
              field_manager: str = "") -> Dict[str, Any]:
        """Wire PATCH — merge (RFC 7386), strategic (merge-key lists),
        or json (RFC 6902 ops); no read-modify-write race window."""
        return self.client.patch(self.kind, name, namespace, body,
                                 patch_type=patch_type,
                                 field_manager=field_manager)

    def apply(self, body: Dict[str, Any], namespace: str = "default", *,
              field_manager: str = "tpu-python-client",
              force: bool = False) -> Dict[str, Any]:
        """Server-Side Apply upsert: declares desired fields; conflicts
        with other field managers surface as ApiError 409 unless
        ``force``."""
        body = dict(body)
        body.setdefault("apiVersion", "tpu.dev/v1")
        body.setdefault("kind", self.kind)
        md = body.setdefault("metadata", {})
        md.setdefault("namespace", namespace)
        return self.client.patch(
            self.kind, md["name"], md["namespace"], body,
            patch_type="apply", field_manager=field_manager, force=force)

    def delete(self, name: str, namespace: str = "default") -> bool:
        try:
            self.client.delete(self.kind, name, namespace)
            return True
        except ApiError as e:
            if e.code == 404:
                return False
            raise

    def status(self, name: str,
               namespace: str = "default") -> Dict[str, Any]:
        return self.get(name, namespace).get("status", {})

    def edit(self, name: str, namespace: str,
             mutate: Callable[[Dict[str, Any]], None],
             retries: int = 5) -> Dict[str, Any]:
        """Read-modify-write with optimistic-concurrency retry: the
        operator writes status continuously, so a bare get→update loses
        races (HTTP 409 on resourceVersion).  Re-fetch and re-apply."""
        for attempt in range(retries):
            obj = self.get(name, namespace)
            mutate(obj)
            try:
                return self.update(obj)
            except ApiError as e:
                if e.code != 409 or attempt == retries - 1:
                    raise
                time.sleep(0.05 * (attempt + 1))
        raise AssertionError("unreachable")

    # wait plumbing ----------------------------------------------------

    def _wait(self, name: str, namespace: str,
              done: Callable[[Dict[str, Any]], bool],
              timeout: float, poll: float, what: str) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        last: Dict[str, Any] = {}
        while time.monotonic() < deadline:
            obj = self.try_get(name, namespace)
            last = (obj or {}).get("status", {})
            if obj is not None and done(last):
                return last
            time.sleep(poll)
        raise WaitTimeout(
            f"{self.kind} {namespace}/{name}: timed out waiting for {what} "
            f"(last status: {last})", last)


class TpuClusterApi(_KindApi):
    """ref RayClusterApi (kuberay_cluster_api.py:20)."""

    kind = C.KIND_CLUSTER

    def wait_until_ready(self, name: str, namespace: str = "default",
                         timeout: float = 600.0,
                         poll: float = 1.0) -> Dict[str, Any]:
        return self._wait(name, namespace,
                          lambda s: s.get("state") == "ready",
                          timeout, poll, "state=ready")

    def scale_worker_group(self, name: str, group_name: str,
                           num_slices: int,
                           namespace: str = "default") -> Dict[str, Any]:
        """Set a worker group's slice count (ref
        update_worker_group_replicas, kuberay_cluster_utils.py:257)."""
        def mutate(obj):
            for g in obj["spec"].get("workerGroupSpecs", []):
                if g.get("groupName") == group_name:
                    g.pop("numSlices", None)  # stale alias must not shadow
                    g["replicas"] = num_slices
                    if g.get("maxReplicas", 1) < num_slices:
                        g["maxReplicas"] = num_slices
                    if g.get("minReplicas", 0) > num_slices:
                        g["minReplicas"] = num_slices
                    return
            raise KeyError(f"worker group {group_name!r} not in {name}")
        return self.edit(name, namespace, mutate)

    def suspend(self, name: str, namespace: str = "default"):
        return self.edit(name, namespace,
                         lambda o: o["spec"].__setitem__("suspend", True))

    def resume(self, name: str, namespace: str = "default"):
        return self.edit(name, namespace,
                         lambda o: o["spec"].__setitem__("suspend", False))


class TpuJobApi(_KindApi):
    """ref RayjobApi (kuberay_job_api.py:24)."""

    kind = C.KIND_JOB

    _TERMINAL = ("Complete", "Failed")

    def submit(self, body: Dict[str, Any],
               namespace: str = "default") -> Dict[str, Any]:
        """ref submit_job (kuberay_job_api.py:58)."""
        return self.create(body, namespace)

    def wait_until_running(self, name: str, namespace: str = "default",
                           timeout: float = 600.0,
                           poll: float = 1.0) -> Dict[str, Any]:
        """ref wait_until_job_running (kuberay_job_api.py:204)."""
        return self._wait(
            name, namespace,
            lambda s: s.get("jobDeploymentStatus") in
            ("Running",) + self._TERMINAL,
            timeout, poll, "deployment Running")

    def wait_until_finished(self, name: str, namespace: str = "default",
                            timeout: float = 3600.0,
                            poll: float = 2.0) -> Dict[str, Any]:
        """ref wait_until_job_finished (kuberay_job_api.py:120).
        Returns the terminal status; raises WaitTimeout otherwise."""
        return self._wait(
            name, namespace,
            lambda s: s.get("jobDeploymentStatus") in self._TERMINAL,
            timeout, poll, "terminal deployment status")

    def succeeded(self, name: str, namespace: str = "default") -> bool:
        s = self.status(name, namespace)
        return s.get("jobDeploymentStatus") == "Complete" and \
            s.get("jobStatus") in ("SUCCEEDED", None)

    def suspend(self, name: str, namespace: str = "default"):
        """ref suspend_job (kuberay_job_api.py:255)."""
        return self.edit(name, namespace,
                         lambda o: o["spec"].__setitem__("suspend", True))

    def resume(self, name: str, namespace: str = "default"):
        return self.edit(name, namespace,
                         lambda o: o["spec"].__setitem__("suspend", False))

    def resubmit(self, name: str, namespace: str = "default"):
        """Delete + recreate with the same spec (ref resubmit_job,
        kuberay_job_api.py:287)."""
        obj = self.get(name, namespace)
        self.delete(name, namespace)
        fresh = {"apiVersion": obj.get("apiVersion", "tpu.dev/v1"),
                 "kind": self.kind,
                 "metadata": {"name": name, "namespace": namespace,
                              "labels": obj["metadata"].get("labels", {})},
                 "spec": obj["spec"]}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                return self.client.create(fresh)
            except ApiError as e:
                if e.code != 409:      # old object still finalizing
                    raise
                time.sleep(0.5)
        raise WaitTimeout(f"resubmit {name}: old object never went away")


class TpuServiceApi(_KindApi):
    kind = C.KIND_SERVICE

    def wait_until_healthy(self, name: str, namespace: str = "default",
                           timeout: float = 600.0,
                           poll: float = 1.0) -> Dict[str, Any]:
        return self._wait(
            name, namespace,
            lambda s: s.get("serviceStatus") in ("Healthy", "Running"),
            timeout, poll, "serviceStatus Healthy")


class ComputeTemplateApi(_KindApi):
    """CRUD for named slice presets (ref apiserver v1 ComputeTemplate
    service; the operator resolves references server-side)."""

    kind = "ComputeTemplate"

    def create_template(self, name: str, accelerator: str, topology: str,
                        cpu: str = "", memory: str = "",
                        namespace: str = "default",
                        description: str = "") -> Dict[str, Any]:
        return self.create({
            "apiVersion": "tpu.dev/v1", "kind": self.kind,
            "metadata": {"name": name, "namespace": namespace},
            "spec": {"accelerator": accelerator, "topology": topology,
                     "cpu": cpu, "memory": memory,
                     "description": description}})
