"""Log + metadata collectors feeding the history archive.

Reference shape: the historyserver ships a per-node collector sidecar
(``historyserver/pkg/collector/logcollector/.../collector.go:23-60``)
that tails the Ray log directory with fsnotify and uploads files to
object storage under ``{clusterDir}/{session}/{node}/logs/...``; the
head-node collector additionally fetches cluster metadata and dashboard
endpoints (``FetchAndStoreClusterMetadata``, ``startup_endpoints.go``).

TPU-native analogues here:

- ``LogCollector`` — polling tailer over a node's log directory
  (fsnotify has no stdlib equivalent; a (size, mtime) poll is the same
  contract).  Changed files upload whole (object stores don't append),
  with a final flush on ``stop()`` mirroring the reference's
  ``processSessionLatestLogs`` shutdown pass.
- ``CoordinatorCollector`` — head-side: scrapes the coordinator's job
  list, per-job logs, and cluster metadata into the archive so a
  deleted cluster's jobs remain debuggable.

Archive layout (shared with server.py):
  ``logs/{ns}/{cluster}/{node}/{relpath}``          raw node logs
  ``logs/{ns}/{cluster}/head/jobs/{job_id}.log``    job driver logs
  ``meta/{ns}/{cluster}/metadata.json``             cluster metadata
  ``meta/{ns}/{cluster}/jobs.json``                 job records
  ``{kind}/{ns}/{name}.json``                       CR snapshots
"""

from __future__ import annotations

import logging
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

from kuberay_tpu.history.storage import StorageBackend


_LOG = logging.getLogger("kuberay_tpu.history.collector")


def stamp_collection(storage: StorageBackend, namespace: str,
                     cluster: str) -> None:
    """Retention stamp: prune_archive ages clusters by their LAST
    collection, so an actively-collected cluster can never age out.
    Called by every collection mode (coordinator AND log-only)."""
    storage.put_doc(f"meta/{namespace}/{cluster}/archived_at.json",
                    {"ts": time.time()})


class LogCollector:
    """Uploads a node's log directory into the archive as files change."""

    def __init__(self, storage: StorageBackend, log_dir: str,
                 cluster: str, namespace: str = "default",
                 node: str = "head", poll_interval: float = 2.0):
        self.storage = storage
        self.log_dir = log_dir
        self.prefix = f"logs/{namespace}/{cluster}/{node}"
        self.poll_interval = poll_interval
        self._seen: Dict[str, Tuple[int, float]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one poll pass (public: tests and the final flush drive it) ----

    def poll_once(self) -> int:
        """Upload files whose (size, mtime) changed; returns upload count."""
        n = 0
        if not os.path.isdir(self.log_dir):
            return 0
        for dirpath, _dirs, files in os.walk(self.log_dir):
            for fn in files:
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.log_dir).replace(os.sep, "/")
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                sig = (st.st_size, st.st_mtime)
                if self._seen.get(rel) == sig:
                    continue
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    continue
                self.storage.put(f"{self.prefix}/{rel}", data)
                self._seen[rel] = sig
                n += 1
        return n

    # -- background loop ----------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="log-collector")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                # Storage hiccup: retried next poll, but a persistently
                # failing backend must leave a trail.
                _LOG.debug("history poll failed; retrying", exc_info=True)
            self._stop.wait(self.poll_interval)

    def stop(self):
        """Stop and run the final flush (ref: processSessionLatestLogs)."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        try:
            self.poll_once()
        except Exception:
            pass


class CoordinatorCollector:
    """Head-side collector: archives the coordinator's cluster metadata,
    job records, and per-job driver logs."""

    def __init__(self, storage: StorageBackend, coordinator_url: str,
                 cluster: str, namespace: str = "default",
                 token: str = "", timeout: float = 5.0):
        self.storage = storage
        self.base = coordinator_url.rstrip("/")
        self.cluster = cluster
        self.namespace = namespace
        self.token = token
        self.timeout = timeout

    def _get(self, path: str) -> Optional[bytes]:
        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        try:
            req = urllib.request.Request(self.base + path, headers=headers)
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read()
        except (urllib.error.URLError, OSError):
            return None

    def collect_once(self) -> int:
        """Scrape metadata + jobs + events + job logs; returns
        archived-object count."""
        n = 0
        meta_prefix = f"meta/{self.namespace}/{self.cluster}"
        stamp_collection(self.storage, self.namespace, self.cluster)
        raw = self._get("/api/cluster")
        if raw is not None:
            self.storage.put(f"{meta_prefix}/metadata.json", raw)
            n += 1
        # Structured task/step/profile events (ref eventserver.go:838) —
        # the post-mortem replay source for /api/history/events.  Merged
        # by event id, NOT overwritten: the coordinator's ring is lossy
        # (eviction, head restarts) and the archive is the durable copy.
        raw = self._get("/api/events?limit=20000")   # = full ring size
        if raw is not None:
            try:
                fresh = json.loads(raw).get("events", [])
            except ValueError:
                fresh = []
            key = f"{meta_prefix}/events.json"
            try:
                old = json.loads(self.storage.get(key) or b"{}")
                existing = old.get("events", [])
            except ValueError:
                existing = []

            def ekey(e):
                # id when present; the full content otherwise (id-less
                # events from an older coordinator must dedup across
                # scrapes without dropping distinct same-timestamp
                # events that differ only in payload).
                return e.get("id") or json.dumps(e, sort_keys=True)
            seen = {ekey(e) for e in existing}
            new = [e for e in fresh if ekey(e) not in seen]
            if new:
                merged = existing + new
                # Order by the coordinator's SERVER-side receive stamps
                # (received_at + monotonic received_seq); the client
                # ``ts`` is display-only fallback for events from an
                # older coordinator — a skewed client clock must not
                # reorder the archive.
                merged.sort(key=lambda e: (
                    e.get("received_at") or e.get("ts") or 0,
                    e.get("received_seq") or 0))
                merged = merged[-100_000:]     # archive cap
                self.storage.put(key,
                                 json.dumps({"events": merged}).encode())
                n += 1
            # No fresh events: the archived copy is already current —
            # skip the rewrite (a full 100k-event PUT per idle poll).
        raw = self._get("/api/jobs/")
        if raw is None:
            return n
        self.storage.put(f"{meta_prefix}/jobs.json", raw)
        n += 1
        try:
            jobs = json.loads(raw)
        except ValueError:
            return n
        items = jobs if isinstance(jobs, list) else jobs.get("jobs", [])
        for job in items:
            jid = job.get("job_id") or job.get("submission_id")
            if not jid:
                continue
            logs = self._get(f"/api/jobs/{jid}/logs")
            if logs is None:
                continue
            try:
                text = json.loads(logs).get("logs", "")
            except ValueError:
                text = logs.decode(errors="replace")
            self.storage.put(
                f"logs/{self.namespace}/{self.cluster}/head/jobs/{jid}.log",
                text.encode())
            n += 1
        return n
