"""History server: post-mortem observability (ref historyserver/, SURVEY
§2.2 — collectors tail live state into object storage; the server
replays a dashboard-compatible API from storage).

Components (reference counterparts in parentheses):
- ``HistoryCollector`` — watches the CR store and archives terminal CRs
  + events + pod summaries (eventcollector).
- ``history.collector.LogCollector`` / ``CoordinatorCollector`` — node
  log dirs and coordinator job logs/metadata (logcollector).
- ``HistoryServer`` — read-only replay API over the archive
  (``pkg/historyserver/router.go``):

  ``GET /api/history/clusters``                 summary rows (live view)
  ``GET /api/history/{kind}``                   archived CRs of a kind
  ``GET /api/history/{kind}/{ns}``              ... in a namespace
  ``GET /api/history/{kind}/{ns}/{name}``       one CR + its events
  ``GET /api/history/logs/{ns}/{cluster}``      log-file listing
  ``GET /api/history/logs/{ns}/{cluster}/{path}`` log content (text)
  ``GET /api/history/meta/{ns}/{cluster}``      archived metadata docs
  ``GET /api/history/goodput/{ns}/{cluster}``   archived goodput ledger
  ``GET /api/history/incidents/{ns}/{cluster}`` archived incident bundles

All storage goes through ``history.storage.StorageBackend`` — local
directory, S3, or GCS (the reference's storage interface seam).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import urllib.parse
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from kuberay_tpu.controlplane.store import Event, ObjectStore
from kuberay_tpu.history.storage import LocalStorage, StorageBackend
from kuberay_tpu.utils.httpjson import JsonHandler

__all__ = ["HistoryCollector", "HistoryServer", "LocalStorage",
           "StorageBackend"]

_LOG = logging.getLogger("kuberay_tpu.history.server")


_ARCHIVED_KINDS = ("TpuCluster", "TpuJob", "TpuService", "TpuCronJob")


def _doc_key(kind: str, ns: str, name: str) -> str:
    return f"{kind}/{ns}/{name}.json"


def list_docs(storage: StorageBackend, kind: str,
              ns: Optional[str] = None) -> List[Dict[str, Any]]:
    prefix = f"{kind}/{ns}/" if ns else f"{kind}/"
    out = []
    for key in storage.list(prefix):
        if key.endswith(".json"):
            doc = storage.get_doc(key)
            if doc is not None:
                out.append(doc)
    return out


class HistoryCollector:
    """Archives CR snapshots on every modification and enriches them with
    events + pod summaries on deletion (the event-collector analogue,
    ref eventcollector.go).

    The store invokes watch callbacks while holding its lock, so the
    callback only ENQUEUES; a worker thread does the storage I/O —
    otherwise a slow S3/GCS endpoint would stall every store mutation
    (API writes, all reconcilers) behind remote HTTP round-trips."""

    def __init__(self, store: ObjectStore, storage: StorageBackend,
                 goodput=None, incidents=None):
        self.store = store
        self.storage = storage
        # Optional obs.GoodputLedger: each archived CR snapshot also
        # persists the object's goodput ledger doc under
        # ``meta/{ns}/{cluster}/goodput.json`` — the time-loss breakdown
        # of a deleted cluster stays debuggable post-mortem.
        self.goodput = goodput
        # Optional obs.IncidentEngine: incident bundles scoped to the
        # archived entity persist under
        # ``meta/{ns}/{cluster}/incidents.json`` so the post-mortem
        # still names the top suspect after the cluster is gone.
        self.incidents = incidents
        self._queue: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name="history-collector")
        self._worker.start()
        self._cancel = store.watch(self._queue.put)

    def close(self, timeout: float = 10.0):
        """Stop watching and drain the queue (archive writes for events
        already observed complete before close returns)."""
        self._cancel()
        self._queue.put(None)
        self._worker.join(timeout=timeout)

    def _drain(self):
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            try:
                self._archive(ev)
            except Exception:
                # Storage hiccup: drop this snapshot, not the thread —
                # visibly, or a dead backend looks like a quiet cluster.
                _LOG.debug("archive failed for %s %s; snapshot dropped",
                           ev.type, ev.kind, exc_info=True)

    def _archive(self, ev: Event):
        if ev.kind not in _ARCHIVED_KINDS:
            return
        md = ev.obj.get("metadata", {})
        ns, name = md.get("namespace", "default"), md.get("name", "")
        if not name:
            return
        key = _doc_key(ev.kind, ns, name)
        doc = self.storage.get_doc(key) or {}
        doc.update({
            "kind": ev.kind,
            "metadata": md,
            "spec": ev.obj.get("spec", {}),
            "status": ev.obj.get("status", {}),
            "lastEventType": ev.type,
            "archivedAt": time.time(),
        })
        if ev.type == Event.DELETED:
            doc["deleted"] = True
            doc["events"] = [
                {"reason": e.get("reason"), "message": e.get("message"),
                 "type": e.get("type"), "eventTime": e.get("eventTime")}
                for e in self.store.list("Event", ns)
                if e.get("involvedObject", {}).get("name") == name
                and e.get("involvedObject", {}).get("kind") == ev.kind]
            doc["pods"] = [
                {"name": p["metadata"]["name"],
                 "phase": p.get("status", {}).get("phase")}
                for p in self.store.list("Pod", ns)
                if p["metadata"].get("labels", {})
                .get("tpu.dev/cluster") == name]
        self.storage.put_doc(key, doc)
        if self.goodput is not None and ev.kind == "TpuCluster":
            # Refresh the goodput doc on every archived snapshot; the
            # DELETED pass freezes it (the ledger closes on deletion), so
            # the time-loss breakdown outlives the cluster.
            gdoc = self.goodput.to_doc(ev.kind, ns, name)
            if gdoc is not None:
                self.storage.put_doc(f"meta/{ns}/{name}/goodput.json", gdoc)
        if self.incidents is not None:
            # Incident bundles scoped to this entity (any kind — the
            # engine matches on namespace+name): refreshed on every
            # archived snapshot, frozen by the DELETED pass.
            bundles = self.incidents.for_entity(ns, name)
            if bundles:
                self.storage.put_doc(
                    f"meta/{ns}/{name}/incidents.json",
                    {"namespace": ns, "name": name,
                     "incidents": bundles})


class HistoryServer:
    """Read-only replay API over the archive (ref router.go's
    dashboard-compatible surface)."""

    def __init__(self, storage: StorageBackend):
        self.storage = storage

    # -- handlers (shared by the HTTP server and direct callers) -------

    def clusters_summary(self) -> List[Dict[str, Any]]:
        rows = []
        for doc in list_docs(self.storage, "TpuCluster"):
            md = doc.get("metadata", {})
            rows.append({
                "name": md.get("name"),
                "namespace": md.get("namespace", "default"),
                "state": doc.get("status", {}).get("state"),
                "deleted": bool(doc.get("deleted")),
                "archivedAt": doc.get("archivedAt"),
            })
        return rows

    def log_files(self, ns: str, cluster: str) -> List[str]:
        prefix = f"logs/{ns}/{cluster}/"
        return [k[len(prefix):] for k in self.storage.list(prefix)]

    def log_content(self, ns: str, cluster: str, rel: str) -> Optional[bytes]:
        return self.storage.get(f"logs/{ns}/{cluster}/{rel}")

    def meta_docs(self, ns: str, cluster: str) -> Dict[str, Any]:
        prefix = f"meta/{ns}/{cluster}/"
        out = {}
        for k in self.storage.list(prefix):
            doc = self.storage.get_doc(k)
            if doc is not None:
                out[k[len(prefix):]] = doc
        return out

    def task_events(self, ns: str, cluster: str) -> List[Dict[str, Any]]:
        """Archived task/step/profile events (collector scrape of the
        coordinator's /api/events — ref eventserver.go:838 replay)."""
        doc = self.storage.get_doc(f"meta/{ns}/{cluster}/events.json")
        if doc is None:
            return []
        return doc.get("events", doc) if isinstance(doc, dict) else doc

    # -- routing (shared by the standalone server and the apiserver's
    #    /api/history mount) ------------------------------------------

    def route(self, path: str):
        """Resolve a GET path.  Returns ``(code, body, is_text)`` for
        history paths, or ``None`` if the path is not a history route."""
        raw = urllib.parse.urlsplit(path).path
        parts = [urllib.parse.unquote(p) for p in raw.split("/") if p]
        if len(parts) < 3 or parts[:2] != ["api", "history"]:
            return None
        head = parts[2]
        if head == "clusters" and len(parts) == 3:
            return 200, {"items": self.clusters_summary()}, False
        if head == "logs":
            if len(parts) == 5:
                return 200, {"files": self.log_files(parts[3],
                                                     parts[4])}, False
            if len(parts) > 5:
                body = self.log_content(parts[3], parts[4],
                                        "/".join(parts[5:]))
                if body is None:
                    return 404, {"message": "no such log"}, False
                return 200, body.decode(errors="replace"), True
            return 404, {"message": "unknown path"}, False
        if head == "meta" and len(parts) == 5:
            return 200, self.meta_docs(parts[3], parts[4]), False
        if head == "events" and len(parts) == 5:
            return 200, {"events": self.task_events(parts[3],
                                                    parts[4])}, False
        if head == "goodput" and len(parts) == 5:
            doc = self.storage.get_doc(
                f"meta/{parts[3]}/{parts[4]}/goodput.json")
            if doc is None:
                return 404, {"message": "no goodput ledger archived"}, False
            return 200, doc, False
        if head == "incidents" and len(parts) == 5:
            doc = self.storage.get_doc(
                f"meta/{parts[3]}/{parts[4]}/incidents.json")
            if doc is None:
                return 404, {"message": "no incidents archived"}, False
            return 200, doc, False
        if head == "timeline" and len(parts) == 5:
            doc = self.storage.get_doc(_doc_key("TpuCluster", parts[3],
                                                parts[4]))
            if doc is None:
                return 404, {"message": "not archived"}, False
            from kuberay_tpu.utils.timeline import cluster_timeline
            jobs = [j for j in list_docs(self.storage, "TpuJob", parts[3])
                    if j.get("status", {}).get("clusterName") == parts[4]]
            return 200, cluster_timeline(
                doc, jobs=jobs,
                task_events=self.task_events(parts[3], parts[4])), False
        kind = head
        if kind not in _ARCHIVED_KINDS:
            return 404, {"message": "unknown kind"}, False
        if len(parts) == 3:
            return 200, {"items": list_docs(self.storage, kind)}, False
        if len(parts) == 4:
            return 200, {"items": list_docs(self.storage, kind,
                                            parts[3])}, False
        doc = self.storage.get_doc(_doc_key(kind, parts[3], parts[4]))
        if doc is None:
            return 404, {"message": "not archived"}, False
        return 200, doc, False

    # -- HTTP ----------------------------------------------------------

    def make_server(self, host="127.0.0.1", port=0) -> ThreadingHTTPServer:
        hs = self

        class Handler(JsonHandler):
            def do_GET(self):
                r = hs.route(self.path)
                if r is None:
                    return self._send(404, {"message": "unknown path"})
                code, body, is_text = r
                if is_text:
                    return self._send_text(code, body)
                return self._send(code, body)

        return ThreadingHTTPServer((host, port), Handler)

    def serve_background(self, host="127.0.0.1", port=0):
        from kuberay_tpu.utils.httpjson import serve_background
        return serve_background(self.make_server(host, port), "history-server")
