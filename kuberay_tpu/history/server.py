"""History server: post-mortem observability (ref historyserver/, SURVEY
§2.2 — collector tails live state into object storage; server replays a
dashboard-compatible API from storage).

Two components, same shapes as the reference:
- ``HistoryCollector``: watches the store and archives terminal CRs,
  events, and pod summaries as JSON files under a storage root (the
  GCS/S3 backend seam is the ``storage`` argument — local directory here,
  same layout an object-store backend would use).
- ``HistoryServer``: read-only HTTP API over the archive
  (``/api/history/{kind}``, ``/api/history/{kind}/{ns}/{name}``) so
  clusters/jobs remain inspectable after deletion.
"""

from __future__ import annotations

import json
import os
import time
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from kuberay_tpu.controlplane.store import Event, ObjectStore
from kuberay_tpu.utils.httpjson import JsonHandler

_ARCHIVED_KINDS = ("TpuCluster", "TpuJob", "TpuService", "TpuCronJob")


class LocalStorage:
    """Directory-backed archive (object-store layout: kind/ns/name.json)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, kind: str, ns: str, name: str, doc: Dict[str, Any]):
        d = os.path.join(self.root, kind, ns)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{name}.tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(d, f"{name}.json"))

    def get(self, kind: str, ns: str, name: str) -> Optional[Dict[str, Any]]:
        path = os.path.join(self.root, kind, ns, f"{name}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def list(self, kind: str, ns: Optional[str] = None) -> List[Dict[str, Any]]:
        base = os.path.join(self.root, kind)
        out = []
        if not os.path.isdir(base):
            return out
        for namespace in sorted(os.listdir(base)):
            if ns is not None and namespace != ns:
                continue
            d = os.path.join(base, namespace)
            for fn in sorted(os.listdir(d)):
                if fn.endswith(".json"):
                    doc = self.get(kind, namespace, fn[:-5])
                    if doc is not None:
                        out.append(doc)
        return out


class HistoryCollector:
    """Archives CR snapshots on every modification and enriches them with
    events + pod summaries on deletion (the fsnotify-tailing collector
    analogue, ref collector.go:23-60)."""

    def __init__(self, store: ObjectStore, storage: LocalStorage):
        self.store = store
        self.storage = storage
        self._cancel = store.watch(self._on_event)

    def close(self):
        self._cancel()

    def _on_event(self, ev: Event):
        if ev.kind not in _ARCHIVED_KINDS:
            return
        md = ev.obj.get("metadata", {})
        ns, name = md.get("namespace", "default"), md.get("name", "")
        if not name:
            return
        doc = self.storage.get(ev.kind, ns, name) or {}
        doc.update({
            "kind": ev.kind,
            "metadata": md,
            "spec": ev.obj.get("spec", {}),
            "status": ev.obj.get("status", {}),
            "lastEventType": ev.type,
            "archivedAt": time.time(),
        })
        if ev.type == Event.DELETED:
            doc["deleted"] = True
            doc["events"] = [
                {"reason": e.get("reason"), "message": e.get("message"),
                 "type": e.get("type"), "eventTime": e.get("eventTime")}
                for e in self.store.list("Event", ns)
                if e.get("involvedObject", {}).get("name") == name
                and e.get("involvedObject", {}).get("kind") == ev.kind]
        self.storage.put(ev.kind, ns, name, doc)


class HistoryServer:
    """Read-only replay API over the archive (ref router.go's
    dashboard-compatible surface)."""

    def __init__(self, storage: LocalStorage):
        self.storage = storage

    def make_server(self, host="127.0.0.1", port=0) -> ThreadingHTTPServer:
        storage = self.storage

        class Handler(JsonHandler):
            def do_GET(self):
                parts = [p for p in self.path.split("/") if p]
                # /api/history/{kind}[/{ns}[/{name}]]
                if len(parts) >= 3 and parts[:2] == ["api", "history"]:
                    kind = parts[2]
                    if kind not in _ARCHIVED_KINDS:
                        return self._send(404, {"message": "unknown kind"})
                    if len(parts) == 3:
                        return self._send(200, {"items": storage.list(kind)})
                    if len(parts) == 4:
                        return self._send(
                            200, {"items": storage.list(kind, parts[3])})
                    doc = storage.get(kind, parts[3], parts[4])
                    if doc is None:
                        return self._send(404, {"message": "not archived"})
                    return self._send(200, doc)
                return self._send(404, {"message": "unknown path"})

        return ThreadingHTTPServer((host, port), Handler)

    def serve_background(self, host="127.0.0.1", port=0):
        from kuberay_tpu.utils.httpjson import serve_background
        return serve_background(self.make_server(host, port), "history-server")
