"""Object-storage backends for the history archive.

Reference shape: ``historyserver/pkg/storage/interface.go`` defines a
``StorageWriter`` (CreateDirectory/WriteFile) + ``StorageReader``
(List/GetContent/ListFiles) pair with GCS / S3 / AzureBlob / AliyunOSS
implementations.  Here the seam is a single byte-level ``StorageBackend``
(put/get/list/delete over object keys) with the same five:

- ``LocalStorage``      — directory-backed (the reference's localtest
  backend).
- ``S3Storage``         — real S3 REST protocol with AWS Signature V4
  request signing (ref ``pkg/storage/s3/``); works against any
  S3-compatible endpoint (AWS, MinIO, GCS-interop).
- ``GCSStorage``        — GCS JSON API with bearer-token auth
  (ref ``pkg/storage/gcs/``).
- ``AzureBlobStorage``  — Blob REST API with Shared Key signing
  (ref ``pkg/storage/azureblob/``).
- ``AliyunOSSStorage``  — OSS REST API with header signing
  (ref ``pkg/storage/aliyunoss/``).

All remote protocols are stdlib-only (urllib + hmac/hashlib + ElementTree)
so the archive works in a hermetic image; they are exercised in tests
against in-process fake endpoints that verify wire format incl. the
SigV4 Authorization header.
"""

from __future__ import annotations

import datetime
import gzip
import hashlib
import hmac
import json
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
import zlib
from typing import Any, Dict, List, Optional


class StorageBackend:
    """Byte-level object store: keys are '/'-separated paths."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        """All keys under prefix, sorted."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    # -- JSON-document convenience used by the CR archive --------------

    def put_doc(self, key: str, doc: Dict[str, Any]) -> None:
        self.put(key, json.dumps(doc).encode())

    def get_doc(self, key: str) -> Optional[Dict[str, Any]]:
        raw = self.get(key)
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            return None


class LocalStorage(StorageBackend):
    """Directory-backed archive (object-store layout on local disk)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        # Normalise and reject traversal out of the root.
        p = os.path.abspath(os.path.join(self.root, key.lstrip("/")))
        if not p.startswith(self.root + os.sep):
            raise ValueError(f"storage key escapes root: {key!r}")
        return p

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except (OSError, ValueError):   # ValueError: traversal key -> miss
            return None

    def list(self, prefix: str = "") -> List[str]:
        # Walk only the subtree the prefix maps to — the key layout IS
        # the directory layout, so a kind/namespace listing must not
        # stat the (much larger) log archive.
        subdir, _, _tail = prefix.rpartition("/")
        try:
            base = self._path(subdir) if subdir else self.root
        except ValueError:
            return []
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except (OSError, ValueError):
            pass


# ---------------------------------------------------------------------------
# S3 (AWS Signature V4)


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(method: str, url: str, region: str, service: str,
                  access_key: str, secret_key: str, payload: bytes = b"",
                  now: Optional[datetime.datetime] = None) -> Dict[str, str]:
    """AWS Signature Version 4 headers for a single request.

    Implements the canonical-request / string-to-sign / signing-key chain
    from the SigV4 spec; the test suite's fake S3 endpoint re-derives the
    signature to prove wire compatibility.
    """
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = _sha256(payload)

    # The URL path arrives ALREADY percent-encoded (S3Storage._url quotes
    # keys); the SigV4 canonical URI is that once-encoded path verbatim —
    # re-quoting would double-encode '%' and mismatch AWS's signature.
    canonical_uri = parsed.path or "/"
    # Canonical query: sorted, URL-encoded pairs.
    q = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q))
    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amz_date}
    signed_headers = ";".join(sorted(headers))
    canonical_headers = "".join(
        f"{k}:{headers[k].strip()}\n" for k in sorted(headers))
    canonical_request = "\n".join([
        method, canonical_uri, canonical_query, canonical_headers,
        signed_headers, payload_hash])

    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope, _sha256(canonical_request.encode())])

    k_date = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()

    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"),
    }


class S3Storage(StorageBackend):
    """S3-protocol backend: PUT/GET/DELETE Object + ListObjectsV2,
    signed with SigV4 (ref ``historyserver/pkg/storage/s3/``).

    ``endpoint`` is the service URL (e.g. ``http://minio:9000``); keys are
    stored under ``{endpoint}/{bucket}/{key}`` (path-style addressing, the
    form every S3-compatible store accepts).
    """

    def __init__(self, endpoint: str, bucket: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 timeout: float = 10.0):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        self.region = region
        self.timeout = timeout

    def _url(self, key: str = "", query: str = "") -> str:
        path = f"/{self.bucket}"
        if key:
            path += "/" + urllib.parse.quote(key, safe="/-_.~")
        return self.endpoint + path + (("?" + query) if query else "")

    def _request(self, method: str, url: str, payload: bytes = b"") -> bytes:
        headers = sigv4_headers(method, url, self.region, "s3",
                                self.access_key, self.secret_key, payload)
        req = urllib.request.Request(url, data=payload or None,
                                     headers=headers, method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def put(self, key: str, data: bytes) -> None:
        self._request("PUT", self._url(key), data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            return self._request("GET", self._url(key))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, key: str) -> None:
        try:
            self._request("DELETE", self._url(key))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def list(self, prefix: str = "") -> List[str]:
        keys: List[str] = []
        token = ""
        while True:
            q = {"list-type": "2", "prefix": prefix}
            if token:
                q["continuation-token"] = token
            url = self._url(query=urllib.parse.urlencode(sorted(q.items())))
            body = self._request("GET", url)
            root = ET.fromstring(body)
            # Namespace-agnostic: S3 responses use the aws ns, fakes may not.
            def _findall(tag):
                return [el for el in root.iter() if el.tag.endswith(tag)]
            for el in _findall("Key"):
                keys.append(el.text or "")
            truncated = next((el.text for el in _findall("IsTruncated")), "false")
            token = next((el.text for el in _findall("NextContinuationToken")), "")
            if truncated != "true" or not token:
                break
        return sorted(keys)


class GCSStorage(StorageBackend):
    """GCS JSON-API backend with bearer-token auth
    (ref ``historyserver/pkg/storage/gcs/``).

    ``endpoint`` defaults to the public API host; override for the
    emulator / fake used in tests.
    """

    def __init__(self, bucket: str, token: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 timeout: float = 10.0):
        self.bucket = bucket
        self.token = token or os.environ.get("GCS_OAUTH_TOKEN", "")
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout

    def _headers(self) -> Dict[str, str]:
        h = {}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _open(self, req: urllib.request.Request) -> bytes:
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def put(self, key: str, data: bytes) -> None:
        url = (f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
               f"?uploadType=media&name={urllib.parse.quote(key, safe='')}")
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={**self._headers(),
                     "Content-Type": "application/octet-stream"})
        self._open(req)

    def get(self, key: str) -> Optional[bytes]:
        url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}?alt=media")
        try:
            return self._open(urllib.request.Request(
                url, headers=self._headers()))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, key: str) -> None:
        url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}")
        try:
            self._open(urllib.request.Request(
                url, method="DELETE", headers=self._headers()))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def list(self, prefix: str = "") -> List[str]:
        keys: List[str] = []
        page = ""
        while True:
            q = {"prefix": prefix}
            if page:
                q["pageToken"] = page
            url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o?"
                   + urllib.parse.urlencode(sorted(q.items())))
            doc = json.loads(self._open(urllib.request.Request(
                url, headers=self._headers())))
            keys.extend(i["name"] for i in doc.get("items", []))
            page = doc.get("nextPageToken", "")
            if not page:
                break
        return sorted(keys)


class AzureBlobStorage(StorageBackend):
    """Azure Blob REST backend with Shared Key authorization
    (ref ``historyserver/pkg/storage/azureblob/``).

    Implements the Shared Key string-to-sign (canonicalized x-ms-*
    headers + canonicalized resource, HMAC-SHA256 over the base64 account
    key) from the Azure Storage auth spec; the test suite's fake endpoint
    re-derives the signature to prove wire compatibility.
    """

    VERSION = "2020-04-08"

    def __init__(self, account: str, container: str, account_key: str = "",
                 endpoint: str = "", timeout: float = 10.0):
        import base64
        self.account = account
        self.container = container
        key = account_key or os.environ.get("AZURE_STORAGE_KEY", "")
        if not key:
            # Fail fast: an empty key would HMAC-sign every request
            # wrong and surface as a stream of opaque 403s mid-run.
            raise ValueError(
                "Azure account key required (AZURE_STORAGE_KEY env or "
                "account_key=)")
        self._key = base64.b64decode(key)
        self.endpoint = (endpoint.rstrip("/") or
                         f"https://{account}.blob.core.windows.net")
        self.timeout = timeout

    def _auth_headers(self, method: str, path: str, query: Dict[str, str],
                      payload: bytes, content_type: str) -> Dict[str, str]:
        import base64
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {
            "x-ms-date": now.strftime("%a, %d %b %Y %H:%M:%S GMT"),
            "x-ms-version": self.VERSION,
        }
        if method == "PUT":
            headers["x-ms-blob-type"] = "BlockBlob"
        canon_headers = "".join(
            f"{k}:{headers[k]}\n" for k in sorted(headers))
        canon_resource = f"/{self.account}{path}" + "".join(
            f"\n{k}:{v}" for k, v in sorted(query.items()))
        content_length = str(len(payload)) if payload else ""
        string_to_sign = "\n".join([
            method,
            "",                    # Content-Encoding
            "",                    # Content-Language
            content_length,        # Content-Length ("" when zero)
            "",                    # Content-MD5
            content_type,          # signed — urllib injects a default
                                   # Content-Type on bodied requests, so
                                   # it MUST be explicit and match
            "",                    # Date (x-ms-date used instead)
            "", "", "", "", "",    # If-* / Range
            canon_headers + canon_resource])
        sig = base64.b64encode(hmac.new(
            self._key, string_to_sign.encode(), hashlib.sha256).digest()
        ).decode()
        headers["Authorization"] = f"SharedKey {self.account}:{sig}"
        return headers

    def _request(self, method: str, path: str,
                 query: Optional[Dict[str, str]] = None,
                 payload: bytes = b"") -> bytes:
        query = query or {}
        url = self.endpoint + urllib.parse.quote(path, safe="/-_.~")
        if query:
            # Percent-encode (never '+'-for-space): Azure canonicalizes
            # by PERCENT-decoding the query string, so a quote_plus '+'
            # would decode to a literal '+' server-side and 403 any
            # prefix containing a space.  With %20 the decoded value the
            # server signs matches the raw value we sign below.
            url += "?" + urllib.parse.urlencode(
                sorted(query.items()), quote_via=urllib.parse.quote)
        ct = "application/octet-stream" if method == "PUT" else ""
        headers = self._auth_headers(method, path, query, payload, ct)
        if ct:
            headers["Content-Type"] = ct
        # data=b'' (NOT None) on empty PUTs: Azure requires a
        # Content-Length header (411 otherwise).
        req = urllib.request.Request(
            url, data=payload if method == "PUT" else None,
            headers=headers, method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def put(self, key: str, data: bytes) -> None:
        self._request("PUT", f"/{self.container}/{key}", payload=data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            return self._request("GET", f"/{self.container}/{key}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, key: str) -> None:
        try:
            self._request("DELETE", f"/{self.container}/{key}")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def list(self, prefix: str = "") -> List[str]:
        keys: List[str] = []
        marker = ""
        while True:
            q = {"restype": "container", "comp": "list", "prefix": prefix}
            if marker:
                q["marker"] = marker
            body = self._request("GET", f"/{self.container}", query=q)
            root = ET.fromstring(body)
            keys.extend(el.text or "" for el in root.iter("Name"))
            marker = next((el.text or "" for el in root.iter("NextMarker")
                           if el.text), "")
            if not marker:
                break
        return sorted(keys)


class AliyunOSSStorage(StorageBackend):
    """Aliyun OSS REST backend with header-based signing
    (ref ``historyserver/pkg/storage/aliyunoss/``): Authorization is
    ``OSS {key_id}:{base64(hmac_sha1(secret, string-to-sign))}`` over
    VERB/MD5/Type/Date + canonicalized x-oss-* headers + resource.
    """

    def __init__(self, bucket: str, access_key_id: str = "",
                 access_key_secret: str = "", endpoint: str = "",
                 timeout: float = 10.0, path_style: bool = False):
        self.bucket = bucket
        self.key_id = access_key_id or os.environ.get(
            "OSS_ACCESS_KEY_ID", "")
        self.secret = access_key_secret or os.environ.get(
            "OSS_ACCESS_KEY_SECRET", "")
        self.endpoint = (endpoint.rstrip("/")
                         or "https://oss-cn-hangzhou.aliyuncs.com")
        # Real OSS requires virtual-host addressing
        # (https://{bucket}.{region-host}/{key} — path-style gets
        # SecondLevelDomainForbidden); the canonicalized resource is
        # "/{bucket}/{key}" in BOTH styles.  path_style=True serves
        # test fakes and S3-compatible gateways.
        self.path_style = path_style
        self.timeout = timeout

    def _object_url(self, key: str) -> str:
        quoted = urllib.parse.quote(key, safe="/-_.~")
        if self.path_style:
            return f"{self.endpoint}/{self.bucket}/{quoted}"
        scheme, _, host = self.endpoint.partition("://")
        return f"{scheme}://{self.bucket}.{host}/{quoted}"

    def _request(self, method: str, key: str = "",
                 query: Optional[Dict[str, str]] = None,
                 payload: bytes = b"") -> bytes:
        import base64
        query = query or {}
        date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%a, %d %b %Y %H:%M:%S GMT")
        resource = f"/{self.bucket}/{key}"
        # Content-Type is part of the OSS string-to-sign; urllib injects
        # a default on bodied requests, so set it explicitly and sign it.
        # (List subresources like prefix/marker are excluded from the
        # canonicalized resource by the OSS spec — only the bare path
        # signs.)
        ct = "application/octet-stream" if method == "PUT" else ""
        string_to_sign = "\n".join([method, "", ct, date, resource])
        sig = base64.b64encode(hmac.new(
            self.secret.encode(), string_to_sign.encode(),
            hashlib.sha1).digest()).decode()
        url = self._object_url(key)
        if query:
            url += "?" + urllib.parse.urlencode(sorted(query.items()))
        headers = {"Date": date,
                   "Authorization": f"OSS {self.key_id}:{sig}"}
        if ct:
            headers["Content-Type"] = ct
        req = urllib.request.Request(
            url, data=payload if method == "PUT" else None, method=method,
            headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def put(self, key: str, data: bytes) -> None:
        self._request("PUT", key, payload=data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            return self._request("GET", key)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, key: str) -> None:
        try:
            self._request("DELETE", key)
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def list(self, prefix: str = "") -> List[str]:
        keys: List[str] = []
        marker = ""
        while True:
            q = {"prefix": prefix}
            if marker:
                q["marker"] = marker
            body = self._request("GET", query=q)
            root = ET.fromstring(body)

            def _texts(tag):
                return [el.text or "" for el in root.iter(tag)]
            keys.extend(_texts("Key"))
            truncated = next(iter(_texts("IsTruncated")), "false")
            marker = next(iter(_texts("NextMarker")), "")
            if truncated != "true" or not marker:
                break
        return sorted(keys)


class CompressedBackend(StorageBackend):
    """gzip wrapper over any backend (ref historyserver/pkg/compression/
    compression.go:16-28 — payloads compress before object storage).

    Keys are unchanged (no ``.gz`` suffix): the wrapper is a transport
    codec, not a naming scheme, so dashboards/tools listing the archive
    see the same layout either way.  ``get`` sniffs the gzip magic and
    passes non-gzip payloads through untouched — an archive written
    before compression existed (or with ``?compress=none``) replays
    transparently, and mixed archives are fine.
    """

    _MAGIC = b"\x1f\x8b"

    def __init__(self, inner: StorageBackend, level: int = 6,
                 compress_writes: bool = True):
        self.inner = inner
        self.level = level
        self.compress_writes = compress_writes

    def put(self, key: str, data: bytes) -> None:
        if not self.compress_writes:
            self.inner.put(key, data)
            return
        self.inner.put(key, gzip.compress(data, compresslevel=self.level))

    def get(self, key: str) -> Optional[bytes]:
        raw = self.inner.get(key)
        if raw is None or not raw.startswith(self._MAGIC):
            return raw
        try:
            return gzip.decompress(raw)
        except (OSError, EOFError, zlib.error):
            # Magic collision on a raw payload (e.g. a .log.gz uploaded
            # before compression existed, truncated mid-write): pass
            # the bytes through untouched.  gzip raises EOFError /
            # zlib.error here, not just OSError.
            return raw

    def list(self, prefix: str = "") -> List[str]:
        return self.inner.list(prefix)

    def delete(self, key: str) -> None:
        self.inner.delete(key)


def backend_from_url(url: str) -> StorageBackend:
    """Factory: ``file:///path``, ``s3://bucket?endpoint=...&region=...``,
    ``gs://bucket?endpoint=...`` — the collector/server CLI seam.

    Payloads gzip by default before upload (ref historyserver compression
    layer); ``?compress=none`` opts out, ``?compress_level=N`` tunes.
    Reads are transparent either way (magic sniffing), so flipping the
    knob never strands an existing archive.
    """
    parsed = urllib.parse.urlsplit(url)
    q = dict(urllib.parse.parse_qsl(parsed.query))

    def wrap(backend: StorageBackend) -> StorageBackend:
        # Read-side decompression is UNCONDITIONAL (magic sniffing):
        # an archive written compressed must replay correctly even when
        # a later process opts out of write compression — the knob can
        # never strand existing data.
        writes = q.get("compress", "gzip") not in ("none", "0", "false")
        return CompressedBackend(backend,
                                 level=int(q.get("compress_level", "6")),
                                 compress_writes=writes)

    if parsed.scheme in ("", "file"):
        return wrap(LocalStorage(parsed.path or url.split("?")[0]))
    if parsed.scheme == "s3":
        return wrap(S3Storage(
            q.get("endpoint", "https://s3.amazonaws.com"),
            parsed.netloc, region=q.get("region", "us-east-1")))
    if parsed.scheme == "gs":
        return wrap(GCSStorage(
            parsed.netloc,
            endpoint=q.get("endpoint", "https://storage.googleapis.com")))
    if parsed.scheme == "azblob":
        # azblob://container?account=myacct[&endpoint=...]; key from
        # AZURE_STORAGE_KEY env.
        if not q.get("account"):
            raise ValueError(
                "azblob:// URL requires ?account=<storage account>")
        return wrap(AzureBlobStorage(q["account"], parsed.netloc,
                                     endpoint=q.get("endpoint", "")))
    if parsed.scheme == "oss":
        # oss://bucket[?endpoint=...&path_style=1]; creds from
        # OSS_ACCESS_KEY_* env.
        return wrap(AliyunOSSStorage(
            parsed.netloc, endpoint=q.get("endpoint", ""),
            path_style=q.get("path_style", "") in ("1", "true")))
    raise ValueError(f"unknown storage scheme: {parsed.scheme}")


def prune_archive(storage: StorageBackend, max_age_seconds: float,
                  now: Optional[float] = None) -> List[str]:
    """Retention: delete whole cluster archives whose LAST collection
    is older than the cutoff (the collector stamps
    ``meta/{ns}/{cluster}/archived_at.json`` every pass).  Returns the
    pruned ``ns/cluster`` names.  Archives predating the stamp are kept
    — retention never guesses at age.
    """
    import time as _time
    now = _time.time() if now is None else now
    removed: List[str] = []
    for key in storage.list("meta/"):
        if not key.endswith("/archived_at.json"):
            continue
        parts = key.split("/")
        if len(parts) != 4:
            continue
        _, ns, cluster, _ = parts
        doc = storage.get_doc(key) or {}
        ts = doc.get("ts", 0)
        if not ts or now - ts <= max_age_seconds:
            continue
        for prefix in (f"meta/{ns}/{cluster}/", f"logs/{ns}/{cluster}/"):
            for k in storage.list(prefix):
                storage.delete(k)
        # The cluster's own CR snapshot ages out with it, and so do
        # Job/Service snapshots that reference it via status
        # (clusterName / active-pending cluster status).  CronJob
        # snapshots reference no cluster and are kept — crons are
        # long-lived by design.
        storage.delete(f"TpuCluster/{ns}/{cluster}.json")
        for kind in ("TpuJob", "TpuService"):
            for k in storage.list(f"{kind}/{ns}/"):
                doc = storage.get_doc(k) or {}
                st = doc.get("status") or {}
                refs = {st.get("clusterName")}
                for css in (st.get("activeServiceStatus"),
                            st.get("pendingServiceStatus")):
                    if isinstance(css, dict):
                        refs.add(css.get("clusterName"))
                if cluster in refs:
                    storage.delete(k)
        removed.append(f"{ns}/{cluster}")
    return removed
