"""History CLI: the reference's two binaries in one module
(ref historyserver/cmd/historyserver/main.go, cmd/collector/main.go).

  python -m kuberay_tpu.history serve   --storage URL [--host H] [--port P]
  python -m kuberay_tpu.history collect --storage URL --cluster NAME
      [--namespace NS] [--node NODE] [--log-dir DIR]
      [--coordinator URL] [--interval SEC] [--once]

Storage URLs: ``file:///var/archive`` | ``s3://bucket?endpoint=...&
region=...`` (creds via AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY) |
``gs://bucket?endpoint=...`` (GCS_OAUTH_TOKEN).
"""

from __future__ import annotations

import argparse
import time

from kuberay_tpu.history.collector import CoordinatorCollector, LogCollector
from kuberay_tpu.history.server import HistoryServer
from kuberay_tpu.history.storage import backend_from_url


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kuberay_tpu.history")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve", help="replay API over the archive")
    sp.add_argument("--storage", required=True)
    sp.add_argument("--host", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=8090)

    cp = sub.add_parser("collect", help="archive node logs / coordinator")
    cp.add_argument("--storage", required=True)
    cp.add_argument("--cluster", required=True)
    cp.add_argument("--namespace", default="default")
    cp.add_argument("--node", default="head")
    cp.add_argument("--log-dir", default="")
    cp.add_argument("--coordinator", default="",
                    help="head coordinator URL (archives jobs + metadata)")
    cp.add_argument("--interval", type=float, default=10.0)
    cp.add_argument("--once", action="store_true")

    args = ap.parse_args(argv)
    storage = backend_from_url(args.storage)

    if args.cmd == "serve":
        srv = HistoryServer(storage).make_server(args.host, args.port)
        print(f"history server on {args.host}:{srv.server_port}")
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        return 0

    log_col = None
    if args.log_dir:
        log_col = LogCollector(storage, args.log_dir, cluster=args.cluster,
                               namespace=args.namespace, node=args.node,
                               poll_interval=args.interval)
    coord_col = None
    if args.coordinator:
        coord_col = CoordinatorCollector(
            storage, args.coordinator, cluster=args.cluster,
            namespace=args.namespace)
    if log_col is None and coord_col is None:
        ap.error("collect needs --log-dir and/or --coordinator")
    try:
        while True:
            n = 0
            if log_col is not None:
                n += log_col.poll_once()
            if coord_col is not None:
                n += coord_col.collect_once()
            if args.once:
                print(f"archived {n} objects")
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
