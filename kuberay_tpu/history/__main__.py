"""History CLI: the reference's two binaries in one module
(ref historyserver/cmd/historyserver/main.go, cmd/collector/main.go).

  python -m kuberay_tpu.history serve   --storage URL [--host H] [--port P]
  python -m kuberay_tpu.history collect --storage URL --cluster NAME
      [--namespace NS] [--node NODE] [--log-dir DIR]
      [--coordinator URL] [--interval SEC] [--once]

Storage URLs: ``file:///var/archive`` | ``s3://bucket?endpoint=...&
region=...`` (creds via AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY) |
``gs://bucket?endpoint=...`` (GCS_OAUTH_TOKEN).
"""

from __future__ import annotations

import argparse
import time

from kuberay_tpu.history.collector import CoordinatorCollector, LogCollector
from kuberay_tpu.history.server import HistoryServer
from kuberay_tpu.history.storage import backend_from_url, prune_archive


def main(argv=None):
    ap = argparse.ArgumentParser(prog="kuberay_tpu.history")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve", help="replay API over the archive")
    sp.add_argument("--storage", required=True)
    sp.add_argument("--host", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=8090)
    sp.add_argument("--retention-days", type=float, default=0,
                    help="prune cluster archives idle longer than this "
                         "(0 = keep forever); checked every 6h")

    pp = sub.add_parser("prune", help="one-shot retention pass")
    pp.add_argument("--storage", required=True)
    pp.add_argument("--max-age-days", type=float, required=True)

    cp = sub.add_parser("collect", help="archive node logs / coordinator")
    cp.add_argument("--storage", required=True)
    cp.add_argument("--cluster", required=True)
    cp.add_argument("--namespace", default="default")
    cp.add_argument("--node", default="head")
    cp.add_argument("--log-dir", default="")
    cp.add_argument("--coordinator", default="",
                    help="head coordinator URL (archives jobs + metadata)")
    cp.add_argument("--interval", type=float, default=10.0)
    cp.add_argument("--once", action="store_true")

    args = ap.parse_args(argv)
    storage = backend_from_url(args.storage)

    if args.cmd == "prune":
        removed = prune_archive(storage, args.max_age_days * 86400)
        print(f"pruned {len(removed)} cluster archives"
              + (": " + ", ".join(removed) if removed else ""))
        return 0

    if args.cmd == "serve":
        if args.retention_days > 0:
            import threading

            def _retention_loop():
                while True:
                    try:
                        removed = prune_archive(
                            storage, args.retention_days * 86400)
                        if removed:
                            print(f"retention: pruned {removed}",
                                  flush=True)
                    except Exception as e:  # noqa: BLE001 — keep serving
                        print(f"retention pass failed: {e}", flush=True)
                    time.sleep(6 * 3600)
            threading.Thread(target=_retention_loop, daemon=True,
                             name="history-retention").start()
        srv = HistoryServer(storage).make_server(args.host, args.port)
        print(f"history server on {args.host}:{srv.server_port}")
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        return 0

    log_col = None
    if args.log_dir:
        log_col = LogCollector(storage, args.log_dir, cluster=args.cluster,
                               namespace=args.namespace, node=args.node,
                               poll_interval=args.interval)
    coord_col = None
    if args.coordinator:
        coord_col = CoordinatorCollector(
            storage, args.coordinator, cluster=args.cluster,
            namespace=args.namespace)
    if log_col is None and coord_col is None:
        ap.error("collect needs --log-dir and/or --coordinator")
    from kuberay_tpu.history.collector import stamp_collection
    try:
        while True:
            n = 0
            # A transient storage/coordinator error must not kill the
            # sidecar — skip the pass and retry on the next interval
            # (LogCollector._run has the same policy).
            try:
                if log_col is not None:
                    n += log_col.poll_once()
                if coord_col is None:
                    # Coordinator mode stamps inside collect_once;
                    # log-only mode must stamp too or retention would
                    # silently exempt these archives forever.
                    stamp_collection(storage, args.namespace,
                                     args.cluster)
                else:
                    n += coord_col.collect_once()
            except Exception as e:  # noqa: BLE001 — keep collecting
                if args.once:
                    raise
                print(f"collect pass failed, will retry: {e}",
                      flush=True)
            if args.once:
                print(f"archived {n} objects")
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
