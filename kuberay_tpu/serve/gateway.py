"""Weighted serve gateway: the Gateway-API consumer for TrafficRoute.

Closes the incremental-upgrade loop (service_controller's
``_reconcile_weighted_services`` records backend weights in a
``TrafficRoute`` object — ref reconcileGateway/HTTPRoute stepping,
rayservice_controller.go:920/:976): this process watches the route and
forwards inference requests to the per-cluster serve backends with
weighted random choice, so traffic genuinely shifts as the controller
steps the weights.

Backend resolution is pluggable: in a real cluster the Service name
resolves via DNS; embedded/tests inject a name->URL map.
"""

from __future__ import annotations

import logging
import json
import random
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.httpjson import JsonHandler, serve_background


_LOG = logging.getLogger("kuberay_tpu.gateway")


class WeightedGateway:
    def __init__(self, store, route_name: str, namespace: str = "default",
                 resolver: Optional[Callable[[str], str]] = None,
                 poll_interval: float = 1.0, metrics=None):
        """``resolver(service_name) -> base_url``; defaults to cluster-DNS
        (http://<svc>.<ns>.svc:<serve-port>).  ``metrics`` is an optional
        MetricsRegistry: forwarded requests observe
        ``tpu_serve_request_duration_seconds{phase="gateway"}`` (the
        end-to-end leg in front of the engine's queue/prefill/decode
        phases) and count ``tpu_gateway_requests_total`` per status code."""
        self.metrics = metrics
        if metrics is not None:
            metrics.describe("tpu_gateway_requests_total",
                             "Requests forwarded by the weighted gateway, "
                             "by HTTP status code")
        self.store = store
        self.route_name = route_name
        self.namespace = namespace
        self.resolver = resolver or (
            lambda svc: f"http://{svc}.{namespace}.svc:{C.PORT_SERVE}")
        self.poll_interval = poll_interval
        self._lock = threading.Lock()
        self._backends: List[Tuple[str, int]] = []   # (url, weight)
        self._stats: Dict[str, int] = {}
        self._stop = threading.Event()
        self._refresh()
        threading.Thread(target=self._watch_loop, daemon=True,
                         name="gateway-route-watch").start()

    # -- route sync --------------------------------------------------------

    def _refresh(self):
        route = self.store.try_get("TrafficRoute", self.route_name,
                                   self.namespace)
        backends = []
        if route is not None:
            for b in route.get("spec", {}).get("backends", []):
                if b.get("weight", 0) > 0:
                    backends.append((self.resolver(b["service"]),
                                     int(b["weight"])))
        with self._lock:
            self._backends = backends

    def _watch_loop(self):
        while not self._stop.is_set():
            try:
                self._refresh()
            except Exception:
                # Keep last-known-good backends on a refresh blip; a
                # persistently failing control plane must be loggable.
                _LOG.debug("route refresh failed; keeping last backends",
                           exc_info=True)
            self._stop.wait(self.poll_interval)

    def close(self):
        self._stop.set()

    # -- routing -----------------------------------------------------------

    def pick_backend(self) -> Optional[str]:
        with self._lock:
            backends = list(self._backends)
        if not backends:
            return None
        total = sum(w for _, w in backends)
        r = random.uniform(0, total)
        acc = 0.0
        for url, w in backends:
            acc += w
            if r <= acc:
                with self._lock:
                    self._stats[url] = self._stats.get(url, 0) + 1
                return url
        return backends[-1][0]

    def forward(self, path: str, body: bytes,
                timeout: float = 300.0) -> Tuple[int, bytes]:
        t0 = time.time()
        code, payload = self._forward(path, body, timeout)
        if self.metrics is not None:
            self.metrics.observe("tpu_serve_request_duration_seconds",
                                 time.time() - t0, {"phase": "gateway"})
            self.metrics.inc("tpu_gateway_requests_total",
                             {"code": str(code)})
        return code, payload

    def _forward(self, path: str, body: bytes,
                 timeout: float) -> Tuple[int, bytes]:
        url = self.pick_backend()
        if url is None:
            return 503, json.dumps(
                {"message": "no healthy backends in route"}).encode()
        req = urllib.request.Request(
            url + path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except Exception as e:
            return 502, json.dumps({"message": f"backend error: {e}"}).encode()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    # -- HTTP --------------------------------------------------------------

    def make_server(self, host="0.0.0.0", port=C.PORT_SERVE):
        gw = self

        class Handler(JsonHandler):
            def do_GET(self):
                if self.path == "/healthz":
                    return self._send(200, {"status": "ok"})
                if self.path == "/stats":
                    return self._send(200, gw.stats())
                if self.path == "/metrics" and gw.metrics is not None:
                    return self._send_text(200, gw.metrics.render(),
                                           "text/plain; version=0.0.4")
                return self._send(404, {"message": "unknown path"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b"{}"
                code, payload = gw.forward(self.path, body)
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        return ThreadingHTTPServer((host, port), Handler)

    def serve_background_http(self, host="127.0.0.1", port=0):
        return serve_background(self.make_server(host, port), "serve-gateway")
