"""Serve gateway: prefix-cache-aware scheduling over TrafficRoute.

Closes the incremental-upgrade loop (service_controller's
``_reconcile_weighted_services`` records backend weights in a
``TrafficRoute`` object — ref reconcileGateway/HTTPRoute stepping,
rayservice_controller.go:920/:976) and, since PR 7, routes like a
scheduler instead of a dice roll:

- **Prefix/session affinity** (SGLang-style cache-aware load
  balancing): a per-backend :class:`~kuberay_tpu.serve.prefix.PrefixIndex`
  shadows each replica's paged-KV prefix cache (same block hash chain,
  serve/prefix.py).  Requests score every weight-eligible backend with
  ``α·prefix-hit-depth − β·queue-depth`` and land on the max — so
  prompts sharing a prefix hit the replica that already holds those KV
  blocks, unless its queue has eaten the saving.
- **ε-fallback**: with probability ``epsilon`` (and always when
  affinity is disabled) the pick degrades to the original weighted
  random choice, which keeps exploring cold replicas and keeps the
  TrafficRoute weights meaningful in expectation.  Weight-0 backends
  are NEVER picked regardless of affinity — the controller's upgrade
  traffic shifts stay authoritative.
- **Continuous-batching admission**: per-backend in-flight tracking plus
  engine queue depth / KV occupancy read back from response headers
  (``X-TPU-Queue-Depth`` etc., serve/server.py).  When every eligible
  backend is at ``max_inflight``, requests wait in a bounded gateway
  queue; past ``max_queue`` waiters or the queue deadline they are SHED
  with 429 + ``Retry-After`` instead of piling onto backend queues —
  burst storms degrade to bounded p99 + explicit sheds, not fleet-wide
  timeouts.
- **Retry-on-connect-failure**: one retry on the next-best backend
  (failed backend excluded) when the connection itself fails; real HTTP
  error responses are returned as-is.

Backend resolution is pluggable: in a real cluster the Service name
resolves via DNS; embedded/tests inject a name->URL map.  ``rng`` and
``clock`` are injectable so seeded runs (benchmark/serve_bench.py
--traffic, sim-adjacent tests) replay exactly.
"""

from __future__ import annotations

import dataclasses
import logging
import json
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.serve.kv_tiers import FleetKvIndex, SessionTable
from kuberay_tpu.serve.prefix import (
    HotPrompts,
    PrefixIndex,
    affinity_score,
    block_hashes,
    decode_score,
    summarize_backend,
)
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.httpjson import JsonHandler, serve_background
from kuberay_tpu.utils.metrics import SERVE_LATENCY_BUCKETS


_LOG = logging.getLogger("kuberay_tpu.gateway")


@dataclasses.dataclass
class GatewayConfig:
    """Routing + admission knobs (docs/serving.md has the full table)."""

    affinity: bool = True          # False = legacy pure weighted random
    alpha: float = 4.0             # score per prefix-hit block
    beta: float = 1.0              # score penalty per queued/in-flight req
    # Load weight for the disagg prefill hop (None = beta).  A prefill
    # replica's cache is just the hot preambles — cheap to replicate
    # across the tier — so spilling a burst to an idle peer costs one
    # preamble prefill while staying home costs the whole queue; the
    # prefill hop can afford a far more load-averse score than the
    # single-hop path, whose spills also fragment decode-resident KV.
    prefill_beta: Optional[float] = None
    epsilon: float = 0.05          # weighted-random exploration fraction
    block_size: int = 16           # MUST match the backends' paged block
    index_capacity: int = 8192     # hashes per backend prefix index
    max_inflight: int = 0          # per-backend admission cap (0 = off)
    max_queue: int = 64            # gateway waiters before shedding
    queue_timeout: float = 10.0    # max seconds a request waits for a slot
    retry_after: float = 1.0       # Retry-After hint on 429s
    retry_connect: bool = True     # one retry on next-best backend
    kv_weight: float = 2.0         # decode-hop bonus per unit KV-free frac
    kv_transfer: bool = True       # ship prefill KV deltas on the 2nd hop
    # Per-request transfer budget in blocks (0 = unlimited).  Shipping
    # the whole delta serializes float32 pages through base64+JSON on
    # the gateway's CPU; beyond a few blocks the transfer costs more
    # than the decode replica recomputing the tail, so cap the shipped
    # prefix and let hop 2 re-prefill the remainder.
    kv_max_blocks: int = 0
    # Stateful sessions (docs/kv-tiers.md): requests carrying a
    # "session" id resume their KV chain from the last-seen backend's
    # tiers, or fleet-fetch it from whichever peer the residency index
    # says holds it, instead of recomputing prefill.
    session_capacity: int = 1024   # gateway session objects (LRU bound)
    session_ttl: float = 600.0     # idle seconds before a session expires
    fleet_fetch: bool = True       # source missing blocks from a peer


class _Overloaded(Exception):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _HopFailed(Exception):
    """A tier-scoped hop could not produce a backend response; carries
    the HTTP error the gateway should surface."""

    def __init__(self, code: int, payload: bytes, backend: str = "none"):
        super().__init__(f"hop failed: http {code}")
        self.code = code
        self.payload = payload
        self.backend = backend


class _BackendState:
    __slots__ = ("service", "url", "weight", "tier", "inflight",
                 "queue_depth", "kv_free_blocks", "kv_total_blocks",
                 "host_free_blocks", "host_total_blocks",
                 "index", "picks")

    def __init__(self, service: str, url: str, index_capacity: int):
        self.service = service
        self.url = url
        self.weight = 0
        self.tier = "mixed"           # prefill | decode | mixed
        self.inflight = 0
        self.queue_depth = 0          # last backend-reported engine queue
        self.kv_free_blocks = 0
        self.kv_total_blocks = 0
        self.host_free_blocks = 0     # host-DRAM KV tier occupancy
        self.host_total_blocks = 0
        self.index = PrefixIndex(index_capacity)
        self.picks = 0

    @property
    def load(self) -> float:
        return self.inflight + self.queue_depth


class WeightedGateway:
    def __init__(self, store, route_name: str, namespace: str = "default",
                 resolver: Optional[Callable[[str], str]] = None,
                 poll_interval: float = 1.0, metrics=None,
                 config: Optional[GatewayConfig] = None,
                 rng: Optional[random.Random] = None, clock=None,
                 tracer=None, flight=None, profiler=None):
        """``resolver(service_name) -> base_url``; defaults to cluster-DNS
        (http://<svc>.<ns>.svc:<serve-port>).  ``metrics`` is an optional
        MetricsRegistry: forwarded requests observe
        ``tpu_serve_request_duration_seconds{phase="gateway"}`` and count
        ``tpu_gateway_requests_total{backend,code}``, prefix-affine picks
        count ``tpu_gateway_prefix_cache_hits_total{backend}``, and shed
        requests count ``tpu_gateway_shed_total{reason}``.  ``rng`` and
        ``clock`` (an object with ``.now()``) default to the module
        ``random``/wall clock; inject both for seeded deterministic
        runs.  ``tracer`` (obs.trace) mints one trace per request —
        gateway-queue / route-decision / forward spans, the traceparent
        header across the replica hop, and the trace id echoed to the
        client.  ``flight`` (obs.FlightRecorder) records backend
        lifecycle — weight changes, dead-backend exclusion,
        retry-failover — keyed ("Backend", ns, service).  ``profiler``
        (obs.RequestProfiler) is noted on every request completion
        with the trace id and the backend that finally answered — the
        feed behind /debug/profile's per-backend scoping and the
        upgrade ramp's build-vs-build trace diff."""
        self.metrics = metrics
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.flight = flight
        self.profiler = profiler
        if metrics is not None:
            metrics.describe("tpu_gateway_requests_total",
                             "Requests forwarded by the serve gateway, "
                             "by backend service and HTTP status code")
            metrics.describe("tpu_gateway_prefix_cache_hits_total",
                             "Requests routed to a backend already "
                             "holding part of their prompt prefix, by "
                             "backend service")
            metrics.describe("tpu_gateway_shed_total",
                             "Requests shed by gateway admission (429 + "
                             "Retry-After), by reason (queue_full | "
                             "deadline)")
            metrics.describe("tpu_serve_kv_transfer_blocks_total",
                             "Paged-KV blocks handled by the prefill->"
                             "decode transfer, by outcome (sent = delta "
                             "blocks shipped, skipped = already resident "
                             "on the decode replica)")
            metrics.describe("tpu_serve_kv_transfer_seconds",
                             "Wall seconds per prefill->decode KV "
                             "transfer (resident probe + export + import)")
            metrics.describe("tpu_gateway_backend_attempts_total",
                             "Forward attempts per backend service, "
                             "including connect failures that failed "
                             "over — the denominator of the upgrade "
                             "gate's green availability SLO")
            metrics.describe("tpu_gateway_backend_errors_total",
                             "Failed forward attempts per backend "
                             "service (connect/transport failure or a "
                             "5xx response) — the numerator of the "
                             "upgrade gate's green availability SLO")
            metrics.describe("tpu_gateway_backend_latency_seconds",
                             "Per-backend forward latency histogram — "
                             "the upgrade gate's green TTFT SLO reads "
                             "this scoped to the green backend")
            metrics.describe("tpu_upgrade_prewarm_prompts_total",
                             "Hot prompt prefixes replayed into a cold "
                             "green backend before its first weight "
                             "step, by backend service")
            metrics.describe("tpu_upgrade_drain_seconds",
                             "Wall seconds from a backend's drain flag "
                             "appearing on the route to its in-flight "
                             "set reaching zero")
            metrics.describe("tpu_serve_session_resumes_total",
                             "Session-carrying requests by where their "
                             "KV chain came from (local = chosen "
                             "backend's tiers, fleet = fetched from a "
                             "named peer, miss = prefill recompute)")
            metrics.describe("tpu_gateway_sessions",
                             "Live session objects in the gateway's "
                             "session table")
            metrics.describe("tpu_kv_fleet_fetch_blocks_total",
                             "Paged-KV blocks handled by session fleet "
                             "fetches, by outcome (sent | skipped)")
            metrics.describe("tpu_kv_index_invalidations_total",
                             "Prefix-index entries unlearned on replica "
                             "eviction adverts, by backend service")
        self.store = store
        self.route_name = route_name
        self.namespace = namespace
        self.resolver = resolver or (
            lambda svc: f"http://{svc}.{namespace}.svc:{C.PORT_SERVE}")
        self.poll_interval = poll_interval
        self.config = config or GatewayConfig()
        self._rng = rng if rng is not None else random.Random()
        self._now = clock.now if clock is not None else time.time
        self._lock = threading.Lock()
        self._slot_free = threading.Condition(self._lock)
        self._states: Dict[str, _BackendState] = {}   # service -> state
        self._active: List[str] = []                  # routed service names
        self._stats: Dict[str, int] = {}              # url -> picks
        self._waiting = 0
        # Upgrade handshakes (docs/upgrades.md): the fleet's hottest
        # prompt prefixes (replayed into cold green backends), replay
        # results per backend, and when each drain flag was first seen.
        self._hot = HotPrompts()
        self._replayed: Dict[str, int] = {}
        self._drain_seen: Dict[str, float] = {}
        # Stateful sessions + fleet-wide residency (serve/kv_tiers.py):
        # the session table keys resume requests to their KV chain, the
        # fleet index folds backend adverts into an exact hash -> tier
        # map per replica.  Both guarded by self._lock.
        self._sessions = SessionTable(self.config.session_capacity,
                                      self.config.session_ttl,
                                      clock=self._now)
        self._fleet = FleetKvIndex()
        self._stop = threading.Event()
        self._refresh()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, daemon=True, name="gateway-route-watch")
        self._watch_thread.start()

    # -- lifecycle ---------------------------------------------------------

    def stop(self):
        """Stop the route watcher and join its thread (a gateway left
        unclosed used to leak one daemon thread per test)."""
        self._stop.set()
        if self._watch_thread.is_alive() and \
                self._watch_thread is not threading.current_thread():
            self._watch_thread.join(timeout=5.0)

    # Back-compat alias (pre-PR-7 callers).
    def close(self):
        self.stop()

    def __enter__(self) -> "WeightedGateway":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- route sync --------------------------------------------------------

    def _refresh(self):
        route = self.store.try_get("TrafficRoute", self.route_name,
                                   self.namespace)
        if route is None:
            # Promotion deletes the route (steady state needs no weighted
            # routing).  Collapse onto the surviving backend — the
            # highest-weight one we last saw — at weight 100 rather than
            # zeroing everything out: there must be no window where the
            # gateway has stale weights or no backends at all.
            self._fallback_to_survivor()
            return
        entries: List[Tuple[str, int, str]] = []
        for b in route.get("spec", {}).get("backends", []):
            if b.get("weight", 0) > 0:
                entries.append((b["service"], int(b["weight"]),
                                b.get("tier") or "mixed"))
        weight_changes: List[Tuple[str, int, int]] = []
        with self._lock:
            # Keep prior state (prefix index, load) across weight steps:
            # an upgrade shifting 10% -> 50% must not cold-start the new
            # cluster's affinity map at every step.
            for svc, w, tier in entries:
                st = self._states.get(svc)
                if st is None:
                    st = self._states[svc] = _BackendState(
                        svc, self.resolver(svc), self.config.index_capacity)
                if st.weight != w:
                    weight_changes.append((svc, st.weight, w))
                st.weight = w
                st.tier = tier
            active = {svc for svc, _, _ in entries}
            for svc, st in self._states.items():
                if svc not in active:
                    if st.weight != 0:
                        weight_changes.append((svc, st.weight, 0))
                    st.weight = 0
            self._active = [svc for svc, _, _ in entries]
        if self.flight is not None:
            for svc, old, new in weight_changes:
                self.flight.record("Backend", self.namespace, svc,
                                   "weight", f"{old} -> {new}")
        self._maybe_prewarm(route)
        self._maybe_drain(route)

    def _fallback_to_survivor(self):
        changes: List[Tuple[str, int, int]] = []
        with self._lock:
            live = [s for s in self._states.values() if s.weight > 0]
            if not live:
                return      # cold start, or already collapsed
            keep = max(live, key=lambda s: (s.weight, s.service))
            for svc, s in self._states.items():
                new = 100 if s is keep else 0
                if s.weight != new:
                    changes.append((svc, s.weight, new))
                s.weight = new
                if s is not keep:
                    # Retired with the route: its blocks are gone for
                    # fleet-fetch purposes, its sessions re-place.
                    self._fleet.drop_backend(svc)
                    self._sessions.forget_backend(svc)
            self._active = [keep.service]
            self._drain_seen.clear()
        if self.flight is not None:
            for svc, old, new in changes:
                self.flight.record("Backend", self.namespace, svc,
                                   "weight",
                                   f"{old} -> {new} (route deleted)")

    # -- upgrade handshakes (prefix pre-warm + session drain) --------------

    def _maybe_prewarm(self, route: dict) -> None:
        """Backends flagged ``prewarm: N`` on the route get the fleet's
        hottest prompt prefixes replayed into them (max_tokens=1 — one
        prefill each), then an ack in the route's status the service
        controller gates the first weight step on."""
        acked = (route.get("status") or {}).get("prewarmed") or {}
        for b in route.get("spec", {}).get("backends", []):
            svc = b.get("service")
            n = int(b.get("prewarm", 0) or 0)
            if not svc or n <= 0 or svc in acked:
                continue
            if svc not in self._replayed:
                self._replayed[svc] = self._replay_prefixes(svc, n)
            self._ack_route("prewarmed", svc, self._replayed[svc])

    def _replay_prefixes(self, svc: str, n: int) -> int:
        with self._lock:
            st = self._states.get(svc)
            url = st.url if st is not None else self.resolver(svc)
            prompts = self._hot.hottest(n)
        ok = 0
        for p in prompts:
            body = json.dumps({"prompt_tokens": p, "max_tokens": 1}).encode()
            try:
                code, _, _ = self._request(url, "/v1/completions", body, 10.0)
            except (urllib.error.URLError, ConnectionError, OSError):
                continue
            if code == 200:
                ok += 1
                hashes = block_hashes(p, self.config.block_size)
                if hashes and st is not None:
                    with self._lock:
                        st.index.insert(hashes)
                if self.metrics is not None:
                    self.metrics.inc("tpu_upgrade_prewarm_prompts_total",
                                     {"backend": svc})
        if self.flight is not None:
            self.flight.record("Backend", self.namespace, svc, "prewarm",
                               f"replayed {ok}/{len(prompts)} hot prefixes")
        return ok

    def _maybe_drain(self, route: dict) -> None:
        """Backends flagged ``drain: true`` (blue, at weight 0) are acked
        in the route's status once their in-flight set reaches zero —
        the service controller holds promotion (and the blue cluster's
        retirement) on it, so retiring replicas never cut off admitted
        requests."""
        acked = (route.get("status") or {}).get("drained") or {}
        flagged = {b.get("service") for b in
                   route.get("spec", {}).get("backends", [])
                   if b.get("drain")}
        for svc in list(self._drain_seen):
            if svc not in flagged:
                self._drain_seen.pop(svc, None)
        for svc in flagged:
            if not svc or svc in acked:
                continue
            t0 = self._drain_seen.setdefault(svc, self._now())
            with self._lock:
                st = self._states.get(svc)
                busy = st is not None and st.inflight > 0
            if busy:
                continue
            if self.metrics is not None:
                self.metrics.observe("tpu_upgrade_drain_seconds",
                                     self._now() - t0)
            if self.flight is not None:
                self.flight.record("Backend", self.namespace, svc,
                                   "drained",
                                   f"after {self._now() - t0:.3f}s")
            self._ack_route("drained", svc, True)

    def _ack_route(self, field: str, svc: str, value) -> None:
        obj = self.store.try_get("TrafficRoute", self.route_name,
                                 self.namespace)
        if obj is None:
            return
        slot = obj.setdefault("status", {}).setdefault(field, {})
        if slot.get(svc) == value:
            return
        slot[svc] = value
        try:
            self.store.update_status(obj)
        except Exception:
            # Conflict/NotFound: the next poll re-acks idempotently.
            _LOG.debug("route %s ack failed; will retry", field,
                       exc_info=True)

    def _watch_loop(self):
        while not self._stop.is_set():
            try:
                self._refresh()
            except Exception:
                # Keep last-known-good backends on a refresh blip; a
                # persistently failing control plane must be loggable.
                _LOG.debug("route refresh failed; keeping last backends",
                           exc_info=True)
            self._stop.wait(self.poll_interval)

    # -- routing -----------------------------------------------------------

    def _eligible_locked(self, exclude: Sequence[str],
                         tier: Optional[str] = None) -> List[_BackendState]:
        return [self._states[svc] for svc in self._active
                if self._states[svc].weight > 0
                and self._states[svc].url not in exclude
                and (tier is None or self._states[svc].tier == tier)]

    def _disagg_locked(self) -> bool:
        """True when the route is a two-tier fleet: at least one live
        prefill backend AND one live decode backend."""
        tiers = {s.tier for s in self._states.values() if s.weight > 0}
        return "prefill" in tiers and "decode" in tiers

    def _weighted_random_locked(self,
                                cands: List[_BackendState]) -> _BackendState:
        total = sum(s.weight for s in cands)
        r = self._rng.uniform(0, total)
        acc = 0.0
        for s in cands:
            acc += s.weight
            if r <= acc:
                return s
        return cands[-1]

    def _select_locked(self, cands: List[_BackendState],
                       hashes: Sequence[int], decode: bool = False,
                       prefill: bool = False
                       ) -> Tuple[_BackendState, int, bool]:
        """Pick one backend among the weight-eligible candidates.
        ``decode`` switches the score to the decode-hop variant (KV
        locality + free-block headroom, serve/prefix.py decode_score);
        ``prefill`` swaps in the prefill-hop load weight.
        Returns (state, prefix_hit_depth_of_pick, epsilon_fallback)."""
        cfg = self.config
        if not cfg.affinity or self._rng.random() < cfg.epsilon:
            s = self._weighted_random_locked(cands)
            return s, 0, cfg.affinity
        if decode:
            scored = [(decode_score(
                s.index.hit_depth(hashes) if hashes else 0, s.load,
                s.kv_free_blocks, s.kv_total_blocks,
                cfg.alpha, cfg.beta, cfg.kv_weight), s)
                for s in cands]
        else:
            beta = cfg.beta
            if prefill and cfg.prefill_beta is not None:
                beta = cfg.prefill_beta
            scored = [(affinity_score(
                s.index.hit_depth(hashes) if hashes else 0,
                s.load, cfg.alpha, beta), s)
                for s in cands]
        # Recompute each pick's depth only for the winner set (hit_depth
        # above already touched the LRU; cheap to re-probe).
        best = max(score for score, _ in scored)
        top = [s for score, s in scored if score == best]
        s = top[0] if len(top) == 1 else self._weighted_random_locked(top)
        depth = s.index.hit_depth(hashes) if hashes else 0
        return s, depth, False

    def pick_backend(self, prompt_tokens: Optional[Sequence[int]] = None,
                     exclude: Sequence[str] = ()) -> Optional[str]:
        """Route one request (no admission wait): the scored pick when
        affinity is on, weighted random on the ε-roll / when off.
        ``exclude`` holds backend URLs already tried (retry path)."""
        hashes = block_hashes(prompt_tokens, self.config.block_size) \
            if prompt_tokens else []
        with self._lock:
            cands = self._eligible_locked(exclude)
            if not cands:
                return None
            s, _, _ = self._select_locked(cands, hashes)
            self._note_pick_locked(s)
            return s.url

    def _note_pick_locked(self, s: _BackendState) -> None:
        s.picks += 1
        self._stats[s.url] = self._stats.get(s.url, 0) + 1

    def _shed(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("tpu_gateway_shed_total", {"reason": reason})
        raise _Overloaded(reason)

    def _acquire(self, hashes: Sequence[int], timeout: float,
                 exclude: Sequence[str], tier: Optional[str] = None,
                 prefer: str = ""
                 ) -> Optional[Tuple[_BackendState, int, bool]]:
        """Admission + routing: pick a backend with a free in-flight slot,
        waiting (bounded queue, bounded time) when all are saturated.
        ``tier`` restricts candidates to one fleet tier (disaggregated
        two-hop path); ``prefer`` names a backend taken over the scored
        pick whenever it is eligible with a free slot (session
        stickiness — its tiers hold the chain).  Returns (state,
        hit_depth, epsilon_fallback), or None when the route has no
        eligible backend (503); raises :class:`_Overloaded` on shed
        (429)."""
        cfg = self.config
        deadline = time.monotonic() + min(timeout, cfg.queue_timeout)
        with self._slot_free:
            while True:
                cands = self._eligible_locked(exclude, tier)
                if not cands:
                    return None
                free = [s for s in cands
                        if cfg.max_inflight <= 0
                        or s.inflight < cfg.max_inflight]
                if free:
                    sticky = [s for s in free if s.service == prefer] \
                        if prefer else []
                    if sticky:
                        s = sticky[0]
                        depth = s.index.hit_depth(hashes) if hashes else 0
                        eps = False
                    else:
                        s, depth, eps = self._select_locked(
                            free, hashes, decode=(tier == "decode"),
                            prefill=(tier == "prefill"))
                    s.inflight += 1
                    self._note_pick_locked(s)
                    if depth > 0 and self.metrics is not None:
                        self.metrics.inc(
                            "tpu_gateway_prefix_cache_hits_total",
                            {"backend": s.service})
                    return s, depth, eps
                # All eligible backends saturated: queue or shed.
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._shed("deadline")
                if self._waiting >= cfg.max_queue:
                    self._shed("queue_full")
                self._waiting += 1
                try:
                    self._slot_free.wait(min(remaining, 0.05))
                finally:
                    self._waiting -= 1

    def _release(self, s: _BackendState) -> None:
        with self._slot_free:
            s.inflight -= 1
            self._slot_free.notify()

    # -- forwarding --------------------------------------------------------

    @staticmethod
    def _prompt_tokens(body: bytes) -> List[int]:
        """Best-effort prompt extraction for the affinity hash; anything
        unparseable routes like a promptless request."""
        try:
            doc = json.loads(body or b"{}")
            toks = doc.get("prompt_tokens")
            if isinstance(toks, list) and \
                    all(isinstance(t, int) for t in toks):
                return toks
        except Exception:
            pass
        return []

    def forward(self, path: str, body: bytes,
                timeout: float = 300.0) -> Tuple[int, bytes]:
        code, payload, _ = self.forward_ex(path, body, timeout)
        return code, payload

    def forward_ex(self, path: str, body: bytes, timeout: float = 300.0
                   ) -> Tuple[int, bytes, Dict[str, str]]:
        """forward() plus response headers the HTTP surface relays
        (Retry-After on sheds, traceparent always)."""
        t0 = self._now()
        backend = "none"
        ctx = self.tracer.start_request("serve-request", ts=t0, path=path)
        try:
            code, payload, backend, headers = self._forward(
                path, body, timeout, ctx)
        except _Overloaded as e:
            code = 429
            payload = json.dumps(
                {"message": f"gateway overloaded ({e.reason}); retry "
                            f"after {self.config.retry_after:g}s"}).encode()
            headers = {"Retry-After": f"{self.config.retry_after:g}"}
        if ctx is not None:
            headers = dict(headers)
            headers["traceparent"] = ctx.to_traceparent()
            self.tracer.finish_request(
                ctx, ts=self._now(),
                status="ok" if code < 400 else "error",
                error="" if code < 400 else f"http {code}")
            if self.profiler is not None:
                self.profiler.note(ctx.trace_id, backend)
        if self.metrics is not None:
            self.metrics.observe("tpu_serve_request_duration_seconds",
                                 self._now() - t0, {"phase": "gateway"},
                                 exemplar=ctx.trace_id if ctx else None)
            self.metrics.inc("tpu_gateway_requests_total",
                             {"backend": backend, "code": str(code)})
        return code, payload, headers

    def _note_attempt(self, service: str, t0: float,
                      code: Optional[int] = None,
                      connect_failed: bool = False) -> None:
        """Per-attempt backend health series — the green-scoped burn-rate
        gate (controlplane.upgrade.green_slos) reads these, so a backend
        that fails over still shows up as an attempt + error on ITS OWN
        series even though the client saw the retry succeed."""
        if self.metrics is None:
            return
        self.metrics.inc("tpu_gateway_backend_attempts_total",
                         {"backend": service})
        if connect_failed or (code is not None and code >= 500):
            self.metrics.inc("tpu_gateway_backend_errors_total",
                             {"backend": service})
        if not connect_failed:
            self.metrics.observe("tpu_gateway_backend_latency_seconds",
                                 self._now() - t0, {"backend": service},
                                 buckets=SERVE_LATENCY_BUCKETS)

    def _forward(self, path: str, body: bytes, timeout: float, ctx=None
                 ) -> Tuple[int, bytes, str, Dict[str, str]]:
        prompt = self._prompt_tokens(body)
        hashes = block_hashes(prompt, self.config.block_size) \
            if prompt else []
        if prompt and path.endswith("/completions"):
            try:
                doc = json.loads(body or b"{}")
            except Exception:
                doc = None
            # Streaming stays single-hop/stateless: the prefill/decode
            # splice and the session chain update both rewrite the token
            # list, which has no incremental representation over SSE.
            if isinstance(doc, dict) and not doc.get("stream"):
                with self._lock:
                    disagg = self._disagg_locked()
                if disagg:
                    return self._forward_disagg(
                        path, timeout, ctx, prompt, hashes, doc)
                sid = doc.get("session")
                if isinstance(sid, str) and sid:
                    return self._forward_session(
                        path, body, timeout, ctx, prompt, hashes, sid)
        tried: List[str] = []
        failed_svc = ""
        attempts = 2 if self.config.retry_connect else 1
        last_err: Optional[Exception] = None
        for _ in range(attempts):
            q0 = self._now()
            try:
                picked = self._acquire(hashes, timeout, exclude=tried)
            except _Overloaded as e:
                self.tracer.record_span(
                    ctx, "gateway-queue", q0, self._now(),
                    status="error", error=f"shed: {e.reason}")
                raise
            if picked is None:
                if tried:
                    break                  # every live backend was tried
                return 503, json.dumps(
                    {"message": "no healthy backends in route"}).encode(), \
                    "none", {}
            s, depth, eps = picked
            q1 = self._now()
            self.tracer.record_span(ctx, "gateway-queue", q0, q1)
            self.tracer.record_span(
                ctx, "route-decision", q1, q1, backend=s.service,
                hit_depth=depth, queue_depth=s.queue_depth,
                epsilon_fallback=eps)
            if failed_svc and self.flight is not None:
                self.flight.record(
                    "Backend", self.namespace, s.service, "retry",
                    f"failover from {failed_svc}")
            f0 = self._now()
            try:
                code, payload, resp_headers = self._request(
                    s.url, path, body, timeout, trace_ctx=ctx)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # Connect/transport failure: this replica may be mid-
                # replacement — retry ONCE on the next-best backend.
                last_err = e
                tried.append(s.url)
                failed_svc = s.service
                self._note_attempt(s.service, f0, connect_failed=True)
                self.tracer.record_span(
                    ctx, "forward", f0, self._now(), backend=s.service,
                    status="error", error=f"connect: {e}")
                if self.flight is not None:
                    self.flight.record(
                        "Backend", self.namespace, s.service, "exclude",
                        f"connect-failure: {e}")
                continue
            finally:
                self._release(s)
            self._note_attempt(s.service, f0, code=code)
            self.tracer.record_span(ctx, "forward", f0, self._now(),
                                    backend=s.service, code=code)
            self._observe_backend(s, resp_headers)
            if hashes and code < 500:
                with self._lock:
                    s.index.insert(hashes)
                    self._hot.record(prompt, self.config.block_size)
            return code, payload, s.service, {}
        return 502, json.dumps(
            {"message": f"backend error: {last_err}"}).encode(), \
            (self._service_of(tried[-1]) if tried else "none"), {}

    # -- stateful session path (docs/kv-tiers.md) -------------------------

    def _forward_session(self, path: str, body: bytes, timeout: float, ctx,
                         prompt: List[int], hashes: Sequence[int], sid: str
                         ) -> Tuple[int, bytes, str, Dict[str, str]]:
        """Resume-aware forward for requests carrying a ``session`` id:
        look the session up (sticky to its last backend), decide where
        its KV chain comes from — the chosen backend's own tiers, or a
        fleet fetch from the peer the residency index names — then
        forward and extend the chain with the generated tokens.  The
        trace decomposes into session-lookup / fleet-fetch (when a peer
        sourced blocks) / forward spans under the serve-request root."""
        cfg = self.config
        l0 = self._now()
        with self._lock:
            self._sessions.sweep()
            sess = self._sessions.lookup(sid)
        self.tracer.record_span(
            ctx, "session-lookup", l0, self._now(), session_id=sid,
            known=sess is not None,
            last_backend=sess.backend if sess is not None else "")
        fetch = {"source": "miss", "blocks": 0}

        def _pre(s: _BackendState) -> None:
            if not hashes:
                return
            with self._lock:
                local = self._fleet.resident_depth(s.service, hashes)
                peer_svc, peer_depth = (None, 0)
                if cfg.fleet_fetch and cfg.kv_transfer:
                    peer_svc, peer_depth = self._fleet.best_source(
                        hashes, exclude=(s.service,))
                peer = self._states.get(peer_svc) if peer_svc else None
            if peer is not None and peer_depth > local:
                k0 = self._now()
                sent = skipped = 0
                status, err = "ok", ""
                try:
                    sent, skipped = self._kv_transfer(peer, s, prompt,
                                                      timeout, ctx)
                except Exception as e:  # best-effort: replica re-prefills
                    status, err = "error", f"fleet-fetch: {e}"
                self.tracer.record_span(
                    ctx, "fleet-fetch", k0, self._now(), src=peer.service,
                    dst=s.service, blocks_sent=sent, blocks_skipped=skipped,
                    status=status, error=err)
                if self.metrics is not None:
                    if sent:
                        self.metrics.inc("tpu_kv_fleet_fetch_blocks_total",
                                         {"outcome": "sent"}, sent)
                    if skipped:
                        self.metrics.inc("tpu_kv_fleet_fetch_blocks_total",
                                         {"outcome": "skipped"}, skipped)
                if sent:
                    fetch["source"], fetch["blocks"] = "fleet", sent
                elif skipped:
                    fetch["source"] = "local"
            elif local > 0:
                fetch["source"] = "local"

        try:
            s, code, payload = self._hop(
                None, hashes, path, body, timeout, ctx, "forward",
                pre_forward=_pre,
                prefer=sess.backend if sess is not None else "")
        except _HopFailed as e:
            return e.code, e.payload, e.backend, {}
        if code == 200:
            try:
                out_tokens = list(json.loads(payload).get("tokens") or [])
            except Exception:
                out_tokens = []
            # The chain covers prompt + response: the next turn's prompt
            # extends this conversation, so its leading hashes match.
            full = list(prompt) + out_tokens
            chain = block_hashes(full, cfg.block_size)
            with self._lock:
                self._sessions.touch(sid, chain, len(full), s.service)
                self._hot.record(prompt, cfg.block_size)
                nsess = len(self._sessions)
            if self.metrics is not None:
                self.metrics.inc("tpu_serve_session_resumes_total",
                                 {"source": fetch["source"]})
                self.metrics.set_gauge("tpu_gateway_sessions", float(nsess))
        return code, payload, s.service, {}

    # -- disaggregated two-hop path ---------------------------------------

    def _hop(self, tier: Optional[str], hashes: Sequence[int], path: str,
             body: bytes, timeout: float, ctx, span_name: str,
             pre_forward=None, prefer: str = ""
             ) -> Tuple[_BackendState, int, bytes]:
        """One tier-scoped forward with the single-hop path's admission +
        retry-on-connect semantics (``tier=None`` admits any backend —
        the session path).  ``pre_forward(state)`` runs while the
        slot is held, before the request — the decode hop's KV transfer
        hook and the session path's fleet fetch, re-run against the
        fallback replica on retry.  ``prefer`` is session stickiness
        (see _acquire).  Returns (state, code, payload); raises
        :class:`_Overloaded` on shed and :class:`_HopFailed` when no
        backend produced a response."""
        tname = tier or "any"
        tried: List[str] = []
        failed_svc = ""
        attempts = 2 if self.config.retry_connect else 1
        last_err: Optional[Exception] = None
        for _ in range(attempts):
            q0 = self._now()
            try:
                picked = self._acquire(hashes, timeout, exclude=tried,
                                       tier=tier, prefer=prefer)
            except _Overloaded as e:
                self.tracer.record_span(
                    ctx, "gateway-queue", q0, self._now(), tier=tname,
                    status="error", error=f"shed: {e.reason}")
                raise
            if picked is None:
                if tried:
                    break
                raise _HopFailed(503, json.dumps(
                    {"message": f"no healthy {tname} backends in route"}
                ).encode())
            s, depth, eps = picked
            q1 = self._now()
            self.tracer.record_span(ctx, "gateway-queue", q0, q1, tier=tname)
            self.tracer.record_span(
                ctx, "route-decision", q1, q1, backend=s.service, tier=tname,
                hit_depth=depth, queue_depth=s.queue_depth,
                epsilon_fallback=eps)
            if failed_svc and self.flight is not None:
                self.flight.record(
                    "Backend", self.namespace, s.service, "retry",
                    f"failover from {failed_svc}")
            if pre_forward is not None:
                pre_forward(s)
            f0 = self._now()
            try:
                code, payload, resp_headers = self._request(
                    s.url, path, body, timeout, trace_ctx=ctx)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last_err = e
                tried.append(s.url)
                failed_svc = s.service
                self._note_attempt(s.service, f0, connect_failed=True)
                self.tracer.record_span(
                    ctx, span_name, f0, self._now(), backend=s.service,
                    status="error", error=f"connect: {e}")
                if self.flight is not None:
                    self.flight.record(
                        "Backend", self.namespace, s.service, "exclude",
                        f"connect-failure: {e}")
                continue
            finally:
                self._release(s)
            self._note_attempt(s.service, f0, code=code)
            self.tracer.record_span(ctx, span_name, f0, self._now(),
                                    backend=s.service, code=code)
            self._observe_backend(s, resp_headers)
            if hashes and code < 500:
                with self._lock:
                    s.index.insert(hashes)
            return s, code, payload
        raise _HopFailed(502, json.dumps(
            {"message": f"{tname} backend error: {last_err}"}).encode(),
            self._service_of(tried[-1]) if tried else "none")

    def _forward_disagg(self, path: str, timeout: float, ctx,
                        prompt: List[int], hashes: Sequence[int], doc: dict
                        ) -> Tuple[int, bytes, str, Dict[str, str]]:
        """Two-hop schedule: hop 1 runs the prefill on the prefill tier
        (prefix affinity, ``max_tokens=1`` so the replica stops after the
        first sampled token), then ships the prompt's KV blocks — delta
        only, resident blocks skipped — into the chosen decode replica,
        and hop 2 finishes generation there seeded with prompt + first
        token.  The merged ``ttft_ms`` is the prefill replica's
        engine-measured enqueue-to-first-token — the same meter a
        colocated response reports, so mixed/disagg TTFTs compare
        apples-to-apples; the gateway-measured hop-1 wall (adds the
        gateway's own scheduling + HTTP time) rides in
        ``disagg.prefill_hop_ms``."""
        cfg = self.config
        t0 = self._now()
        pre = dict(doc)
        pre["max_tokens"] = 1
        pre.pop("stream", None)
        try:
            pf, code, payload = self._hop(
                "prefill", hashes, path, json.dumps(pre).encode(),
                timeout, ctx, "prefill-forward")
        except _HopFailed as e:
            return e.code, e.payload, e.backend, {}
        if self.metrics is not None:
            self.metrics.observe("tpu_serve_request_duration_seconds",
                                 self._now() - t0,
                                 {"phase": "gateway-prefill"},
                                 exemplar=ctx.trace_id if ctx else None)
        if code != 200:
            return code, payload, pf.service, {}
        try:
            pdoc = json.loads(payload)
        except Exception:
            return 502, json.dumps(
                {"message": "unparseable prefill response"}).encode(), \
                pf.service, {}
        tok0 = list(pdoc.get("tokens") or [])[:1]
        ttft_ms = (self._now() - t0) * 1e3
        try:
            max_tokens = int(doc.get("max_tokens", 64))
        except (TypeError, ValueError):
            max_tokens = 64
        if max_tokens <= 1 or not tok0:
            pdoc.setdefault("ttft_ms", round(ttft_ms, 3))
            pdoc["disagg"] = {"prefill": pf.service, "decode": None,
                              "prefill_hop_ms": round(ttft_ms, 3),
                              "kv_sent": 0, "kv_skipped": 0}
            return 200, json.dumps(pdoc).encode(), pf.service, {}

        xfer = {"sent": 0, "skipped": 0}

        def _pre(de: _BackendState) -> None:
            if not cfg.kv_transfer or de.url == pf.url:
                return
            k0 = self._now()
            sent = skipped = 0
            status, err = "ok", ""
            try:
                sent, skipped = self._kv_transfer(pf, de, prompt, timeout,
                                                  ctx)
            except Exception as e:      # best-effort: decode re-prefills
                status, err = "error", f"kv-transfer: {e}"
            k1 = self._now()
            self.tracer.record_span(
                ctx, "kv-transfer", k0, k1, src=pf.service, dst=de.service,
                blocks_sent=sent, blocks_skipped=skipped, status=status,
                error=err)
            xfer["sent"], xfer["skipped"] = sent, skipped
            if self.metrics is not None:
                if sent:
                    self.metrics.inc("tpu_serve_kv_transfer_blocks_total",
                                     {"outcome": "sent"}, sent)
                if skipped:
                    self.metrics.inc("tpu_serve_kv_transfer_blocks_total",
                                     {"outcome": "skipped"}, skipped)
                self.metrics.observe("tpu_serve_kv_transfer_seconds",
                                     k1 - k0)

        dec = dict(doc)
        dec["prompt_tokens"] = list(prompt) + tok0
        dec["max_tokens"] = max_tokens - 1
        dec.pop("stream", None)
        d0 = self._now()
        try:
            de, code, payload = self._hop(
                "decode", hashes, path, json.dumps(dec).encode(),
                timeout, ctx, "decode-forward", pre_forward=_pre)
        except _HopFailed as e:
            return e.code, e.payload, e.backend, {}
        if self.metrics is not None:
            self.metrics.observe("tpu_serve_request_duration_seconds",
                                 self._now() - d0,
                                 {"phase": "gateway-decode"},
                                 exemplar=ctx.trace_id if ctx else None)
        if code != 200:
            return code, payload, de.service, {}
        try:
            ddoc = json.loads(payload)
        except Exception:
            return 502, json.dumps(
                {"message": "unparseable decode response"}).encode(), \
                de.service, {}
        merged = dict(ddoc)
        merged["tokens"] = tok0 + list(ddoc.get("tokens") or [])
        merged["prompt_len"] = len(prompt)
        try:
            merged["ttft_ms"] = round(float(pdoc["ttft_ms"]), 3)
        except (KeyError, TypeError, ValueError):
            merged["ttft_ms"] = round(ttft_ms, 3)
        merged["disagg"] = {"prefill": pf.service, "decode": de.service,
                            "prefill_hop_ms": round(ttft_ms, 3),
                            "kv_sent": xfer["sent"],
                            "kv_skipped": xfer["skipped"]}
        return 200, json.dumps(merged).encode(), de.service, {}

    def _kv_transfer(self, pf: _BackendState, de: _BackendState,
                     prompt: List[int], timeout: float, ctx
                     ) -> Tuple[int, int]:
        """Delta-only KV handoff keyed by the chained block hashes: probe
        the decode replica for resident prefix blocks, export only the
        missing tail from the prefill replica, import it into the decode
        pool.  Returns (sent, skipped) full-block counts."""
        probe = json.dumps({"prompt_tokens": list(prompt)}).encode()
        code, payload, _ = self._request(de.url, "/v1/kv/resident", probe,
                                         timeout, trace_ctx=ctx)
        resident = 0
        if code == 200:
            try:
                resident = int(json.loads(payload).get(
                    "resident_blocks", 0))
            except Exception:
                resident = 0
        total = len(prompt) // self.config.block_size
        if resident >= total:
            return 0, resident
        code, payload, _ = self._request(
            pf.url, "/v1/kv/export",
            json.dumps({"prompt_tokens": list(prompt),
                        "skip_blocks": resident,
                        "max_blocks": self.config.kv_max_blocks}).encode(),
            timeout, trace_ctx=ctx)
        if code != 200:
            raise RuntimeError(f"export failed: http {code}")
        blocks = json.loads(payload).get("blocks") or []
        if not blocks:
            return 0, resident
        code, payload, _ = self._request(
            de.url, "/v1/kv/import",
            json.dumps({"prompt_tokens": list(prompt),
                        "blocks": blocks}).encode(),
            timeout, trace_ctx=ctx)
        if code != 200:
            raise RuntimeError(f"import failed: http {code}")
        rdoc = json.loads(payload)
        return int(rdoc.get("imported", 0)), int(rdoc.get(
            "skipped", resident))

    def _service_of(self, url: str) -> str:
        with self._lock:
            for st in self._states.values():
                if st.url == url:
                    return st.service
        return "none"

    def _request(self, base_url: str, path: str, body: Optional[bytes],
                 timeout: float, trace_ctx=None, method: Optional[str] = None
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        headers = {"Content-Type": "application/json"}
        if trace_ctx is not None:
            headers["traceparent"] = trace_ctx.to_traceparent()
        req = urllib.request.Request(
            base_url + path, data=body, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers or {})

    def _observe_backend(self, s: _BackendState,
                         headers: Dict[str, str]) -> None:
        """Continuous-batching feedback: fold the engine's self-reported
        queue depth / KV occupancy (serve/server.py headers) into the
        routing state."""
        def _int(name: str, default: int) -> int:
            try:
                return int(headers.get(name, default))
            except (TypeError, ValueError):
                return default
        with self._lock:
            s.queue_depth = _int("X-TPU-Queue-Depth", s.queue_depth)
            s.kv_free_blocks = _int("X-TPU-KV-Free-Blocks", s.kv_free_blocks)
            s.kv_total_blocks = _int("X-TPU-KV-Total-Blocks",
                                     s.kv_total_blocks)
            s.host_free_blocks = _int("X-TPU-KV-Host-Free-Blocks",
                                      s.host_free_blocks)
            s.host_total_blocks = _int("X-TPU-KV-Host-Total-Blocks",
                                       s.host_total_blocks)
            adv = _int("X-TPU-KV-Advert-Seq", -1)
            stale = adv >= 0 and self._fleet.needs_sync(s.service, adv)
        if stale:
            self._sync_advert(s)

    def _sync_advert(self, s: _BackendState) -> None:
        """Pull the backend's residency-advert delta and fold it into
        the fleet index; evicted hashes are also UNLEARNED from the
        routing shadow, so a stale index entry can neither attract
        affinity traffic nor direct a fleet fetch at a scrubbed block."""
        since = self._fleet.seq(s.service)
        try:
            code, payload, _ = self._request(
                s.url, f"/v1/kv/advert?since={since}", None, 5.0,
                method="GET")
        except (urllib.error.URLError, ConnectionError, OSError):
            return
        if code != 200:
            return
        try:
            doc = json.loads(payload)
            dels = [int(h) for h in doc.get("del", [])]
        except Exception:
            return
        with self._lock:
            self._fleet.apply(s.service, doc)
            unlearned = s.index.discard(dels) if dels else 0
        if unlearned and self.metrics is not None:
            self.metrics.inc("tpu_kv_index_invalidations_total",
                             {"backend": s.service}, unlearned)

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def backend_stats(self) -> List[dict]:
        """Per-backend routing state (served at GET /backends)."""
        with self._lock:
            return [summarize_backend(
                s.service, s.url, s.weight, s.inflight, s.queue_depth,
                s.kv_free_blocks, s.kv_total_blocks, len(s.index), s.picks,
                tier=s.tier, host_free_blocks=s.host_free_blocks,
                host_total_blocks=s.host_total_blocks)
                for s in self._states.values()]

    def total_queue_depth(self) -> int:
        """Fleet load signal (in-flight + backend-reported queues) — the
        queue-depth input of the SLO autoscaler (controlplane/slo.py)."""
        with self._lock:
            return sum(s.inflight + s.queue_depth
                       for s in self._states.values())

    def tier_queue_depth(self, tier: str) -> int:
        """Per-tier load signal — the queue-depth input of the per-tier
        SLO signals (controlplane/slo.py, one ServeSloSignal per worker
        group in a disaggregated fleet)."""
        with self._lock:
            return sum(s.inflight + s.queue_depth
                       for s in self._states.values() if s.tier == tier)

    def kv_tier_headroom(self) -> Dict[str, float]:
        """Fleet-wide free-block fraction per KV tier (device pool and
        host-DRAM tier), from the occupancy headers live backends last
        reported — the capacity input of the SLO autoscaler's KV
        headroom gate (controlplane/slo.py)."""
        with self._lock:
            live = [s for s in self._states.values() if s.weight > 0]
            out = {}
            for name, free_attr, total_attr in (
                    ("device", "kv_free_blocks", "kv_total_blocks"),
                    ("host", "host_free_blocks", "host_total_blocks")):
                free = sum(getattr(s, free_attr) for s in live)
                total = sum(getattr(s, total_attr) for s in live)
                out[name] = round(free / total, 4) if total else 1.0
            return out

    def session_stats(self) -> Dict[str, object]:
        """Session table + fleet residency snapshot (GET /sessions)."""
        with self._lock:
            return {**self._sessions.stats(),
                    "fleet_index_blocks": self._fleet.size(),
                    "fleet_backends": self._fleet.stats()}

    # -- HTTP --------------------------------------------------------------

    def make_server(self, host="0.0.0.0", port=C.PORT_SERVE):
        gw = self

        class Handler(JsonHandler):
            def do_GET(self):
                if self.path == "/healthz":
                    return self._send(200, {"status": "ok"})
                if self.path == "/stats":
                    return self._send(200, gw.stats())
                if self.path == "/backends":
                    return self._send(200, {"backends": gw.backend_stats()})
                if self.path == "/sessions":
                    return self._send(200, gw.session_stats())
                if self.path == "/metrics" and gw.metrics is not None:
                    return self._send_text(200, gw.metrics.render(),
                                           "text/plain; version=0.0.4")
                return self._send(404, {"message": "unknown path"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b"{}"
                code, payload, headers = gw.forward_ex(self.path, body)
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        return ThreadingHTTPServer((host, port), Handler)

    def serve_background_http(self, host="127.0.0.1", port=0):
        return serve_background(self.make_server(host, port), "serve-gateway")
