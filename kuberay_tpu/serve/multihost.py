"""Multi-host tensor-parallel serving: lockstep SPMD across a slice.

A TpuService slice has one serving process per host, all joined into one
``jax.distributed`` group (the operator injects TPU_WORKER_ID /
TPU_WORKER_HOSTNAMES — builders/pod.py; same contract the training
launcher consumes).  Every jitted engine step is a global SPMD program
over the slice-wide mesh, so **all processes must launch the same
programs with the same operands in the same order**.

Protocol (the JetStream/MaxText-style driver, first-party here):

- host 0 runs the HTTP frontend + the real scheduling loop
  (``MultihostServeEngine``); before every device call it broadcasts a
  fixed-shape *step plan* (op code + operands) via
  ``multihost_utils.broadcast_one_to_all``;
- every other host runs ``follower_loop``: receive plan → dispatch the
  identical jitted call.  Followers hold their own params/cache shards
  and no request state — scheduling lives only on host 0.

The plan is a pytree of fixed-shape arrays (broadcast requires identical
shapes on every process), sized by the engine's max_len/max_slots/γ at
construction.  The RNG subkey rides in the plan, so sampling slots stay
bit-identical across hosts without replaying host 0's key-split sequence.

Degenerate case: with one process the broadcast is the identity, so the
same code path serves single-host multi-chip TP unchanged.

Reference parity: vLLM's multi-host TPU serving runs as a Ray placement
group wired by the reference's RayService
(``config/samples/vllm/ray-service.vllm-tpu-v6e-singlehost.yaml``); here
the protocol is native to the framework.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from kuberay_tpu.serve.engine import ServeEngine

OP_STOP, OP_PREFILL, OP_DECODE, OP_VERIFY = 0, 1, 2, 3


def _zero_plan(max_len: int, max_slots: int, gamma: int,
               max_blocks: int = 0) -> Dict[str, Any]:
    plan = {
        "op": np.int32(0),
        # slot, real_len, bucket, start_pos
        "scalars": np.zeros(4, np.int32),
        # [temperature, top_p, top_k] for prefill's target slot.
        "temp": np.zeros(3, np.float32),
        "tokens": np.zeros(max_len, np.int32),
        "last": np.zeros(max_slots, np.int32),
        "lens": np.zeros(max_slots, np.int32),
        "temps": np.zeros((max_slots, 3), np.float32),
        "mask": np.zeros(max_slots, np.float32),
        "vtoks": np.zeros((max_slots, gamma + 1), np.int32),
        "ntok": np.zeros(max_slots, np.int32),
        "key": np.zeros(2, np.uint32),
    }
    if max_blocks:
        # Paged engines: host 0 owns the allocator; followers receive
        # the block tables with every plan.
        plan["tables"] = np.zeros((max_slots, max_blocks), np.int32)
    return plan


def _plan_shape(engine: ServeEngine) -> Dict[str, Any]:
    return _zero_plan(engine.max_len, engine.max_slots, engine.speculative,
                     getattr(engine, "max_blocks", 0))


def _broadcast(plan, is_source: bool):
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(plan, is_source=is_source)


class MultihostServeEngine(ServeEngine):
    """Host-0 engine: broadcasts a step plan before every device call.

    Construct with the slice-wide mesh (``serve/sharding.serve_mesh`` over
    all global devices).  Call :meth:`stop` when shutting down so
    followers exit their loop.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._plan0 = _plan_shape(self)
        self.monitor = None          # optional GroupMonitor (host 0)
        self.group_failed = False    # set by the frontend on degradation
        self._compiled_ops: set = set()   # (op, shape-key)s already run

    def attach_monitor(self, monitor) -> None:
        """Step begin/end watchdog hooks (serve/group_health.py): a dead
        follower leaves every subsequent collective hung; the monitor's
        watchdog turns that hang into a detected degradation."""
        self.monitor = monitor

    def _send(self, **updates) -> None:
        plan = dict(self._plan0)
        plan.update(updates)
        if "tables" in plan:
            plan["tables"] = np.asarray(self.tables, np.int32)
        _broadcast(plan, is_source=True)

    def stop(self) -> None:
        if jax.process_count() > 1 and self._group_alive():
            self._send(op=np.int32(OP_STOP))

    def _group_alive(self) -> bool:
        """STOP-broadcast guard: once degraded — via the watchdog OR a
        collective raising on the scheduling thread — broadcasting would
        hang/raise in the same dead group."""
        if self.group_failed:
            return False
        return self.monitor is None or self.monitor.degraded is None

    def _watched(self, op_key, send_fn, device_fn):
        """Run broadcast + device call under the step watchdog.  The
        window opens BEFORE the plan broadcast — a follower wedged
        mid-collective (heartbeats still beating) hangs host 0 inside
        the broadcast itself, and an unwatched broadcast would never
        degrade.  First occurrence of a program shape gets the compile
        budget (XLA compilation can dwarf a step)."""
        if self.monitor is not None:
            self.monitor.step_begin(
                compiling=op_key not in self._compiled_ops)
        try:
            send_fn()
            out = device_fn()
            # The jitted call returns ASYNC values; block so the watchdog
            # measures the actual collective, not dispatch latency.
            jax.block_until_ready(out)
            self._compiled_ops.add(op_key)
            return out
        finally:
            if self.monitor is not None:
                self.monitor.step_end()

    def _prefill_device(self, padded, slot, real_len, sub, temperature,
                        bucket, start_pos=0):
        def send():
            if jax.process_count() > 1:
                tokens = np.zeros(self.max_len, np.int32)
                tokens[:len(padded)] = padded
                self._send(
                    op=np.int32(OP_PREFILL),
                    scalars=np.array([slot, real_len, bucket, start_pos],
                                     np.int32),
                    temp=np.asarray(temperature, np.float32),
                    tokens=tokens,
                    key=np.asarray(sub, np.uint32))
        return self._watched(
            ("prefill", bucket, self._filters_on(temperature)), send,
            lambda: super(MultihostServeEngine, self)._prefill_device(
                padded, slot, real_len, sub, temperature, bucket,
                start_pos))

    def _decode_call(self, last, temps, mask, sub):
        def send():
            if jax.process_count() > 1:
                self._send(
                    op=np.int32(OP_DECODE),
                    last=np.asarray(last, np.int32),
                    lens=np.asarray(self.lens, np.int32),
                    temps=np.asarray(temps, np.float32),
                    mask=np.asarray(mask, np.float32),
                    key=np.asarray(sub, np.uint32))
        return self._watched(
            ("decode", self._filters_on(temps)), send,
            lambda: super(MultihostServeEngine, self)._decode_call(
                last, temps, mask, sub))

    def _verify_device(self, toks, ntok, sub, temps, mask):
        def send():
            if jax.process_count() > 1:
                self._send(
                    op=np.int32(OP_VERIFY),
                    vtoks=np.asarray(toks, np.int32),
                    ntok=np.asarray(ntok, np.int32),
                    lens=np.asarray(self.lens, np.int32),
                    temps=np.asarray(temps, np.float32),
                    mask=np.asarray(mask, np.float32),
                    key=np.asarray(sub, np.uint32))
        return self._watched(
            ("verify", self._filters_on(temps)), send,
            lambda: super(MultihostServeEngine, self)._verify_device(
                toks, ntok, sub, temps, mask))


def follower_loop(engine: ServeEngine) -> int:
    """Run on every non-zero process: replay host 0's device calls.

    ``engine`` must be constructed with the SAME ctor arguments as host
    0's ``MultihostServeEngine`` (same params init / checkpoint, same
    mesh) so the compiled programs and shardings match.  Returns the
    number of device calls replayed.
    """
    plan0 = _plan_shape(engine)
    steps = 0
    while True:
        plan = _broadcast(plan0, is_source=False)
        op = int(plan["op"])
        if op == OP_STOP:
            return steps
        steps += 1
        if "tables" in plan:
            engine.tables[:] = np.asarray(plan["tables"])
        # Engines use legacy uint32[2] PRNG keys — the raw array IS the key.
        key = jnp.asarray(plan["key"], jnp.uint32)
        if op == OP_PREFILL:
            slot, real_len, bucket, start_pos = (int(x)
                                                 for x in plan["scalars"])
            padded = np.asarray(plan["tokens"][:bucket])
            engine._prefill_device(padded, slot, real_len, key,
                                   np.asarray(plan["temp"]), bucket,
                                   start_pos)
        elif op == OP_DECODE:
            engine.lens[:] = np.asarray(plan["lens"])
            engine._decode_call(np.asarray(plan["last"]),
                                np.asarray(plan["temps"]),
                                np.asarray(plan["mask"]), key)
        elif op == OP_VERIFY:
            engine.lens[:] = np.asarray(plan["lens"])
            engine._verify_device(np.asarray(plan["vtoks"]),
                                  np.asarray(plan["ntok"]), key,
                                  np.asarray(plan["temps"]),
                                  np.asarray(plan["mask"]))
        else:  # pragma: no cover - protocol error
            raise RuntimeError(f"unknown serve op {op}")


from kuberay_tpu.serve.paged_engine import PagedServeEngine  # noqa: E402


class MultihostPagedServeEngine(MultihostServeEngine, PagedServeEngine):
    """Host-0 paged engine: MultihostServeEngine's broadcast wrappers
    compose over PagedServeEngine through the shared device funnels
    (_prefill_device/_decode_call, MRO: broadcast first, paged kernel
    second); block tables ride every plan, so followers replay against
    host 0's allocator decisions without running an allocator at all."""
