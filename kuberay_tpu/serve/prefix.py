"""Prefix-affinity primitives shared by the paged KV cache and the
serve gateway.

The gateway's routing problem is the replica-level mirror of the
BlockAllocator's block-level one: a request whose prompt shares a
block-aligned prefix with earlier traffic should land where those KV
blocks already live.  Both sides therefore hash prompts the SAME way —
a chained hash over full ``block_size`` token blocks
(:func:`block_hashes`, the vLLM/SGLang prefix-cache key) — so the
gateway's per-backend index is a faithful shadow of what each replica's
:class:`~kuberay_tpu.serve.paged_kv.BlockAllocator` can actually serve
from cache.

This module is deliberately jax-free: the gateway imports it without
pulling the device stack.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Sequence


def chain_hash(parent: int, block_tokens: Sequence[int]) -> int:
    """One link of the prefix hash chain.  Python's tuple-of-int hash is
    deterministic (PYTHONHASHSEED only salts str/bytes), so two processes
    hashing the same prompt agree."""
    return hash((parent, tuple(block_tokens)))


def block_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
    """Hash chain over the FULL blocks of a token sequence (the partial
    tail block is never cacheable and never hashed)."""
    out: List[int] = []
    parent = 0
    for i in range(0, len(tokens) - len(tokens) % block_size, block_size):
        parent = chain_hash(parent, tokens[i:i + block_size])
        out.append(parent)
    return out


class PrefixIndex:
    """Bounded LRU set of block hashes one backend plausibly holds.

    The gateway inserts a request's prompt hashes after the backend
    serves it (that replica's allocator has now prefilled + registered
    those blocks) and probes with :meth:`hit_depth` when routing.  The
    LRU bound mirrors the replica-side reality that refcount-0 cached
    blocks are cannibalized least-recently-used first — an index entry
    older than ``capacity`` insertions is exactly the block the
    allocator would have evicted.
    """

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._hashes: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._hashes)

    def insert(self, hashes: Sequence[int]) -> None:
        for h in hashes:
            if h in self._hashes:
                self._hashes.move_to_end(h)
            else:
                self._hashes[h] = None
        while len(self._hashes) > self.capacity:
            self._hashes.popitem(last=False)

    def hit_depth(self, hashes: Sequence[int]) -> int:
        """Longest PREFIX of ``hashes`` present, in blocks.  Prefix, not
        membership: a replica serves ``tokens[:k*bs]`` from cache only
        when every block before ``k`` is cached too (match_prefix walks
        the chain and stops at the first miss).  Probing touches the LRU
        order — a hot prefix being routed to stays resident."""
        depth = 0
        for h in hashes:
            if h not in self._hashes:
                break
            self._hashes.move_to_end(h)
            depth += 1
        return depth

    def discard(self, hashes: Sequence[int]) -> int:
        """Unlearn: drop hashes the replica adverted as evicted, so a
        stale shadow entry cannot keep attracting traffic (or direct a
        fleet fetch) toward a block the allocator scrubbed.  Returns how
        many entries actually left."""
        n = 0
        for h in hashes:
            if h in self._hashes:
                del self._hashes[h]
                n += 1
        return n


class HotPrompts:
    """Bounded LRU of block-aligned prompt prefixes with hit counts.

    The gateway records every successfully-served prompt's leading
    blocks here; before an upgrade's first weight step it replays the
    :meth:`hottest` prefixes against the cold green fleet so green
    replicas start with the same hot KV blocks the blue fleet earned
    (docs/upgrades.md pre-warm).  Prefixes are capped at ``max_blocks``
    blocks — the shared preamble is what repeats across requests; the
    unique tail would just pollute the replay budget.
    """

    def __init__(self, capacity: int = 512, max_blocks: int = 4):
        self.capacity = capacity
        self.max_blocks = max_blocks
        self._counts: "collections.OrderedDict[tuple, int]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._counts)

    def record(self, tokens: Sequence[int], block_size: int) -> None:
        n = min(len(tokens) - len(tokens) % block_size,
                self.max_blocks * block_size)
        if n <= 0:
            return
        key = tuple(tokens[:n])
        self._counts[key] = self._counts.pop(key, 0) + 1
        while len(self._counts) > self.capacity:
            self._counts.popitem(last=False)

    def hottest(self, n: int) -> List[List[int]]:
        """Top-``n`` prefixes by hit count; ties break most-recently-used
        first (stable sort over reversed LRU order), so the replay order
        is deterministic for a deterministic request stream."""
        ranked = sorted(reversed(self._counts.items()),
                        key=lambda kv: -kv[1])
        return [list(k) for k, _ in ranked[:max(0, n)]]


def affinity_score(hit_depth: int, queue_depth: float,
                   alpha: float, beta: float) -> float:
    """The routing score: ``α·prefix-hit-depth − β·queue-depth``.

    α prices a cached block (prefill compute saved); β prices a queued/
    in-flight request ahead of this one (HOL latency).  With α/β ≈ the
    ratio of per-block prefill cost to per-request service time, a deep
    prefix hit wins until the affine replica's queue eats the saving —
    which is exactly when spilling to a cold replica is correct
    (SGLang's cache-aware load balancing tradeoff).
    """
    return alpha * hit_depth - beta * queue_depth


class BackendSnapshot(dict):
    """Plain-dict view of one backend's routing state (``/backends``)."""


def summarize_backend(service: str, url: str, weight: int, inflight: int,
                      queue_depth: int, kv_free_blocks: int,
                      kv_total_blocks: int, index_size: int,
                      picks: int, tier: str = "mixed",
                      host_free_blocks: int = 0,
                      host_total_blocks: int = 0) -> BackendSnapshot:
    occ = 0.0
    if kv_total_blocks > 0:
        occ = round(1.0 - kv_free_blocks / kv_total_blocks, 4)
    host_occ = 0.0
    if host_total_blocks > 0:
        host_occ = round(1.0 - host_free_blocks / host_total_blocks, 4)
    return BackendSnapshot(
        service=service, url=url, weight=weight, tier=tier,
        inflight=inflight, queue_depth=queue_depth, kv_occupancy=occ,
        kv_host_occupancy=host_occ,
        prefix_index_size=index_size, picks=picks)


def decode_score(hit_depth: int, queue_depth: float, kv_free_blocks: int,
                 kv_total_blocks: int, alpha: float, beta: float,
                 kv_weight: float) -> float:
    """Decode-hop routing score for disaggregated serving: KV locality
    (blocks this replica would NOT need shipped) priced like a prefix
    hit, load priced like the prefill hop, plus a free-KV-fraction bonus
    — a decode replica about to exhaust its pool preempts mid-decode,
    which costs far more than landing on a slightly colder peer."""
    free_frac = kv_free_blocks / kv_total_blocks if kv_total_blocks else 0.0
    return alpha * hit_depth - beta * queue_depth + kv_weight * free_frac


def aggregate_queue_depth(states: Dict[str, "object"]) -> int:
    """Fleet-wide load signal for the SLO autoscaler: requests in flight
    through the gateway plus backend-reported engine queue depths."""
    total = 0
    for s in states.values():
        total += getattr(s, "inflight", 0) + getattr(s, "queue_depth", 0)
    return total
