"""Paged KV cache with prefix caching for the serve engine.

The vLLM PagedAttention idea (block-table indirection + hash-based prefix
reuse) rebuilt for TPU/XLA semantics rather than as a CUDA kernel port:

- ONE flat static-shape physical pool per layer
  (``[L, num_blocks*block_size, Hkv, D]``) so every step compiles once;
  a request's logical cache is a row of physical block ids (its block
  table), padded to a static ``max_blocks`` width.
- Reads GATHER the request's live blocks into the same contiguous
  ``[B, max, Hkv, D]`` view the non-paged path uses, so the attention
  math (and the Pallas decode kernel in ops/decode_attention.py) is
  shared verbatim.  Writes SCATTER into the flat pool with
  ``mode="drop"`` — masked rows aim at an out-of-range index and write
  nothing, the paged analogue of kv_cache.py's write_mask.
- Prefix caching is block-aligned and read-only: a shared block is never
  a write target (writes always start at the first private, non-cached
  position), so no copy-on-write machinery is needed.
- The allocator is host-side pure Python (refcounts, free list, LRU
  reuse of refcount-0 cached blocks) — bookkeeping stays off-device,
  every FLOP stays under jit, matching the engine's design.

Capability analogue: the reference serves models via Ray Serve + vLLM
(docs reference `ray-operator` RayService samples); the paged cache is
what makes many concurrent long-prompt requests fit in HBM.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from kuberay_tpu.serve.prefix import block_hashes as _prefix_block_hashes
from kuberay_tpu.serve.prefix import chain_hash as _chain_hash


# ---------------------------------------------------------------------------
# Host-side block allocator + prefix cache
# ---------------------------------------------------------------------------

class BlockAllocator:
    """Physical-block bookkeeping: refcounted allocation plus a
    prefix-hash table enabling cross-request block sharing.

    Blocks with refcount 0 that still hold a registered prefix stay in
    the hash table and are reused LRU-last — a free block is only
    scrubbed (hash entry dropped) when allocation demands it.
    """

    def __init__(self, num_blocks: int, block_size: int, *,
                 on_register=None, on_evict=None):
        self.num_blocks = num_blocks
        self.block_size = block_size
        # Device-tier membership hooks (hash registered / hash scrubbed):
        # the tier store mirrors the pool through these so fleet adverts
        # cover the device tier without the store reaching into the pool.
        self.on_register = on_register
        self.on_evict = on_evict
        self.refcount = [0] * num_blocks
        # Free blocks split by cache status so allocate() is O(1): plain
        # deque for uncached, insertion-ordered dict (= LRU) for
        # refcount-0 blocks still holding a registered prefix.
        self._free_uncached: collections.deque = collections.deque(
            range(num_blocks))
        self._free_cached: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        # prefix hash -> (block id, exact block tokens).  The tokens are
        # compared on every match: a 64-bit chained-hash collision must
        # degrade to a cache miss, never silently serve another prompt's
        # KV content (the failure class vLLM's prefix cache verifies
        # against).  block id -> hash is kept for eviction.
        self._hash_to_block: Dict[int, tuple] = {}
        self._block_to_hash: Dict[int, int] = {}
        # LRU order among refcount-0 cached blocks (ids also in _free).
        self.prefix_hits = 0          # tokens served from cache
        self.prefix_queries = 0       # tokens eligible for caching

    # -- hashing ----------------------------------------------------------

    def _chain(self, parent: int, block_tokens: Sequence[int]) -> int:
        return _chain_hash(parent, block_tokens)

    def block_hashes(self, tokens: Sequence[int]) -> List[int]:
        """Hash chain over the FULL blocks of a token sequence — the
        SAME chain the gateway's per-backend PrefixIndex keys on
        (serve/prefix.py), so gateway affinity predictions and replica
        cache hits agree."""
        return _prefix_block_hashes(tokens, self.block_size)

    # -- allocation -------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free_uncached) + len(self._free_cached)

    def allocate(self) -> Optional[int]:
        """Pop a free block, preferring ones with no cached prefix;
        cannibalizing a cached block evicts the LEAST-recently-freed one
        and scrubs its hash entry.  O(1)."""
        if self._free_uncached:
            bid = self._free_uncached.popleft()
        elif self._free_cached:
            bid, _ = self._free_cached.popitem(last=False)   # LRU evict
            h = self._block_to_hash.pop(bid)
            self._hash_to_block.pop(h, None)
            if self.on_evict is not None:
                self.on_evict(h)
        else:
            return None
        self.refcount[bid] = 1
        return bid

    def free(self, bid: int) -> None:
        self.refcount[bid] -= 1
        assert self.refcount[bid] >= 0, f"double free of block {bid}"
        if self.refcount[bid] == 0:
            if bid in self._block_to_hash:         # cached hash survives
                self._free_cached[bid] = None      # MRU end
            else:
                self._free_uncached.append(bid)

    # -- prefix cache -----------------------------------------------------

    def match_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Longest cached block-aligned prefix; increfs every returned
        block (caller owns them and must ``free`` each later)."""
        ids: List[int] = []
        bs = self.block_size
        for i, h in enumerate(self.block_hashes(tokens)):
            entry = self._hash_to_block.get(h)
            if entry is None:
                break
            bid, blk_tokens = entry
            if blk_tokens != tuple(tokens[i * bs:(i + 1) * bs]):
                break                              # hash collision: miss
            if self.refcount[bid] == 0:
                del self._free_cached[bid]         # resurrect cached block
            self.refcount[bid] += 1
            ids.append(bid)
        # Hit/query counters are the CALLER's to bump (count_prefix_stats)
        # — an admission retried while waiting for memory would otherwise
        # re-count the same tokens every engine step.
        return ids

    def count_prefix_stats(self, n_prompt_tokens: int,
                           n_cached_blocks: int) -> None:
        self.prefix_queries += (n_prompt_tokens -
                                n_prompt_tokens % self.block_size)
        self.prefix_hits += n_cached_blocks * self.block_size

    def resident_prefix_blocks(self, tokens: Sequence[int]) -> int:
        """Longest cached block-aligned prefix, in blocks, WITHOUT
        increfing (a pure query — the KV-transfer delta probe).  Token
        verification matches :meth:`match_prefix`: a hash collision
        reads as non-resident, so the peer ships the real content."""
        bs = self.block_size
        depth = 0
        for i, h in enumerate(self.block_hashes(tokens)):
            entry = self._hash_to_block.get(h)
            if entry is None or \
                    entry[1] != tuple(tokens[i * bs:(i + 1) * bs]):
                break
            depth += 1
        return depth

    def lookup_block(self, h: int) -> Optional[tuple]:
        """(block id, exact block tokens) registered under a prefix
        hash, or None — the export side's content-addressable read."""
        return self._hash_to_block.get(h)

    def hash_of(self, bid: int) -> Optional[int]:
        """Prefix hash published for a block id, or None (private/tail
        blocks never enter the hash table)."""
        return self._block_to_hash.get(bid)

    def registered_hashes(self) -> List[int]:
        """All prefix hashes currently resident in the pool — the
        device-tier listing an advert snapshot starts from."""
        return list(self._hash_to_block)

    def import_block(self, h: int, block_tokens: Sequence[int]
                     ) -> Optional[int]:
        """Adopt one externally produced prefix block (KV transfer from
        a prefill-tier peer): allocate a physical block and publish it
        in the hash table.  The block comes back refcount-1 — the caller
        writes the shipped KV content into the pool, then ``free``s it,
        after which it is refcount-0 cached: reusable by the next
        :meth:`match_prefix` and LRU-evictable exactly like a locally
        prefilled block.  Returns None when the hash is already resident
        or the pool is exhausted (the caller skips the block)."""
        if h in self._hash_to_block:
            return None                    # already resident; skip
        bid = self.allocate()
        if bid is None:
            return None
        self._hash_to_block[h] = (bid, tuple(block_tokens))
        self._block_to_hash[bid] = h
        if self.on_register is not None:
            self.on_register(h)
        return bid

    def register_prefix(self, tokens: Sequence[int],
                        block_ids: Sequence[int]) -> None:
        """Publish a request's full blocks into the prefix cache (after
        its prefill completed, so the pool contents are valid)."""
        bs = self.block_size
        for i, (h, bid) in enumerate(zip(self.block_hashes(tokens),
                                         block_ids)):
            if h in self._hash_to_block:
                continue               # first writer wins; same content
            if bid in self._block_to_hash:
                continue               # block already published
            self._hash_to_block[h] = (bid, tuple(tokens[i * bs:(i + 1) * bs]))
            self._block_to_hash[bid] = h
            if self.on_register is not None:
                self.on_register(h)


# ---------------------------------------------------------------------------
# Device-side paged forward
# ---------------------------------------------------------------------------

def init_paged_cache(cfg, num_blocks: int, block_size: int,
                     quant: str = "none") -> Dict[str, jax.Array]:
    """Flat physical pool, head-major: [L, Hkv, num_blocks*block_size, D].

    Head-major so one (head, page) pair is a contiguous
    ``block_size * head_dim`` run — the paged Pallas kernel's indirect
    page fetch is then a single dense DMA (ops/paged_attention.py).

    ``quant="int8"`` stores the pool as int8 with one f32 absmax scale
    per (head, position) vector: the pool at rest is ~half the bf16
    bytes, which is the knob that matters — more blocks per HBM GB means
    more concurrent requests (vLLM kv_cache_dtype=int8 role).
    """
    shape = (cfg.n_layers, cfg.n_kv_heads,
             num_blocks * block_size, cfg.head_dim)
    if quant == "int8":
        sshape = shape[:-1]
        leaf = lambda: {"q": jnp.zeros(shape, jnp.int8),     # noqa: E731
                        "s": jnp.zeros(sshape, jnp.float32)}
        return {"k": leaf(), "v": leaf()}
    if quant != "none":
        raise ValueError(f"unknown kv quant {quant!r}")
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _physical_positions(block_tables, positions, block_size):
    """[B, T] logical positions -> [B, T] flat pool indices via the
    request's block table."""
    blk = positions // block_size                               # [B, T]
    phys_blk = jnp.take_along_axis(block_tables, blk, axis=1)   # [B, T]
    return phys_blk * block_size + positions % block_size


def gather_scales(spool, tables, block_size: int):
    """[Hkv, P] scale pool + [B, max_blocks] tables -> [B, Hkv, K]
    per-request view in the dense quant kernels' lane-major layout
    (same flat_indices as gather_view — scales must resolve through the
    identical logical->physical map as their values)."""
    from kuberay_tpu.ops.paged_attention import flat_indices
    flat = flat_indices(tables, block_size)
    return jnp.take(spool, flat, axis=1).transpose(1, 0, 2)   # [B, Hkv, K]


def make_paged_quant_forward(block_size: int, base_forward=None,
                             decode_impl: str = "auto", mesh=None):
    """int8 paged pool: quantize-on-write scatter + per-request gathered
    int8 views consumed by the DENSE quant attention (decode kernel +
    _cached_attention_quant_multi).

    Deliberate design: the gather materializes an int8 logical view per
    step — half the bytes of the round-1 bf16 gather — instead of a
    block-native quant Pallas kernel.  The quant pool's win is HBM
    CAPACITY (twice the blocks per GB -> more concurrent requests); a
    table-native int8 kernel is future work gated on hardware validation
    (round 2's lesson: interpret-mode passes do not validate lane
    tiling).
    """
    from kuberay_tpu.serve.kv_cache import (
        _cached_attention_quant_multi,
        forward_with_cache,
        quantize_kv,
    )
    from kuberay_tpu.ops.decode_attention import decode_attention_quant
    from kuberay_tpu.ops.paged_attention import gather_view
    base = base_forward or forward_with_cache

    def fwd(cfg, params, tokens, cache, block_tables, start,
            write_mask=None, token_mask=None):
        B, T = tokens.shape
        P = cache["k"]["q"].shape[2]
        positions = start[:, None] + jnp.arange(T)[None, :]
        phys = _physical_positions(block_tables, positions, block_size)
        if write_mask is None:
            write_mask = jnp.ones((B,), jnp.float32)
        wgate = token_mask if token_mask is not None \
            else jnp.broadcast_to(write_mask[:, None], (B, T))
        wphys = jnp.where(wgate > 0, phys, P).reshape(-1)

        def kv_update(ck, cv, kk, vv):        # ck/cv: {"q","s"} per layer
            H, D = kk.shape[2], kk.shape[3]
            kq, ks = quantize_kv(kk)          # [B,T,H,D] i8, [B,T,H,1]
            vq, vs = quantize_kv(vv)

            def scat(pool, rows):             # pool [H,P,...] rows [B,T,H,..]
                r = rows.reshape(B * T, H, *rows.shape[3:]).swapaxes(0, 1)
                return pool.at[:, wphys].set(r.astype(pool.dtype),
                                             mode="drop")
            nk = {"q": scat(ck["q"], kq), "s": scat(ck["s"], ks[..., 0])}
            nv = {"q": scat(cv["q"], vq), "s": scat(cv["s"], vs[..., 0])}
            if T == 1:
                return nk, nv, nk, nv
            view = lambda p: {                               # noqa: E731
                "q": gather_view(p["q"], block_tables, block_size),
                "s": gather_scales(p["s"], block_tables, block_size)}
            return nk, nv, view(nk), view(nv)

        if T == 1:
            def attention(q, pk, pv, lens, q_positions):
                kq = gather_view(pk["q"], block_tables, block_size)
                ks = gather_scales(pk["s"], block_tables, block_size)
                vq = gather_view(pv["q"], block_tables, block_size)
                vs = gather_scales(pv["s"], block_tables, block_size)

                def local(q_, kq_, ks_, vq_, vs_, lens_):
                    return decode_attention_quant(
                        q_[:, 0], kq_, ks_, vq_, vs_, lens_,
                        impl=decode_impl)[:, None]

                if mesh is None:
                    return local(q, kq, ks, vq, vs, lens)
                from jax.sharding import PartitionSpec as P_
                fn = jax.shard_map(
                    local, mesh=mesh,
                    in_specs=(P_(None, None, ("tp", "tpr"), None),
                              P_(None, None, "tp", None),
                              P_(None, "tp", None),
                              P_(None, None, "tp", None),
                              P_(None, "tp", None), P_(None)),
                    out_specs=P_(None, None, ("tp", "tpr"), None),
                    check_vma=False)
                return fn(q, kq, ks, vq, vs, lens)
        else:
            if mesh is None:
                attention = _cached_attention_quant_multi
            else:
                from kuberay_tpu.serve.sharding import (
                    make_tp_attention_quant)
                attention = make_tp_attention_quant(
                    mesh, _cached_attention_quant_multi)

        return base(cfg, params, tokens, cache, start, write_mask,
                    token_mask=token_mask, kv_update=kv_update,
                    attention=attention)

    return fwd


def make_paged_forward(block_size: int, base_forward=None,
                       decode_impl: str = "auto", mesh=None):
    """Paged counterpart of kv_cache.forward_with_cache for a fixed
    block size (compile-time structure, like the mesh in pjit).

    The transformer layer body lives ONLY in forward_with_cache; this
    wrapper contributes a ``kv_update`` strategy that scatters new K/V
    into the flat pool, plus an ``attention`` strategy:

    - decode (T == 1): block-table-NATIVE — the raw pool and tables go
      straight to the paged Pallas kernel, which resolves logical->
      physical pages in its BlockSpec index map.  No gathered copy of
      the logical KV is ever materialized (the round-1 gather cost one
      full logical-cache copy per generated token).
    - prefill (T > 1): per-request contiguous views are gathered once
      (prefill runs once per request; the dense masked attention over
      the gathered view stays the simplest correct thing).

    ``base_forward`` selects the model family (forward_with_cache for
    Llama — the default — or forward_with_cache_mixtral for MoE);
    ``decode_impl`` forwards to paged_decode_attention (auto|pallas|
    xla|pallas_interpret).

    The returned ``fwd(cfg, params, tokens, cache, block_tables, start,
    write_mask, token_mask)`` takes ``block_tables: [B, max_blocks]`` of
    physical block ids per request (entries past the live length may be
    anything — reads are length-masked and writes past the live
    positions never happen).  The pool axis is shared by all requests,
    so write targets must be disjoint across rows — guaranteed because
    each live block belongs to exactly one writer (prefix-shared blocks
    are never written).
    """
    from kuberay_tpu.serve.kv_cache import forward_with_cache
    from kuberay_tpu.ops.paged_attention import (
        gather_view, paged_decode_attention)
    base = base_forward or forward_with_cache

    def fwd(cfg, params, tokens, cache, block_tables, start,
            write_mask=None, token_mask=None):
        B, T = tokens.shape
        P = cache["k"].shape[2]                       # pool positions
        positions = start[:, None] + jnp.arange(T)[None, :]
        phys = _physical_positions(block_tables, positions, block_size)
        if write_mask is None:
            write_mask = jnp.ones((B,), jnp.float32)
        # Masked lanes scatter out of range -> dropped (no write).  Unlike
        # the dense cache, padding writes CANNOT be tolerated here: a
        # padding position's block-table lookup aliases another request's
        # physical block, so the gate must be per-token (real tokens of
        # writable rows only), not just per-row.
        wgate = token_mask if token_mask is not None \
            else jnp.broadcast_to(write_mask[:, None], (B, T))
        wphys = jnp.where(wgate > 0, phys, P).reshape(-1)

        def kv_update(ck, cv, kk, vv):                # ck/cv: [Hkv, P, D]
            H, D = ck.shape[0], ck.shape[-1]
            # [B, T, H, D] -> [H, B*T, D] rows for the head-major scatter.
            krows = kk.reshape(B * T, H, D).swapaxes(0, 1)
            vrows = vv.reshape(B * T, H, D).swapaxes(0, 1)
            ck = ck.at[:, wphys].set(krows.astype(ck.dtype), mode="drop")
            cv = cv.at[:, wphys].set(vrows.astype(cv.dtype), mode="drop")
            if T == 1:
                return ck, cv, ck, cv     # native: attention gets the pool
            return ck, cv, gather_view(ck, block_tables, block_size), \
                gather_view(cv, block_tables, block_size)

        if T == 1:
            def local_decode(q, pk, pv, lens, tables):
                out = paged_decode_attention(
                    q[:, 0], pk, pv, lens, tables, block_size,
                    impl=decode_impl)
                return out[:, None]

            if mesh is None:
                def attention(q, pk, pv, lens, q_positions):
                    return local_decode(q, pk, pv, lens, block_tables)
            else:
                # Tensor parallel: the paged Pallas kernel is invisible
                # to the SPMD partitioner — each chip runs it on its
                # local kv-head shard of the pool, with the full block
                # table (specs live in serve/sharding.py).
                from kuberay_tpu.serve.sharding import (
                    make_tp_paged_attention)
                fn = make_tp_paged_attention(mesh, local_decode)

                def attention(q, pk, pv, lens, q_positions):
                    return fn(q, pk, pv, lens, block_tables)
        elif mesh is not None:
            # Prefill on gathered per-request views: the stock sharded
            # dense attention (views inherit the pool's kv-head split).
            from kuberay_tpu.serve.sharding import make_tp_attention
            attention = make_tp_attention(mesh)
        else:
            attention = None              # dense masked attention on views

        return base(cfg, params, tokens, cache, start, write_mask,
                    token_mask=token_mask, kv_update=kv_update,
                    attention=attention)

    return fwd
