"""Inference HTTP server: the PORT_SERVE surface a TpuService fronts.

What runs behind the serve Services the controller manages (the Ray
Serve + vLLM role).  A background engine thread drains the continuous
batcher; HTTP handlers enqueue requests and wait on per-request events:

    POST /v1/completions   {"prompt_tokens": [...], "max_tokens": N,
                            "temperature": T}  ->  {"tokens": [...], ...}
    GET  /healthz | /stats

Token-id in/out (tokenization is the client's concern here; a tokenizer
sidecar slots in front for text APIs).  On startup the server registers
its serve-app status with the coordinator so the TpuService controller's
health polling sees RUNNING (runtime/coordinator_server.py PUT
/api/serve/applications/{name}/status).
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
import time
import uuid
from http.server import ThreadingHTTPServer
from typing import Any, Dict, Optional

from kuberay_tpu.obs.trace import TraceContext
from kuberay_tpu.serve.engine import Request, Response, ServeEngine
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.httpjson import JsonHandler


class ServeFrontend:
    def __init__(self, engine: ServeEngine, max_queue: int = 256,
                 monitor=None, on_degraded=None):
        self.engine = engine
        self.max_queue = max_queue
        self.monitor = monitor               # GroupMonitor (host 0) or None
        self._on_degraded_cb = on_degraded   # e.g. coordinator DEGRADED post
        self._degraded: Optional[str] = None
        self._lock = threading.Lock()
        self._waiters: Dict[str, threading.Event] = {}
        self._results: Dict[str, Response] = {}
        # rid -> queue of token-list batches for streaming consumers.
        # Completion is signaled via the rid's waiter Event (the stream
        # generator then drains the queue and yields the final
        # Response) — no in-queue sentinel.
        self._streams: Dict[str, "queue.Queue"] = {}
        # Control calls executed BY the engine-loop thread between steps
        # (KV export/import must serialize with step(): an import racing
        # a step loses its pool write when the step publishes its own
        # new cache array).  Each entry: (fn, done event, result box).
        self._control: list = []
        self._stop = threading.Event()
        self._stats = {"requests": 0, "completed": 0, "rejected": 0,
                       "tokens_out": 0, "failed_degraded": 0}
        engine.token_callback = self._on_tokens
        if monitor is not None and hasattr(engine, "attach_monitor"):
            engine.attach_monitor(monitor)
            monitor.on_degraded = self._handle_degraded
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-engine-loop")
        self._thread.start()

    # -- degradation -------------------------------------------------------

    @property
    def degraded(self) -> Optional[str]:
        with self._lock:
            return self._degraded

    def _handle_degraded(self, reason: str) -> None:
        """One-way transition: stop admitting, fail every pending waiter
        (their collective will never complete — an immediate 503 beats a
        client-timeout hang), and surface upward.  The engine-loop
        thread may be permanently stuck inside a dead collective; that
        is expected — recovery is whole-slice replacement by the
        TpuService controller, not in-process repair (the same unit the
        cluster controller repairs, ref raycluster_controller.go:1269)."""
        with self._lock:
            if self._degraded is not None:
                return
            self._degraded = reason
            waiters = list(self._waiters.items())
            self._waiters.clear()
            self._stats["failed_degraded"] += len(waiters)
        # Inform the engine (STOP-broadcast guard) and the monitor (so
        # /stats' group view agrees) even when the signal originated
        # from an engine exception rather than the watchdog.
        if hasattr(self.engine, "group_failed"):
            self.engine.group_failed = True
        if self.monitor is not None:
            self.monitor.mark_degraded(reason)
        for _, ev in waiters:
            ev.set()                       # submit() sees no result -> None
        if self._on_degraded_cb is not None:
            try:
                self._on_degraded_cb(reason)
            except Exception:
                pass

    # -- engine loop -------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            if self.degraded is not None:
                # Parked: device calls would hang/mispair in the dead
                # group.  Queued requests are failed by _handle_degraded;
                # the pod is replaced by the controller.
                self._stop.wait(0.1)
                continue
            self._drain_control()
            if not self.engine.has_work():
                self._stop.wait(0.005)
                continue
            try:
                responses = self.engine.step()
            except Exception as e:
                # The distributed runtime may also surface a dead peer as
                # an exception from the collective (instead of a hang) —
                # same degradation, nicer failure mode.
                self._handle_degraded(f"engine step failed: {e!r}")
                continue
            for resp in responses:
                with self._lock:
                    self._stats["completed"] += 1
                    self._stats["tokens_out"] += len(resp.tokens)
                    ev = self._waiters.pop(resp.request_id, None)
                    if ev is not None:
                        # Only park results someone still waits for — a
                        # timed-out client already gave up, and an orphaned
                        # entry would leak forever.
                        self._results[resp.request_id] = resp
                if ev is not None:
                    ev.set()

    def _drain_control(self):
        """Run queued control calls on the engine-loop thread."""
        with self._lock:
            if not self._control:
                return
            batch, self._control = self._control, []
        for fn, ev, box in batch:
            try:
                box["result"] = fn(self.engine)
            except Exception as e:          # surfaced to the caller
                box["error"] = e
            ev.set()

    def call_engine(self, fn, timeout: float = 30.0):
        """Execute ``fn(engine)`` on the engine-loop thread, serialized
        with step() — the seam KV-block export/import rides (handler
        threads must never touch allocator/cache state mid-step).
        Raises TimeoutError when the loop is wedged or degraded."""
        ev = threading.Event()
        box: Dict[str, Any] = {}
        with self._lock:
            self._control.append((fn, ev, box))
        if not ev.wait(timeout):
            raise TimeoutError("engine loop did not service the call "
                               f"within {timeout:g}s")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _admit(self, rid, ev, prompt_tokens, max_tokens, temperature,
               eos_token, stream_queue=None, top_p=1.0, top_k=0,
               stop_token_ids=None, trace=None) -> bool:
        """Shared admission for blocking and streaming submits: one place
        for the degraded/backlog rejection invariants and stats."""
        with self._lock:
            if self._degraded is not None or \
                    len(self.engine.queue) >= self.max_queue:
                self._stats["rejected"] += 1
                return False
            self._stats["requests"] += 1
            self._waiters[rid] = ev
            if stream_queue is not None:
                self._streams[rid] = stream_queue
            self.engine.add_request(Request(
                rid, list(prompt_tokens), max_new_tokens=max_tokens,
                temperature=temperature, top_p=top_p, top_k=top_k,
                eos_token=eos_token, stop_token_ids=stop_token_ids,
                trace=trace))
            return True

    def submit(self, prompt_tokens, max_tokens=64, temperature=0.0,
               eos_token=None, timeout: float = 300.0, top_p: float = 1.0,
               top_k: int = 0, stop_token_ids=None,
               trace=None) -> Optional[Response]:
        rid = uuid.uuid4().hex
        ev = threading.Event()
        if not self._admit(rid, ev, prompt_tokens, max_tokens,
                           temperature, eos_token, top_p=top_p,
                           top_k=top_k, stop_token_ids=stop_token_ids,
                           trace=trace):
            return None
        if not ev.wait(timeout):
            with self._lock:
                self._waiters.pop(rid, None)
                # The loop may have parked the result in the same instant;
                # reap it or it leaks forever.
                self._results.pop(rid, None)
            return None
        with self._lock:
            # No parked result = woken by _handle_degraded, not by a
            # completion: the request died with the group.
            return self._results.pop(rid, None)

    # -- streaming ---------------------------------------------------------

    def _on_tokens(self, rid: str, tokens) -> None:
        """Engine-thread hook: push freshly emitted tokens to a stream."""
        with self._lock:
            q = self._streams.get(rid)
        if q is not None:
            q.put(list(tokens))

    def submit_stream(self, prompt_tokens, max_tokens=64, temperature=0.0,
                      eos_token=None, timeout: float = 300.0,
                      top_p: float = 1.0, top_k: int = 0,
                      stop_token_ids=None, trace=None):
        """Generator of token batches as the engine emits them, ending
        with a Response (or None on overload/degraded/timeout) — the
        vLLM-style streaming surface.  Tokens arrive per engine step:
        singles for plain decode, runs for accepted speculation."""
        rid = uuid.uuid4().hex
        ev = threading.Event()
        q: queue.Queue = queue.Queue()
        # NEVER yield under self._lock: a generator suspended at a yield
        # holds the lock across arbitrary consumer work (a slow client's
        # socket write), which would freeze the engine loop and every
        # other request.
        if not self._admit(rid, ev, prompt_tokens, max_tokens,
                           temperature, eos_token, stream_queue=q,
                           top_p=top_p, top_k=top_k,
                           stop_token_ids=stop_token_ids, trace=trace):
            yield None
            return
        deadline = time.monotonic() + timeout
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    yield None
                    return
                if ev.is_set():
                    # Finished (or degraded): drain the queue, then the
                    # final Response (popped under the lock, yielded
                    # outside it).
                    while True:
                        try:
                            yield q.get_nowait()
                        except queue.Empty:
                            break
                    with self._lock:
                        final = self._results.pop(rid, None)
                    yield final
                    return
                try:
                    yield q.get(timeout=min(0.1, remaining))
                except queue.Empty:
                    continue
        finally:
            with self._lock:
                self._streams.pop(rid, None)
                self._waiters.pop(rid, None)
                self._results.pop(rid, None)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {**self._stats,
                   "active_slots": self.engine.num_active,
                   "queued": len(self.engine.queue),
                   # Speculative acceptance counters (zeros when off).
                   **getattr(self.engine, "spec_stats", {}),
                   # Paged engines expose pool/prefix-cache counters.
                   **getattr(self.engine, "stats", {})}
            degraded = self._degraded
        if degraded is not None:
            out["degraded"] = degraded
        if self.monitor is not None:
            out["group"] = self.monitor.status()
        return out

    def drain(self, timeout: float = 60.0) -> bool:
        """Graceful shutdown step: let the engine loop finish queued +
        in-flight requests (their submit() callers get real responses)
        instead of dropping them mid-roll.  Returns True when fully
        drained, False on timeout (remaining work is abandoned) or
        immediately when degraded (stuck collective: nothing drains)."""
        if self.degraded is not None:
            return False
        deadline = time.monotonic() + timeout       # wall-clock-step safe
        while time.monotonic() < deadline:
            if not self.engine.has_work():
                return True
            time.sleep(0.05)
        return False

    def close(self, timeout: Optional[float] = 2.0):
        """Stop the engine loop.  ``timeout=None`` blocks until the
        thread is actually dead — required before a multi-host engine
        may broadcast STOP (a live loop thread could still be issuing
        collectives, and two threads' broadcasts can mispair).  A
        degraded group caps the wait: the loop thread may be pinned
        inside a dead collective forever (it is daemonic; process exit
        reaps it — and the engine's STOP broadcast is skipped anyway)."""
        self._stop.set()
        if self.degraded is not None:
            timeout = 2.0 if timeout is None else min(timeout, 2.0)
        self._thread.join(timeout=timeout)

    # -- HTTP --------------------------------------------------------------

    def make_server(self, host="0.0.0.0",
                    port=C.PORT_SERVE) -> ThreadingHTTPServer:
        frontend = self

        class Handler(JsonHandler):
            def _load_headers(self):
                """Continuous-batching feedback for the gateway: engine
                queue depth + KV-block occupancy ride every completion
                response (WeightedGateway folds them into its routing
                score and admission decisions)."""
                st = frontend.engine.stats
                h = {"X-TPU-Queue-Depth": str(st.get("queue_depth", 0)),
                     "X-TPU-Active-Slots": str(st.get("active_slots", 0))}
                if "num_blocks" in st:
                    h["X-TPU-KV-Free-Blocks"] = str(st["free_blocks"])
                    h["X-TPU-KV-Total-Blocks"] = str(st["num_blocks"])
                if "advert_seq" in st:
                    # Tiered engines piggyback their residency-advert
                    # cursor + host-tier occupancy; the gateway pulls
                    # the /v1/kv/advert delta when the cursor moves.
                    h["X-TPU-KV-Advert-Seq"] = str(st["advert_seq"])
                    h["X-TPU-KV-Host-Free-Blocks"] = str(
                        st["host_blocks_total"] - st["host_blocks_used"])
                    h["X-TPU-KV-Host-Total-Blocks"] = str(
                        st["host_blocks_total"])
                return h

            def do_GET(self):
                if self.path == "/healthz":
                    # 503 on degradation: the pod's readiness/liveness
                    # probe fails, which is the kubelet-visible half of
                    # slice replacement.
                    if frontend.degraded is not None:
                        return self._send(503, {
                            "status": "degraded",
                            "reason": frontend.degraded})
                    return self._send(200, {"status": "ok"})
                if self.path == "/stats":
                    return self._send(200, frontend.stats())
                if self.path.split("?", 1)[0] == "/v1/kv/advert":
                    # Residency advert delta for the gateway's fleet
                    # index (serve/kv_tiers.py).  ?since=N returns the
                    # membership changes after N, or a full snapshot
                    # when N fell out of the bounded advert log.
                    if not hasattr(frontend.engine, "kv_advert"):
                        return self._send(501, {
                            "message": "KV adverts require a paged "
                                       "engine (--paged)"})
                    qs = self.path.partition("?")[2]
                    since = 0
                    for part in qs.split("&"):
                        if part.startswith("since="):
                            try:
                                since = int(part[6:])
                            except ValueError:
                                return self._send(400, {
                                    "message": "since must be an int"})
                    try:
                        doc = frontend.call_engine(
                            lambda e: e.kv_advert(since))
                    except TimeoutError as e:
                        return self._send(503, {"message": str(e)})
                    return self._send(200, doc,
                                      headers=self._load_headers())
                if self.path == "/metrics":
                    # Prometheus text exposition (the vLLM-server
                    # /metrics role): every numeric stat becomes a
                    # tpu_serve_* gauge/counter.  Monotonic stats are
                    # counters; point-in-time ones gauges.
                    counters = {"requests", "completed", "rejected",
                                "tokens_out", "prefix_hit_tokens",
                                "prefix_query_tokens", "drafted",
                                "accepted", "verify_steps"}
                    lines = []
                    for k, v in sorted(frontend.stats().items()):
                        if isinstance(v, bool) or \
                                not isinstance(v, (int, float)):
                            continue
                        name = f"tpu_serve_{k}"
                        kind = "counter" if k in counters else "gauge"
                        lines.append(f"# TYPE {name} {kind}")
                        lines.append(f"{name} {v}")
                    text = "\n".join(lines) + "\n"
                    # Engines built with a MetricsRegistry also expose
                    # the request-phase histograms
                    # (tpu_serve_request_duration_seconds{phase=...}).
                    reg = getattr(frontend.engine, "metrics", None)
                    if reg is not None and hasattr(reg, "render"):
                        text += reg.render()
                    return self._send_text(200, text,
                                           "text/plain; version=0.0.4")
                return self._send(404, {"message": "unknown path"})

            def do_POST(self):
                if self.path in ("/v1/kv/resident", "/v1/kv/export",
                                 "/v1/kv/import"):
                    return self._kv_endpoint()
                if self.path != "/v1/completions":
                    return self._send(404, {"message": "unknown path"})
                try:
                    body = self._body()
                except Exception as e:
                    return self._send(400, {"message": f"bad body: {e}"})
                if not isinstance(body, dict):
                    return self._send(400, {"message": "body must be a JSON "
                                                       "object"})
                prompt = body.get("prompt_tokens")
                if not isinstance(prompt, list) or not prompt or \
                        not all(isinstance(t, int) for t in prompt):
                    return self._send(
                        400, {"message": "prompt_tokens must be a non-empty "
                                         "list of token ids"})
                try:
                    max_tokens = int(body.get("max_tokens", 64))
                    temperature = float(body.get("temperature", 0.0))
                    top_p = float(body.get("top_p", 1.0))
                    top_k = int(body.get("top_k", 0))
                    stop_ids = body.get("stop_token_ids")
                    if stop_ids is not None and (
                            not isinstance(stop_ids, list) or
                            not all(isinstance(t, int) for t in stop_ids)):
                        return self._send(400, {
                            "message": "stop_token_ids must be a list "
                                       "of token ids"})
                    # Clamped: shutdown joins handler threads, so an
                    # unbounded client timeout would become an unbounded
                    # SIGTERM-to-exit time.
                    timeout = min(float(body.get("timeout", 300.0)), 600.0)
                except (TypeError, ValueError) as e:
                    return self._send(400, {"message": f"bad parameter: {e}"})
                if max_tokens <= 0:
                    return self._send(400, {"message": "max_tokens must be > 0"})
                if not 0.0 < top_p <= 1.0:
                    return self._send(400, {"message": "top_p must be in (0, 1]"})
                if top_k < 0:
                    return self._send(400, {"message": "top_k must be >= 0"})
                # Distributed tracing: adopt the gateway-minted trace
                # context so the engine's child spans (engine-queue /
                # prefill / decode / kv-alloc) land in the same trace,
                # and echo it so direct-replica clients can follow up at
                # /debug/traces too.
                trace = TraceContext.from_traceparent(
                    self.headers.get("traceparent"))
                resp_headers = self._load_headers()
                if trace is not None:
                    resp_headers["traceparent"] = trace.to_traceparent()
                if body.get("stream"):
                    return self._stream_completion(
                        prompt, max_tokens, temperature,
                        body.get("eos_token"), timeout, top_p, top_k,
                        stop_ids, trace)
                resp = frontend.submit(
                    prompt, max_tokens=max_tokens, temperature=temperature,
                    eos_token=body.get("eos_token"), timeout=timeout,
                    top_p=top_p, top_k=top_k, stop_token_ids=stop_ids,
                    trace=trace)
                if resp is None:
                    return self._send(503,
                                      {"message": "overloaded or timed out"},
                                      headers=resp_headers)
                return self._send(200, {
                    "id": resp.request_id,
                    "tokens": resp.tokens,
                    "finish_reason": resp.finish_reason,
                    "prompt_len": resp.prompt_len,
                    "ttft_ms": (round(resp.ttft_s * 1e3, 3)
                                if resp.ttft_s is not None else None),
                }, headers=resp_headers)

            def _kv_endpoint(self):
                """KV-block transfer protocol (disaggregated serving,
                docs/serving.md): ``resident`` probes the delta,
                ``export`` reads registered prefix blocks off a prefill
                replica, ``import`` adopts them on a decode replica.
                All three serialize with the engine loop via
                call_engine."""
                if not hasattr(frontend.engine, "import_kv_blocks"):
                    return self._send(501, {
                        "message": "KV-block transfer requires a paged "
                                   "engine (--paged)"})
                if frontend.degraded is not None:
                    return self._send(503, {"message": "degraded"})
                try:
                    body = self._body()
                except Exception as e:
                    return self._send(400, {"message": f"bad body: {e}"})
                prompt = body.get("prompt_tokens") \
                    if isinstance(body, dict) else None
                if not isinstance(prompt, list) or not prompt or \
                        not all(isinstance(t, int) for t in prompt):
                    return self._send(400, {
                        "message": "prompt_tokens must be a non-empty "
                                   "list of token ids"})
                try:
                    if self.path == "/v1/kv/resident":
                        n = frontend.call_engine(
                            lambda e: e.resident_prefix_blocks(prompt))
                        return self._send(
                            200, {"resident_blocks": n},
                            headers=self._load_headers())
                    if self.path == "/v1/kv/export":
                        try:
                            skip = int(body.get("skip_blocks", 0))
                            cap = int(body.get("max_blocks", 0))
                        except (TypeError, ValueError):
                            return self._send(400, {
                                "message": "skip_blocks/max_blocks must "
                                           "be ints"})
                        blocks = frontend.call_engine(
                            lambda e: e.export_kv_blocks(
                                prompt, skip_blocks=max(0, skip),
                                max_blocks=max(0, cap)))
                        return self._send(
                            200, {"blocks": blocks,
                                  "block_size": frontend.engine.block_size},
                            headers=self._load_headers())
                    blocks = body.get("blocks")
                    if not isinstance(blocks, list):
                        return self._send(400, {
                            "message": "blocks must be a list"})
                    counts = frontend.call_engine(
                        lambda e: e.import_kv_blocks(prompt, blocks))
                    return self._send(200, counts,
                                      headers=self._load_headers())
                except NotImplementedError as e:
                    return self._send(501, {"message": str(e)})
                except TimeoutError as e:
                    return self._send(503, {"message": str(e)})

            def _stream_completion(self, prompt, max_tokens, temperature,
                                   eos_token, timeout, top_p=1.0, top_k=0,
                                   stop_token_ids=None, trace=None):
                """Chunked NDJSON streaming ("stream": true): one
                {"tokens": [...]} line per engine emission (singles for
                plain decode, runs for accepted speculation), then a
                final line with finish_reason — or {"error": ...} if
                the request died mid-stream.  Admission rejection is
                decided BEFORE headers go out, so overloaded/degraded
                streams return the same 503 the blocking path does."""
                _json = json
                gen = frontend.submit_stream(
                    prompt, max_tokens=max_tokens,
                    temperature=temperature, eos_token=eos_token,
                    timeout=timeout, top_p=top_p, top_k=top_k,
                    stop_token_ids=stop_token_ids, trace=trace)
                try:
                    first = next(gen)
                except StopIteration:
                    first = None
                if first is None:
                    gen.close()
                    return self._send(503, {"message":
                                            "overloaded or timed out"})
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def emit(doc) -> bool:
                    data = _json.dumps(doc).encode() + b"\n"
                    try:
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                        return True
                    except (BrokenPipeError, ConnectionError, OSError):
                        return False

                for item in itertools.chain([first], gen):
                    if item is None:
                        emit({"error": "overloaded, degraded, or timed "
                                       "out"})
                        break
                    if isinstance(item, list):
                        if not emit({"tokens": item}):
                            return   # client gone; generator cleanup runs
                    else:
                        emit({"id": item.request_id,
                              "finish_reason": item.finish_reason,
                              "prompt_len": item.prompt_len,
                              "num_tokens": len(item.tokens)})
                        break
                try:
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    pass

        srv = ThreadingHTTPServer((host, port), Handler)
        # Non-daemon handler threads: socketserver only tracks (and
        # server_close() only joins) non-daemon threads, and the
        # graceful-drain path depends on that join — a daemonic handler
        # can be killed at interpreter exit between its submit()
        # returning and the response bytes hitting the socket.
        srv.daemon_threads = False
        return srv

    def serve_background(self, host="127.0.0.1", port=0):
        from kuberay_tpu.utils.httpjson import serve_background
        return serve_background(self.make_server(host, port), "serve-http")


def register_with_coordinator(app_name: str, coordinator_url: str,
                              status: str = "RUNNING") -> bool:
    """Report serve-app health to the head coordinator (what flips the
    TpuService controller's app status to RUNNING)."""
    from kuberay_tpu.runtime.coordinator_client import (
        CoordinatorClient, CoordinatorError)
    try:
        CoordinatorClient(coordinator_url).set_serve_app_status(
            app_name, status)
        return True
    except CoordinatorError:
        return False


# serve-config application keys -> engine CLI args (the
# serveConfig-to-engine wire: what a TpuService's spec.serveConfig
# application block may set; explicit CLI flags are overwritten — the
# controller-submitted config is the source of truth in a managed pod).
# key -> (coercion, allowed-choices or None): raw JSON/YAML values get
# the same typing + choices discipline the argparse path enforces, so a
# string "8" or an invalid kv_quant fails with a clean parameter error
# instead of a deep engine traceback.
_CONFIG_KEYS = {
    "model": (str, None),
    "paged": (bool, None),
    "block_size": (int, None),
    "num_blocks": (int, None),
    "host_blocks": (int, None),
    "spill_blocks": (int, None),
    "prefill_chunk": (int, None),
    "speculative": (int, None),
    "kv_quant": (str, ("none", "int8")),
    "weight_quant": (str, ("none", "int8")),
    "tp": (int, None),
    "max_slots": (int, None),
    "max_len": (int, None),
    "checkpoint_dir": (str, None),
    "checkpoint_step": (int, None),
    "decode_impl": (str, ("auto", "pallas", "xla", "pallas_interpret")),
    # Group-health watchdog overrides (multi-host slices): the adaptive
    # budget usually makes these unnecessary, but an app with known
    # extreme step-time variance can widen its own envelope without
    # touching operator env.
    "group_miss_timeout": (float, None),
    "group_step_timeout": (float, None),
    "group_compile_timeout": (float, None),
    "group_budget_multiplier": (float, None),
}


def _apply_coordinator_config(args, ap) -> None:
    """Fetch the submitted serve config and fold this app's settings
    into ``args`` (bounded wait: the controller PUTs the config only
    once the cluster reports ready, which may be after pod start)."""
    import time as _time
    from kuberay_tpu.runtime.coordinator_client import (
        CoordinatorClient, CoordinatorError)
    if not args.coordinator:
        ap.error("--config-from-coordinator requires --coordinator "
                 "(or auto with the operator env)")
    client = CoordinatorClient(args.coordinator)
    deadline = _time.time() + args.config_wait
    cfg = None
    while _time.time() < deadline:
        try:
            doc = client.get_serve_config()
        except CoordinatorError:
            doc = {}
        for app in (doc or {}).get("applications", []) or []:
            if app.get("name") == args.app_name:
                cfg = app
                break
        if cfg is not None:
            break
        _time.sleep(1.0)
    if cfg is None:
        ap.error(f"serve config for app {args.app_name!r} did not "
                 f"appear on {args.coordinator} within "
                 f"{args.config_wait:.0f}s")
    applied = {}
    for key, (coerce, choices) in _CONFIG_KEYS.items():
        if key not in cfg:
            continue
        try:
            val = coerce(cfg[key])
        except (TypeError, ValueError):
            ap.error(f"serve config {key}={cfg[key]!r}: not a valid "
                     f"{coerce.__name__}")
        if choices is not None and val not in choices:
            ap.error(f"serve config {key}={val!r}: must be one of "
                     f"{choices}")
        setattr(args, key, val)
        applied[key] = val
    print(f"serve config applied for app {args.app_name!r}: {applied}",
          flush=True)


def main(argv=None):  # pragma: no cover - process wrapper
    import argparse
    from kuberay_tpu.utils.platform import pin_platform_from_env
    pin_platform_from_env()
    import jax
    from kuberay_tpu.models import llama
    ap = argparse.ArgumentParser(prog="tpu-serve")
    ap.add_argument("--model", default="llama_1b")
    ap.add_argument("--port", type=int, default=C.PORT_SERVE)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--max-slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=2048)
    ap.add_argument("--app-name", default="llm")
    ap.add_argument("--coordinator", default="")
    ap.add_argument("--checkpoint-dir", default="",
                    help="serve params restored from this TRAIN "
                         "checkpoint directory (instead of seed-0 "
                         "init); sharded onto the serve mesh under --tp")
    ap.add_argument("--checkpoint-step", type=int, default=-1,
                    help="checkpoint step to serve (-1 = latest; 0 is "
                         "a real step)")
    ap.add_argument("--config-from-coordinator", action="store_true",
                    help="read this app's engine settings from the "
                         "coordinator's submitted serve config (what "
                         "the TpuService controller PUT) before "
                         "starting — the serveConfig-to-engine wire")
    ap.add_argument("--config-wait", type=float, default=60.0,
                    help="seconds to wait for the serve config to "
                         "appear on the coordinator")
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache with prefix caching")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=0,
                    help="KV pool size in blocks (0 = dense-equivalent)")
    ap.add_argument("--host-blocks", type=int, default=0,
                    help="host-DRAM KV tier capacity in blocks (0 = "
                         "tiering off; paged engines only)")
    ap.add_argument("--spill-blocks", type=int, default=0,
                    help="bounded spill KV tier behind the host tier "
                         "(blocks; 0 = off)")
    ap.add_argument("--decode-impl", default="auto",
                    choices=["auto", "pallas", "xla", "pallas_interpret"],
                    help="decode attention path for the paged and "
                         "int8-quantized caches (auto: pallas on TPU)")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunked prefill size (0 = whole-prompt prefill)")
    ap.add_argument("--speculative", type=int, default=0,
                    help="prompt-lookup speculative decoding draft length "
                         "(dense engine, greedy slots; 0 = off)")
    ap.add_argument("--kv-quant", default="none", choices=["none", "int8"],
                    help="KV cache storage dtype (dense engine)")
    ap.add_argument("--weight-quant", default="none",
                    choices=["none", "int8"],
                    help="W8A16: int8 matmul weights with per-channel "
                         "scales (half the weight HBM + decode "
                         "bandwidth); composes with every engine mode")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor parallelism over the slice's chips "
                         "(0 = all global devices; composes with all "
                         "engine modes incl. --paged). "
                         "Multi-host: every host of the TpuService slice "
                         "runs this same command; the operator's env "
                         "contract joins them into one jax.distributed "
                         "group and hosts >0 become lockstep followers")
    import os as _argenv
    ap.add_argument("--group-miss-timeout", type=float,
                    default=float(_argenv.environ.get(
                        "TPU_GROUP_MISS_TIMEOUT", "10")),
                    help="seconds of missed follower heartbeats before "
                         "the group degrades")
    ap.add_argument("--group-step-timeout", type=float,
                    default=float(_argenv.environ.get(
                        "TPU_GROUP_STEP_TIMEOUT", "60")),
                    help="COLD-START device-step budget; after ~20 "
                         "observed steps the watchdog switches to an "
                         "adaptive budget (multiplier x rolling p99, "
                         "floored at the miss timeout)")
    ap.add_argument("--group-compile-timeout", type=float,
                    default=float(_argenv.environ.get(
                        "TPU_GROUP_COMPILE_TIMEOUT", "900")),
                    help="budget for first-shape (compiling) steps")
    ap.add_argument("--group-budget-multiplier", type=float,
                    default=float(_argenv.environ.get(
                        "TPU_GROUP_BUDGET_MULTIPLIER", "20")),
                    help="adaptive budget = this x rolling p99 step "
                         "time")
    args = ap.parse_args(argv)
    if args.coordinator == "auto":
        # Resolve from the operator-injected env (builders/pod.py).
        import os as _os0
        from kuberay_tpu.runtime.coordinator_client import dashboard_url
        addr = _os0.environ.get(C.ENV_COORDINATOR_ADDRESS, "")
        args.coordinator = dashboard_url(addr) if addr else ""
    if args.config_from_coordinator:
        _apply_coordinator_config(args, ap)
    # Slice identity: same env contract as the training launcher
    # (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES injected by builders/pod.py).
    from kuberay_tpu.train.launcher import (
        WorkerIdentity, initialize_distributed)
    ident = WorkerIdentity.from_env()
    if ident.is_distributed:
        initialize_distributed(ident)
    tp = args.tp if args.tp > 0 else len(jax.devices())
    if ident.is_distributed and args.tp == 1:
        tp = len(jax.devices())        # multi-host implies slice-wide TP
    if jax.process_count() > 1 and tp != len(jax.devices()):
        # A sub-slice mesh would exclude some hosts' chips: those hosts
        # crash before reaching follower_loop and the rest hang in their
        # first collective.  Slice-wide TP is the only multi-host layout.
        ap.error(f"multi-host serving requires tp == total chips "
                 f"({len(jax.devices())}); got --tp {args.tp}. "
                 f"Use --tp 0 (auto)")

    cfg = llama.CONFIGS[args.model]
    mesh = None
    param_sh = None
    if tp > 1:
        from kuberay_tpu.serve.sharding import (
            init_sharded_params, param_shardings, serve_mesh)
        mesh = serve_mesh(tp, n_kv_heads=cfg.n_kv_heads)
        param_sh = param_shardings(cfg, mesh)
    params = None
    if args.checkpoint_dir:
        # Train-to-serve handoff: restore the trained params (sharded
        # straight onto the serve mesh when tp > 1) instead of seed-0
        # weights.  Missing checkpoint is a hard error — silently
        # serving random weights would look like a broken model.
        from kuberay_tpu.train.checkpoint import load_params_for_serving
        step = None if args.checkpoint_step < 0 else args.checkpoint_step
        params = load_params_for_serving(
            args.checkpoint_dir, step=step,
            shardings=param_sh, dtype=cfg.dtype)
        if params is None:
            ap.error(f"no checkpoint found in {args.checkpoint_dir}"
                     + (f" at step {step}" if step is not None else ""))
        print(f"restored params from {args.checkpoint_dir} "
              f"(step {'latest' if step is None else step})", flush=True)
    elif tp > 1:
        # Init directly into shards — the flagship model does not fit
        # one chip (checkpoint restore takes the same sharding tree).
        params = init_sharded_params(cfg, jax.random.PRNGKey(0), mesh)
    else:
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
    if args.paged:
        engine_kw = dict(max_slots=args.max_slots, max_len=args.max_len,
                         num_blocks=args.num_blocks,
                         block_size=args.block_size,
                         decode_impl=args.decode_impl,
                         prefill_chunk=args.prefill_chunk,
                         speculative=args.speculative,
                         kv_quant=args.kv_quant, mesh=mesh,
                         weight_quant=args.weight_quant,
                         donate_params=args.weight_quant != "none",
                         host_blocks=args.host_blocks,
                         spill_blocks=args.spill_blocks)
    else:
        engine_kw = dict(max_slots=args.max_slots, max_len=args.max_len,
                         prefill_chunk=args.prefill_chunk,
                         speculative=args.speculative,
                         kv_quant=args.kv_quant,
                         decode_impl=args.decode_impl, mesh=mesh,
                         weight_quant=args.weight_quant,
                         donate_params=args.weight_quant != "none")
    # Request-phase histograms (queue | prefill | decode) for the
    # /metrics surface; host 0 only — followers have no frontend.
    if jax.process_count() == 1 or jax.process_index() == 0:
        from kuberay_tpu.utils.metrics import MetricsRegistry
        engine_kw["metrics"] = MetricsRegistry()
    # ONE class-pair selection for both roles: hosts and followers must
    # construct matching engines or plan pytree shapes diverge (a
    # cross-host hang, not an error).
    if args.paged:
        from kuberay_tpu.serve.multihost import MultihostPagedServeEngine
        from kuberay_tpu.serve.paged_engine import PagedServeEngine
        engine_cls, multihost_cls = (PagedServeEngine,
                                     MultihostPagedServeEngine)
    else:
        from kuberay_tpu.serve.multihost import MultihostServeEngine
        engine_cls, multihost_cls = ServeEngine, MultihostServeEngine

    import os as _os
    hb_port = int(_os.environ.get("TPU_GROUP_HEALTH_PORT",
                                  C.PORT_GROUP_HEALTH))
    if jax.process_count() > 1 and jax.process_index() > 0:
        # Follower host: no frontend, no scheduling — replay host 0's
        # device calls until it broadcasts STOP.  Paged followers hold a
        # pool but no allocator state (tables ride the plan).  A daemon
        # thread heartbeats host 0 so a follower death is DETECTED there
        # instead of manifesting only as a hung collective.
        from kuberay_tpu.serve.group_health import start_heartbeat
        from kuberay_tpu.serve.multihost import follower_loop
        engine = engine_cls(cfg, params, **engine_kw)
        host0 = ident.hostnames[0] if ident.hostnames else "127.0.0.1"
        start_heartbeat(host0, hb_port, ident.worker_id)
        print(f"serve follower {jax.process_index()}/"
              f"{jax.process_count()} ready", flush=True)
        follower_loop(engine)
        return

    monitor = None
    if jax.process_count() > 1:
        from kuberay_tpu.serve.group_health import GroupMonitor
        engine = multihost_cls(cfg, params, **engine_kw)
        monitor = GroupMonitor(
            expected=list(range(1, jax.process_count())),
            miss_timeout=args.group_miss_timeout,
            step_timeout=args.group_step_timeout,
            compile_timeout=args.group_compile_timeout,
            budget_multiplier=args.group_budget_multiplier)
        monitor.listen(port=hb_port)
    else:
        engine = engine_cls(cfg, params, **engine_kw)

    def on_degraded(reason: str) -> None:
        # Surface upward: the TpuService controller maps a DEGRADED app
        # to the ServeGroupDegraded condition and replaces the slice.
        # The transition fires exactly once, so the report RETRIES until
        # delivered — a transient coordinator blip exactly when a slice
        # fails must not lose the replacement trigger (the daemon thread
        # dies with the process once the slice is replaced).
        print(f"serve: DEGRADED — {reason}", flush=True)
        if not args.coordinator:
            return

        def report_until_delivered():
            from kuberay_tpu.runtime.coordinator_client import (
                CoordinatorClient, CoordinatorError)
            while True:
                try:
                    CoordinatorClient(args.coordinator) \
                        .set_serve_app_status(args.app_name, "DEGRADED",
                                              reason)
                    return
                except CoordinatorError:
                    time.sleep(5.0)

        threading.Thread(target=report_until_delivered, daemon=True,
                         name="degraded-report").start()

    frontend = ServeFrontend(engine, monitor=monitor,
                             on_degraded=on_degraded)
    srv = frontend.make_server(args.host, args.port)
    if args.coordinator:
        register_with_coordinator(args.app_name, args.coordinator)
    print(f"serving {args.model} on {args.host}:{srv.server_address[1]} "
          f"(tp={tp}, hosts={jax.process_count()})", flush=True)
    # Graceful termination (a TpuService roll SIGTERMs old-cluster pods):
    # stop accepting, DRAIN in-flight requests to real responses, then
    # shut the engine down.  The handler must not call srv.shutdown()
    # inline — it runs on the thread executing serve_forever.
    import signal

    def _on_term(signum, frame):
        print("serve: SIGTERM — draining", flush=True)
        threading.Thread(target=srv.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # pragma: no cover - non-main thread
        pass
    try:
        srv.serve_forever()
    finally:
        if args.coordinator:
            # FIRST: flip the app status so the controller stops routing
            # here while we drain (we already stopped accepting).
            try:
                register_with_coordinator(args.app_name, args.coordinator,
                                          status="STOPPED")
            except Exception:
                pass
        drained = frontend.drain(timeout=60.0)
        # Join in-flight HTTP handler threads (non-daemon by
        # make_server precisely so server_close tracks and joins them;
        # a daemonic handler could die between its submit() returning
        # and the response bytes hitting the socket).
        try:
            srv.server_close()
        except OSError:
            pass
        print(f"serve: drained={drained}", flush=True)
        # Quiesce the engine-loop thread BEFORE broadcasting STOP — two
        # threads issuing collectives concurrently can pair a follower's
        # receive with the wrong send.  Wait for real thread death, not a
        # bounded join: an in-flight step must finish its broadcasts.
        frontend.close(timeout=None)
        if hasattr(engine, "stop"):
            engine.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
