"""Tensor-parallel serving: mesh + shardings for the inference engines.

The serving counterpart of ``parallel/mesh.py``'s training rules — the
payload half of BASELINE config #4 (a TpuService on a v5e-16 slice serving
Llama-3-8B, the role vLLM-on-TPU plays for the reference:
reference ``config/samples/vllm/ray-service.vllm-tpu-v6e-singlehost.yaml``).

Design: a 2-axis serving mesh ``("tp", "tpr")`` over the slice's chips.

- ``tp`` — the kv-head axis: q heads, kv heads, mlp width, and vocab all
  split here; the KV cache shards its kv-head axis on it.
- ``tpr`` — kv replication: when the requested parallelism exceeds
  ``n_kv_heads`` (llama3_8b has 8 kv heads but a v5e-16 slice has 16
  chips), the extra factor goes here.  Q heads/mlp/vocab split over
  ``(tp, tpr)`` jointly; the KV cache is *replicated* across ``tpr`` —
  exactly GQA's memory/compute trade (kv reads are the decode bottleneck
  and stay fully parallel; the cache costs tpr× memory vs the ideal).
  With tp ≤ n_kv_heads, tpr is 1 and this is plain head-sharded TP.

Param placement (``models/*.param_axes`` → ``SERVE_RULES``): one chip
holds ~1/(tp·tpr) of the weights — this is what lets 8B+ models serve on
chips they cannot fit on alone.  XLA inserts one psum per layer (after
``wo``/``w_down``) plus the logits gather, all riding ICI.

Pallas kernels (decode attention, int8 decode) are invisible to the SPMD
partitioner, so attention is wrapped in ``shard_map``: each chip runs the
unmodified kernel on its local head shard, no collectives inside.

GQA grouping survives the split: q heads shard over (tp, tpr) in
contiguous blocks, so the shard at mesh coordinate (i, j) holds q heads
whose kv head is exactly i — the kv shard the cache sharding puts there.

The host-side engine loop is unchanged: scheduling is data-independent of
sharding.  Multi-host lockstep execution lives in ``serve/multihost.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kuberay_tpu.parallel.mesh import logical_to_sharding

shard_map = jax.shard_map

# Serving logical->mesh rules.  Differs from training DEFAULT_RULES:
# no fsdp/sp/ep axes exist here — embed/batch/seq/expert replicate; the
# head/width axes split over the joint (tp, tpr) parallelism except kv
# heads, which split over tp only (replicated across tpr).
SERVE_RULES: Dict[str, object] = {
    "batch": None,
    "seq": None,
    "embed": None,
    "heads": ("tp", "tpr"),
    "kv_heads": "tp",
    "mlp": ("tp", "tpr"),
    "vocab": ("tp", "tpr"),
    "layers": None,
    "expert": None,
    "head_dim": None,
    "norm": None,
}


def tp_factors(tp: int, n_kv_heads: Optional[int] = None) -> tuple:
    """Split total parallelism into (kv-shard factor, kv-replica factor)."""
    if n_kv_heads is None or tp <= n_kv_heads:
        return tp, 1
    if tp % n_kv_heads:
        raise ValueError(
            f"tp={tp} exceeds n_kv_heads={n_kv_heads} but is not a "
            f"multiple of it")
    return n_kv_heads, tp // n_kv_heads


def serve_mesh(tp: int, devices: Optional[Sequence[jax.Device]] = None,
               n_kv_heads: Optional[int] = None) -> Mesh:
    """A serving mesh over ``tp`` chips: axes ("tp", "tpr").

    Pass the model's ``n_kv_heads`` so tp > n_kv_heads lands the excess
    on the kv-replication axis; without it, tp must divide the model's
    kv heads (validate_tp enforces this at engine construction).
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < tp:
        raise ValueError(f"tp={tp} needs {tp} devices, have {len(devices)}")
    kv, rep = tp_factors(tp, n_kv_heads)
    arr = np.array(devices[:tp]).reshape(kv, rep)
    return Mesh(arr, ("tp", "tpr"))


def mesh_tp(mesh: Mesh) -> int:
    """Total tensor parallelism of a serving mesh."""
    return mesh.shape.get("tp", 1) * mesh.shape.get("tpr", 1)


def validate_tp(cfg, mesh: Mesh) -> None:
    """Serving TP needs even splits (NamedSharding requires divisibility,
    and GQA groups must not straddle shards)."""
    tp = mesh_tp(mesh)
    kv = mesh.shape.get("tp", 1)
    problems = []
    if cfg.n_kv_heads % kv:
        problems.append(f"n_kv_heads={cfg.n_kv_heads} by kv axis {kv}")
    if cfg.n_heads % tp:
        problems.append(f"n_heads={cfg.n_heads}")
    if cfg.d_ff % tp:
        problems.append(f"d_ff={cfg.d_ff}")
    if cfg.vocab_size % tp:
        problems.append(f"vocab_size={cfg.vocab_size}")
    if problems:
        raise ValueError(
            f"tp={tp} does not divide {', '.join(problems)}; choose a tp "
            f"that divides heads/d_ff/vocab (build the mesh with "
            f"serve_mesh(tp, n_kv_heads=...) so kv replication absorbs "
            f"tp > n_kv_heads)")


def param_shardings(cfg, mesh: Mesh):
    """NamedSharding tree matching the model's params tree."""
    from kuberay_tpu.models import llama
    try:
        from kuberay_tpu.models import mixtral
        is_moe = isinstance(cfg, mixtral.MixtralConfig)
    except ImportError:  # pragma: no cover
        is_moe = False
    axes = mixtral.param_axes(cfg) if is_moe else llama.param_axes(cfg)
    return jax.tree.map(
        lambda a: logical_to_sharding(SERVE_RULES, mesh, a), axes,
        is_leaf=lambda x: isinstance(x, tuple))


def cache_shardings(cfg, mesh: Mesh, quant: str = "none"):
    """Shardings for the ``init_kv_cache`` layout: kv heads on ``tp``
    (replicated across ``tpr``).

    bf16: k/v are [L, slots, max_len, Hkv, D].  int8 adds per-(slot,
    position, head) scales in the lane-major [L, slots, Hkv, max_len]
    layout (kv_cache.init_kv_cache).
    """
    kv = NamedSharding(mesh, P(None, None, None, "tp", None))
    if quant == "int8":
        leaf = {"q": kv, "s": NamedSharding(mesh, P(None, None, "tp", None))}
        return {"k": leaf, "v": leaf}
    return {"k": kv, "v": kv}


_Q_HEADS = P(None, None, ("tp", "tpr"), None)
_KV_HEADS = P(None, None, "tp", None)


def make_tp_attention(mesh: Mesh):
    """shard_map the dense cache-attention over the serving mesh.

    Per-layer shapes (inside the model's layer scan): q [B, T, Hq, D];
    ck/cv [B, max_len, Hkv, D]; lens [B]; positions [B, T].  Heads are
    independent, so each shard runs the stock attention (including the
    Pallas decode kernel on TPU) on its local q heads against its local
    (or tpr-replicated) kv heads — no collective inside.
    """
    from kuberay_tpu.serve.kv_cache import _cached_attention

    fn = shard_map(
        _cached_attention, mesh=mesh,
        in_specs=(_Q_HEADS, _KV_HEADS, _KV_HEADS, P(None), P(None, None)),
        out_specs=_Q_HEADS, check_vma=False)

    def attention(q, ck, cv, lens, q_positions):
        return fn(q, ck, cv, lens, q_positions)

    return attention


def make_tp_attention_quant(mesh: Mesh, attention_fn):
    """shard_map an int8-cache attention closure (make_quantized_forward's
    inner ``attention``) over the serving mesh.  Cache leaves are
    {"q": [B, M, Hkv, D] int8, "s": [B, Hkv, M] f32}."""
    kv_struct = {"q": _KV_HEADS, "s": P(None, "tp", None)}
    fn = shard_map(
        attention_fn, mesh=mesh,
        in_specs=(_Q_HEADS, kv_struct, kv_struct, P(None), P(None, None)),
        out_specs=_Q_HEADS, check_vma=False)

    def attention(q, ckv, cvv, lens, q_positions):
        return fn(q, ckv, cvv, lens, q_positions)

    return attention


def paged_cache_shardings(mesh: Mesh, quant: str = "none"):
    """Shardings for the paged pool layout [L, Hkv, P, D]
    (paged_kv.init_paged_cache): kv heads on tp, replicated on tpr.
    int8 adds per-(head, position) scale pools [L, Hkv, P]."""
    kv = NamedSharding(mesh, P(None, "tp", None, None))
    if quant == "int8":
        leaf = {"q": kv, "s": NamedSharding(mesh, P(None, "tp", None))}
        return {"k": leaf, "v": leaf}
    return {"k": kv, "v": kv}


def make_tp_paged_attention(mesh: Mesh, local_decode):
    """shard_map a block-table-native paged decode closure
    (paged_kv.make_paged_forward's ``local_decode(q, pk, pv, lens,
    tables)``): q [B, 1, Hq, D] heads over (tp, tpr); pool [Hkv, P, D]
    heads over tp; tables/lens replicated — the full table is valid on
    every shard because the pool's position axis is unsplit."""
    return shard_map(
        local_decode, mesh=mesh,
        in_specs=(_Q_HEADS, P("tp", None, None), P("tp", None, None),
                  P(None), P(None, None)),
        out_specs=_Q_HEADS, check_vma=False)


def init_sharded_params(cfg, key, mesh: Mesh):
    """Random-init params directly into their serving shards.

    ``init_params`` + ``device_put`` would materialize the full model on
    one chip first — an 8B bf16 model is ~16 GB and does not fit.  jit
    with ``out_shardings`` makes XLA generate each shard in place.  Real
    deployments restore a checkpoint instead (train/checkpoint.py's Orbax
    sharded restore takes the same sharding tree).
    """
    from kuberay_tpu.models import llama
    try:
        from kuberay_tpu.models import mixtral
        mod = mixtral if isinstance(cfg, mixtral.MixtralConfig) else llama
    except ImportError:  # pragma: no cover
        mod = llama
    p_sh = param_shardings(cfg, mesh)
    init = jax.jit(lambda k: mod.init_params(cfg, k), out_shardings=p_sh)
    return init(key)
