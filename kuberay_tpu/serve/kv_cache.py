"""KV-cache inference path for the Llama family.

Static-shape cache ([layers, slots, max_len, kv_heads, head_dim]) so every
prefill/decode step compiles once and stays on the MXU; per-slot lengths
drive masking (no dynamic shapes under jit).  Slot-granular updates let a
continuous-batching engine admit/evict requests without touching other
slots.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from kuberay_tpu.ops.rmsnorm import rmsnorm
from kuberay_tpu.ops.rope import apply_rope, rope_frequencies

_NEG_INF = -1e30


def init_kv_cache(cfg, slots: int, max_len: int,
                  quant: str = "none") -> Dict[str, Any]:
    """Works for any config exposing n_layers/n_kv_heads/head_dim/dtype
    (Llama and Mixtral).  ``quant="int8"`` stores K/V as int8 with one
    f32 absmax scale per (slot, position, head) vector — the cache at
    rest is ~half the bytes of bf16 (vLLM kv_cache_dtype=int8 role)."""
    shape = (cfg.n_layers, slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    if quant == "int8":
        # Scales live position-on-lanes ([..., Hkv, max_len]) — the
        # layout the Pallas decode kernel streams (a [..., 1] trailing
        # axis would violate TPU lane tiling).
        sshape = (cfg.n_layers, slots, cfg.n_kv_heads, max_len)
        leaf = lambda: {"q": jnp.zeros(shape, jnp.int8),     # noqa: E731
                        "s": jnp.zeros(sshape, jnp.float32)}
        return {"k": leaf(), "v": leaf()}
    if quant != "none":
        raise ValueError(f"unknown kv quant {quant!r}")
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def quantize_kv(x: jax.Array):
    """Per-vector symmetric int8: scale = absmax/127 over the head dim.
    x: [..., D] -> (q int8 [..., D], s f32 [..., 1])."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127
                 ).astype(jnp.int8)
    return q, s


def dequantize_kv(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * s).astype(dtype)


def _cached_attention(q, ck, cv, lens, q_positions):
    """q: [B, T, Hq, D] new queries; ck/cv: [B, max, Hkv, D] cache (already
    containing the new tokens); lens: [B] valid lengths AFTER insertion;
    q_positions: [B, T] absolute positions of the queries."""
    B, T, Hq, D = q.shape
    if T == 1:
        # Decode hot path: the Pallas kernel streams only each slot's live
        # cache blocks (ops/decode_attention.py); GQA handled inside.
        from kuberay_tpu.ops.decode_attention import decode_attention
        return decode_attention(q[:, 0], ck, cv, lens)[:, None]
    Hkv = ck.shape[2]
    group = Hq // Hkv
    if group > 1:
        ck = jnp.repeat(ck, group, axis=2)
        cv = jnp.repeat(cv, group, axis=2)
    s = jnp.einsum("bthd,bkhd->bhtk", q, ck,
                   preferred_element_type=jnp.float32) / (D ** 0.5)
    cols = jnp.arange(ck.shape[1])[None, None, :]               # [1,1,max]
    mask = (cols <= q_positions[:, :, None]) & \
        (cols < lens[:, None, None])                            # [B,T,max]
    s = jnp.where(mask[:, None, :, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhtk,bkhd->bthd", p.astype(cv.dtype), cv,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def forward_with_cache_mixtral(cfg, params, tokens, cache, start,
                               write_mask=None, token_mask=None,
                               kv_update=None, attention=None):
    """Mixtral against the cache: the shared layer plumbing with the MoE
    FFN swapped in.  Router aux losses are irrelevant at inference.  The
    token mask keeps padding/inactive slots out of expert routing."""
    from kuberay_tpu.models.mixtral import (
        MixtralConfig, moe_ffn, moe_ffn_dropless)

    assert isinstance(cfg, MixtralConfig)

    def ffn(cfg_, h, lp, mask):
        # Dropless routing for BOTH decode and prefill: each token's
        # routing depends only on its own hidden state, so outputs are
        # invariant to batch composition, chunked-prefill boundaries, and
        # cached-prefix reuse — the properties serving correctness rests
        # on (capacity routing has none of them: which tokens overflow an
        # expert depends on what else is in the call).  The grouped
        # ragged_dot path (ops/moe_matmul.py) makes this the CHEAPER
        # option too: K*T matmul rows vs capacity's ~K*T*capacity_factor.
        # Capacity dispatch (moe_ffn) remains the training path, where
        # batched one-hot einsums + fixed shapes win under pjit.
        return moe_ffn_dropless(cfg_, h, lp, token_mask=mask)

    return forward_with_cache(cfg, params, tokens, cache, start,
                              write_mask, token_mask=token_mask, ffn=ffn,
                              kv_update=kv_update, attention=attention)


def _insert_kv(ck, cv, kk, vv, positions, start, write_mask, T):
    """Shared cache insertion: dynamic-slice decode path, one-hot prefill."""
    if T == 1:
        def upd(cache_row, new_row, pos, m):
            written = jax.lax.dynamic_update_slice(
                cache_row, new_row.astype(cache_row.dtype), (pos, 0, 0))
            return jnp.where(m > 0, written, cache_row)
        return (jax.vmap(upd)(ck, kk, start, write_mask),
                jax.vmap(upd)(cv, vv, start, write_mask))
    onehot = (jax.nn.one_hot(positions, ck.shape[1], dtype=ck.dtype)
              * write_mask[:, None, None].astype(ck.dtype))
    ck = ck * (1 - onehot.sum(1)[..., None, None]) + \
        jnp.einsum("btm,bthd->bmhd", onehot, kk)
    cv = cv * (1 - onehot.sum(1)[..., None, None]) + \
        jnp.einsum("btm,bthd->bmhd", onehot, vv)
    return ck, cv


def _insert_scales(cs, new_s, positions, start, write_mask, T):
    """Insert per-position scales into the [S, Hkv, M] cache layout.
    new_s: [S, T, Hkv]."""
    if T == 1:
        def upd(row, new_row, pos, m):        # row: [Hkv, M]
            written = jax.lax.dynamic_update_slice(
                row, new_row[:, None], (0, pos))
            return jnp.where(m > 0, written, row)
        return jax.vmap(upd)(cs, new_s[:, 0], start, write_mask)
    onehot = (jax.nn.one_hot(positions, cs.shape[-1], dtype=cs.dtype)
              * write_mask[:, None, None].astype(cs.dtype))   # [S, T, M]
    keep = 1 - onehot.sum(1)                                  # [S, M]
    return cs * keep[:, None, :] + jnp.einsum("btm,bth->bhm", onehot, new_s)


def _cached_attention_quant_multi(q, ckv, cvv, lens, q_positions):
    """T>1 attention straight off the int8 cache (chunked prefill /
    speculative verify hot path): scales fold into score columns and
    probability rows, the int8→f32 converts fuse into the dots, and —
    unlike dequantize-then-attend — no full bf16 copy of the cache is
    ever materialized (ADVICE r2: that copy ran per chunk / per verify
    step, negating the int8 bandwidth win).  GQA via a grouped einsum
    instead of repeating the cache."""
    B, T, Hq, D = q.shape
    kq, ks = ckv["q"], ckv["s"]        # [B, M, Hkv, D] i8, [B, Hkv, M] f32
    vq, vs = cvv["q"], cvv["s"]
    Hkv = kq.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, T, Hkv, G, D)
    s = jnp.einsum("btngd,bmnd->bntgm", qg.astype(jnp.float32),
                   kq.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    s = s * ks[:, :, None, None, :] / (D ** 0.5)
    cols = jnp.arange(kq.shape[1])[None, None, :]
    mask = (cols <= q_positions[:, :, None]) & \
        (cols < lens[:, None, None])                    # [B, T, M]
    s = jnp.where(mask[:, None, :, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bntgm,bmnd->btngd",
                     p * vs[:, :, None, None, :],
                     vq.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(B, T, Hq, D).astype(q.dtype)


def make_quantized_forward(base_forward=None, decode_impl: str = "auto",
                           mesh=None):
    """Wrap a cache forward with int8 K/V storage (init_kv_cache
    quant="int8" layout).  Same seam as make_paged_forward: this wrapper
    contributes a ``kv_update`` that quantizes on write, and an
    ``attention`` that consumes the int8 cache natively on BOTH paths:
    decode (T == 1) via ops/decode_attention.decode_attention_quant
    (streams HALF the bf16 kernel's HBM bytes), and multi-token calls
    (chunked prefill, speculative verify) via
    ``_cached_attention_quant_multi`` — scales fold into score columns
    and probability rows, never materializing a dequantized cache."""
    from kuberay_tpu.ops.decode_attention import decode_attention_quant
    base = base_forward or forward_with_cache

    def fwd(cfg, params, tokens, cache, start, write_mask=None,
            token_mask=None):
        B, T = tokens.shape
        positions = start[:, None] + jnp.arange(T)[None, :]
        if write_mask is None:
            write_mask = jnp.ones((B,), jnp.float32)

        def kv_update(ck, cv, kk, vv):        # ck/cv: {"q","s"} per layer
            kq, ks = quantize_kv(kk)          # ks: [S, T, Hkv, 1]
            vq, vs = quantize_kv(vv)
            nkq, nvq = _insert_kv(ck["q"], cv["q"], kq, vq, positions,
                                  start, write_mask, T)
            nks = _insert_scales(ck["s"], ks[..., 0], positions, start,
                                 write_mask, T)
            nvs = _insert_scales(cv["s"], vs[..., 0], positions, start,
                                 write_mask, T)
            nk, nv = {"q": nkq, "s": nks}, {"q": nvq, "s": nvs}
            return nk, nv, nk, nv             # attention reads the structs

        def attention(q, ckv, cvv, lens, q_positions):
            if q.shape[1] == 1:
                out = decode_attention_quant(
                    q[:, 0], ckv["q"], ckv["s"], cvv["q"], cvv["s"],
                    lens, impl=decode_impl)
                return out[:, None]
            return _cached_attention_quant_multi(q, ckv, cvv, lens,
                                                 q_positions)

        if mesh is not None:
            # Tensor-parallel: each chip runs the int8 kernel on its
            # local kv-head shard (serve/sharding.py cache layout).
            from kuberay_tpu.serve.sharding import make_tp_attention_quant
            attention = make_tp_attention_quant(mesh, attention)

        return base(cfg, params, tokens, cache, start, write_mask,
                    token_mask=token_mask, kv_update=kv_update,
                    attention=attention)

    return fwd


def _dense_ffn(cfg, h, lp, token_mask):
    return (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]


def forward_with_cache(cfg, params: Dict[str, Any],
                       tokens: jax.Array, cache: Dict[str, jax.Array],
                       start: jax.Array,
                       write_mask: jax.Array = None,
                       token_mask: jax.Array = None,
                       ffn=None, kv_update=None, attention=None
                       ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Run T new tokens through the model against the cache.

    tokens: [B, T] (right-padded; positions beyond a slot's real length are
    masked out by the caller's sampling); start: [B] number of tokens
    already in each slot's cache; write_mask: [B] 1.0 for rows whose cache
    may be written (prefill targets ONE slot — without the mask every row
    would scatter into positions start..start+T and corrupt its neighbors);
    token_mask: [B, T] real-token mask consumed by routing FFNs; ``ffn``
    customizes the feed-forward block (dense default, MoE for Mixtral);
    ``kv_update(ck, cv, kk, vv) -> (new_ck, new_cv, ck_view, cv_view)``
    customizes the cache layout — the default inserts into the per-slot
    contiguous cache, the paged path (serve/paged_kv.py) scatters into a
    block pool and gathers per-request views; ``attention(q, ck_view,
    cv_view, lens, positions)`` customizes the attention read (default
    ``_cached_attention``; the block-table-native paged path passes the
    raw pool plus a kernel that resolves the indirection itself).
    Everything else (the transformer layer body) is layout-agnostic and
    lives only here.  Returns (logits [B, T, V], new cache).
    """
    B, T = tokens.shape
    positions = start[:, None] + jnp.arange(T)[None, :]          # [B, T]
    x = jnp.take(params["embed"], tokens, axis=0)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    lens = start + T
    if write_mask is None:
        write_mask = jnp.ones((B,), jnp.float32)
    if ffn is None:
        ffn = _dense_ffn
    if attention is None:
        attention = _cached_attention
    if kv_update is None:
        # Default layout: insert new K/V at each slot's offset; masked
        # rows write nothing (dynamic-slice decode fast path, one-hot
        # prefill scatter).  The attention view IS the cache row.
        def kv_update(ck, cv, kk, vv):
            nk, nv = _insert_kv(ck, cv, kk, vv, positions, start,
                                write_mask, T)
            return nk, nv, nk, nv

    def layer_fn(x, layer_in):
        lp, ck, cv = layer_in
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"]).reshape(B, T, cfg.n_heads, cfg.head_dim)
        kk = (h @ lp["wk"]).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
        vv = (h @ lp["wv"]).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin, positions)
        kk = apply_rope(kk, cos, sin, positions)
        ck, cv, ck_view, cv_view = kv_update(ck, cv, kk, vv)
        attn = attention(q, ck_view, cv_view, lens, positions)
        x = x + (attn.reshape(B, T, -1) @ lp["wo"]).astype(x.dtype)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ffn(cfg, h, lp, token_mask).astype(x.dtype)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        layer_fn, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if getattr(cfg, "tie_embeddings", False) \
        else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", x, head,
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v}
