"""Tiered content-addressed KV block store + stateful session serving.

Three-tier hierarchy for paged-attention KV blocks, keyed by the chained
block hashes from :mod:`kuberay_tpu.serve.prefix`:

- **device** — the paged pool owned by :class:`BlockAllocator`.  The
  allocator remains the source of truth; this module only mirrors its
  membership (via ``note_device``) so tier adverts cover all three tiers.
- **host** — a bounded LRU of blocks demoted off-device when their last
  reference dropped.  Payloads are opaque to the store (the engine keeps
  float32 numpy copies produced by the ``export_kv_blocks`` wire format;
  the sim keeps raw token tuples).
- **spill** — a second bounded LRU fed by host-tier pressure.  When it
  overflows, the LRU block is dropped for good (next miss recomputes).

Every entry is content-addressed: ``checkout`` re-verifies that the
stored tokens are exactly the tokens the caller hashed, so a hash
collision or a stale overwrite yields a miss, never wrong KV.  This is
the invariant the sim's ``no-stale-block`` checker replays.

The store is the *only* sanctioned door to off-device block storage —
analysis rule ``kv-block-through-tier-seam`` flags code that reaches
into the underlying tier dicts instead of going through
``checkout``/``pin``.

Alongside the store:

- :class:`SessionTable` — gateway-side session objects (session id →
  block-hash chain + last-seen backend) with capacity and TTL bounds,
  so a multi-turn request resumes by block fetch instead of prefill.
- :class:`FleetKvIndex` — a fleet-wide content-addressed residency map
  built from backend adverts (monotonic sequence numbers over the load
  header channel; deltas fetched from ``/v1/kv/advert``), so placement
  can score *true* residency and name a peer to source missing blocks.

Everything here is plain Python — no jax imports — so the gateway, the
control plane, and the sim can all use it.

Thread-safety: the engine mutates its store only on the engine loop
(``call_engine`` seam); the gateway guards its session table and fleet
index with the gateway lock.  The store itself takes no locks.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple

import time

__all__ = [
    "KvTierStore",
    "SessionTable",
    "Session",
    "FleetKvIndex",
]

TIER_DEVICE = "device"
TIER_HOST = "host"
TIER_SPILL = "spill"


def _describe_tier_metrics(metrics) -> None:
    metrics.describe("tpu_kv_tier_blocks",
                     "KV blocks currently resident per tier")
    metrics.describe("tpu_kv_tier_capacity_blocks",
                     "Configured KV block capacity per tier")
    metrics.describe("tpu_kv_tier_hits_total",
                     "Tier-store checkouts that returned a block, per tier")
    metrics.describe("tpu_kv_tier_misses_total",
                     "Tier-store checkouts that found no block")
    metrics.describe("tpu_kv_tier_demotions_total",
                     "Blocks demoted between tiers (src/dst labelled)")
    metrics.describe("tpu_kv_tier_promotions_total",
                     "Blocks promoted toward device (source tier labelled)")
    metrics.describe("tpu_kv_tier_evictions_total",
                     "Blocks dropped from the bottom of the hierarchy")
    metrics.describe("tpu_kv_tier_stale_drops_total",
                     "Checkouts whose stored tokens mismatched the hash "
                     "(entry dropped instead of served)")


class KvTierStore:
    """Host + spill LRU tiers with capacity accounting and an advert log.

    ``host_blocks``/``spill_blocks`` are capacities in KV blocks; a tier
    with capacity 0 is disabled.  ``admit`` lands a block in the host
    tier, demoting host→spill (and spill→gone) under pressure, skipping
    pinned entries.  ``checkout`` verifies content and promotes
    spill→host on hit.  Each membership change appends to a bounded
    advert log; readers poll ``advert_since(seq)`` and get either a
    delta or, after falling behind the log window, a full snapshot.
    """

    def __init__(self, host_blocks: int, spill_blocks: int = 0, *,
                 metrics=None, advert_capacity: int = 4096):
        self.host_blocks = int(host_blocks)
        self.spill_blocks = int(spill_blocks)
        # hash -> (tokens tuple, opaque payload); OrderedDict end = MRU.
        self._host: "OrderedDict[int, Tuple[Tuple[int, ...], Any]]" = \
            OrderedDict()
        self._spill: "OrderedDict[int, Tuple[Tuple[int, ...], Any]]" = \
            OrderedDict()
        self._pins: Dict[int, int] = {}
        # Device-tier mirror (membership only; payloads live in the pool).
        self._device: Dict[int, None] = {}
        # Hashes freed on device and awaiting an async device->host copy.
        self._pending: "OrderedDict[int, None]" = OrderedDict()
        self._advert: Deque[Tuple[int, str, str, int]] = \
            deque(maxlen=max(16, int(advert_capacity)))
        self._seq = 0
        self._metrics = metrics
        self.hits = {TIER_HOST: 0, TIER_SPILL: 0}
        self.misses = 0
        self.demotions = 0
        self.promotions = 0
        self.evictions = 0
        self.stale_drops = 0
        if metrics is not None:
            _describe_tier_metrics(metrics)
            metrics.set_gauge("tpu_kv_tier_capacity_blocks",
                              float(self.host_blocks),
                              {"tier": TIER_HOST})
            metrics.set_gauge("tpu_kv_tier_capacity_blocks",
                              float(self.spill_blocks),
                              {"tier": TIER_SPILL})

    # ---------------------------------------------------------- advert log

    def _record(self, op: str, tier: str, h: int) -> None:
        self._seq += 1
        self._advert.append((self._seq, op, tier, h))

    @property
    def advert_seq(self) -> int:
        return self._seq

    def advert_since(self, seq: int) -> Dict[str, Any]:
        """Delta of membership changes after ``seq``, or a snapshot.

        Returns ``{"seq", "reset", "add": [[hash, tier], ...],
        "del": [hash, ...]}``.  A reader that fell out of the bounded
        log window (or asks from seq 0) gets ``reset: True`` with the
        full residency listing across all three tiers.
        """
        if seq >= self._seq:
            return {"seq": self._seq, "reset": False, "add": [], "del": []}
        oldest = self._advert[0][0] if self._advert else self._seq + 1
        if seq + 1 < oldest:
            add = ([[h, TIER_DEVICE] for h in self._device]
                   + [[h, TIER_HOST] for h in self._host]
                   + [[h, TIER_SPILL] for h in self._spill])
            return {"seq": self._seq, "reset": True, "add": add, "del": []}
        add: List[List[Any]] = []
        dels: List[int] = []
        for s, op, tier, h in self._advert:
            if s <= seq:
                continue
            if op == "add":
                add.append([h, tier])
            else:
                dels.append(h)
        return {"seq": self._seq, "reset": False, "add": add, "del": dels}

    # ------------------------------------------------------- device mirror

    def note_device(self, h: int, present: bool) -> None:
        """Mirror device-pool membership (called from allocator hooks).

        A block registered on device no longer needs a pending demotion
        copy; a block evicted from device stays wherever the hierarchy
        already holds it.
        """
        if present:
            if h not in self._device:
                self._device[h] = None
                self._record("add", TIER_DEVICE, h)
        else:
            if h in self._device:
                del self._device[h]
                self._record("del", TIER_DEVICE, h)
            self._pending.pop(h, None)

    def note_freed(self, h: int) -> None:
        """Queue a device-resident block for asynchronous demotion.

        Called when the last sequence reference drops; the engine's step
        pump later copies the block host-ward (bounded per step) while
        it is still resident in the pool.
        """
        if h in self._host or h in self._spill:
            return
        self._pending[h] = None
        self._pending.move_to_end(h)

    def pop_pending(self) -> Optional[int]:
        """Next hash awaiting a device->host copy (FIFO), or None."""
        if not self._pending:
            return None
        h, _ = self._pending.popitem(last=False)
        return h

    def pending_count(self) -> int:
        return len(self._pending)

    # -------------------------------------------------------------- tiers

    def admit(self, h: int, tokens: Iterable[int], payload: Any) -> bool:
        """Land a block in the host tier, demoting under pressure.

        Returns False when the host tier is disabled or full of pinned
        entries (the block is simply not kept).
        """
        if self.host_blocks <= 0:
            return False
        tokens = tuple(tokens)
        self._pending.pop(h, None)
        if h in self._host:
            self._host.move_to_end(h)
            return True
        if h in self._spill:
            # Re-admission from spill is a promotion within the store.
            del self._spill[h]
            self._record("del", TIER_SPILL, h)
        self._host[h] = (tokens, payload)
        self._record("add", TIER_HOST, h)
        self._evict_pressure()
        if h not in self._host:
            return False
        self._gauge()
        return True

    def _evict_pressure(self) -> None:
        while len(self._host) > self.host_blocks:
            victim = self._lru_unpinned(self._host)
            if victim is None:
                # Everything pinned: shed the newest admit instead of
                # blocking (callers treat a failed admit as a drop).
                victim = next(reversed(self._host))
            toks, payload = self._host.pop(victim)
            self._record("del", TIER_HOST, victim)
            if self.spill_blocks > 0:
                self._spill[victim] = (toks, payload)
                self._spill.move_to_end(victim)
                self._record("add", TIER_SPILL, victim)
                self.demotions += 1
                if self._metrics is not None:
                    self._metrics.inc("tpu_kv_tier_demotions_total",
                                      {"src": TIER_HOST, "dst": TIER_SPILL})
            else:
                self.evictions += 1
                if self._metrics is not None:
                    self._metrics.inc("tpu_kv_tier_evictions_total",
                                      {"tier": TIER_HOST})
        while len(self._spill) > self.spill_blocks:
            victim = self._lru_unpinned(self._spill)
            if victim is None:
                victim = next(reversed(self._spill))
            self._spill.pop(victim)
            self._record("del", TIER_SPILL, victim)
            self.evictions += 1
            if self._metrics is not None:
                self._metrics.inc("tpu_kv_tier_evictions_total",
                                  {"tier": TIER_SPILL})

    def _lru_unpinned(self, tier: "OrderedDict") -> Optional[int]:
        for h in tier:
            if self._pins.get(h, 0) <= 0:
                return h
        return None

    def checkout(self, h: int, tokens: Iterable[int]) -> Optional[Any]:
        """Content-verified read: the payload for ``h``, or None.

        The caller supplies the exact tokens it hashed; a stored entry
        whose tokens differ is dropped (counted as a stale drop) rather
        than served — a block served under hash H must contain exactly
        the tokens that hash to H.  A spill hit is promoted to the host
        tier on its way out.
        """
        tokens = tuple(tokens)
        for tier_name, tier in ((TIER_HOST, self._host),
                                (TIER_SPILL, self._spill)):
            entry = tier.get(h)
            if entry is None:
                continue
            stored_tokens, payload = entry
            if stored_tokens != tokens:
                del tier[h]
                self._record("del", tier_name, h)
                self.stale_drops += 1
                if self._metrics is not None:
                    self._metrics.inc("tpu_kv_tier_stale_drops_total")
                self._gauge()
                return None
            self.hits[tier_name] += 1
            if self._metrics is not None:
                self._metrics.inc("tpu_kv_tier_hits_total",
                                  {"tier": tier_name})
            if tier_name == TIER_SPILL:
                del self._spill[h]
                self._record("del", TIER_SPILL, h)
                self._host[h] = (stored_tokens, payload)
                self._record("add", TIER_HOST, h)
                self.promotions += 1
                if self._metrics is not None:
                    self._metrics.inc("tpu_kv_tier_promotions_total",
                                      {"src": TIER_SPILL})
                self._evict_pressure()
            else:
                self._host.move_to_end(h)
            self._gauge()
            return payload
        self.misses += 1
        if self._metrics is not None:
            self._metrics.inc("tpu_kv_tier_misses_total")
        return None

    def pin(self, h: int) -> None:
        """Exclude ``h`` from tier eviction until ``unpin``."""
        self._pins[h] = self._pins.get(h, 0) + 1

    def unpin(self, h: int) -> None:
        n = self._pins.get(h, 0) - 1
        if n <= 0:
            self._pins.pop(h, None)
        else:
            self._pins[h] = n

    def tier_of(self, h: int) -> Optional[str]:
        if h in self._device:
            return TIER_DEVICE
        if h in self._host:
            return TIER_HOST
        if h in self._spill:
            return TIER_SPILL
        return None

    def contains(self, h: int) -> bool:
        return h in self._host or h in self._spill

    def discard(self, h: int) -> int:
        """Drop ``h`` from every tier; returns how many tier copies
        actually left (0 = the hash was not resident)."""
        n = 0
        if self._host.pop(h, None) is not None:
            self._record("del", TIER_HOST, h)
            n += 1
        if self._spill.pop(h, None) is not None:
            self._record("del", TIER_SPILL, h)
            n += 1
        self._pending.pop(h, None)
        self._gauge()
        return n

    def _gauge(self) -> None:
        if self._metrics is None:
            return
        self._metrics.set_gauge("tpu_kv_tier_blocks",
                                float(len(self._host)),
                                {"tier": TIER_HOST})
        self._metrics.set_gauge("tpu_kv_tier_blocks",
                                float(len(self._spill)),
                                {"tier": TIER_SPILL})

    def stats(self) -> Dict[str, Any]:
        return {
            "host_blocks_used": len(self._host),
            "host_blocks_total": self.host_blocks,
            "spill_blocks_used": len(self._spill),
            "spill_blocks_total": self.spill_blocks,
            "pending_demotions": len(self._pending),
            "tier_hits_host": self.hits[TIER_HOST],
            "tier_hits_spill": self.hits[TIER_SPILL],
            "tier_misses": self.misses,
            "tier_demotions": self.demotions,
            "tier_promotions": self.promotions,
            "tier_evictions": self.evictions,
            "tier_stale_drops": self.stale_drops,
            "advert_seq": self._seq,
        }


class Session:
    """One gateway session: the KV chain a returning user resumes from."""

    __slots__ = ("sid", "hashes", "ntokens", "backend", "last_seen")

    def __init__(self, sid: str, hashes: Tuple[int, ...], ntokens: int,
                 backend: str, last_seen: float):
        self.sid = sid
        self.hashes = hashes
        self.ntokens = ntokens
        self.backend = backend
        self.last_seen = last_seen


class SessionTable:
    """Bounded session-id → block-hash-chain table with TTL eviction.

    ``lookup`` returns a live session without refreshing its TTL;
    ``touch`` upserts after a successful forward and refreshes it.
    Capacity overflow evicts the least-recently-touched session.
    """

    def __init__(self, capacity: int = 1024, ttl: float = 600.0, *,
                 clock: Optional[Callable[[], float]] = None):
        self.capacity = max(1, int(capacity))
        self.ttl = float(ttl)
        self._clock = clock or time.monotonic
        self._sessions: "OrderedDict[str, Session]" = OrderedDict()
        self.resumes = 0
        self.expired = 0
        self.evicted = 0

    def lookup(self, sid: str) -> Optional[Session]:
        sess = self._sessions.get(sid)
        if sess is None:
            return None
        if self.ttl > 0 and self._clock() - sess.last_seen > self.ttl:
            del self._sessions[sid]
            self.expired += 1
            return None
        self.resumes += 1
        return sess

    def touch(self, sid: str, hashes: Iterable[int], ntokens: int,
              backend: str) -> Session:
        now = self._clock()
        sess = self._sessions.get(sid)
        if sess is None:
            sess = Session(sid, tuple(hashes), int(ntokens), backend, now)
            self._sessions[sid] = sess
        else:
            sess.hashes = tuple(hashes)
            sess.ntokens = int(ntokens)
            sess.backend = backend
            sess.last_seen = now
            self._sessions.move_to_end(sid)
        while len(self._sessions) > self.capacity:
            self._sessions.popitem(last=False)
            self.evicted += 1
        return sess

    def sweep(self) -> int:
        """Drop sessions past their TTL; returns how many went."""
        if self.ttl <= 0:
            return 0
        now = self._clock()
        dead = [sid for sid, s in self._sessions.items()
                if now - s.last_seen > self.ttl]
        for sid in dead:
            del self._sessions[sid]
        self.expired += len(dead)
        return len(dead)

    def forget_backend(self, service: str) -> int:
        """Detach sessions pinned to a dead backend (chain kept — the
        blocks may still be resident elsewhere in the fleet)."""
        n = 0
        for sess in self._sessions.values():
            if sess.backend == service:
                sess.backend = ""
                n += 1
        return n

    def __len__(self) -> int:
        return len(self._sessions)

    def stats(self) -> Dict[str, Any]:
        return {
            "sessions": len(self._sessions),
            "session_capacity": self.capacity,
            "session_ttl_seconds": self.ttl,
            "session_resumes": self.resumes,
            "session_expired": self.expired,
            "session_evicted": self.evicted,
        }


class FleetKvIndex:
    """Fleet-wide content-addressed residency: backend → {hash: tier}.

    Built from backend advert deltas (``KvTierStore.advert_since``
    payloads relayed through ``/v1/kv/advert``).  Exact, not a shadow:
    entries leave when the owning replica adverts a ``del`` or the
    backend itself is dropped, so a stale entry cannot direct a fleet
    fetch at an evicted block.  Size is bounded by the fleet's actual
    block capacity (each replica adverts at most device+host+spill
    blocks), so no separate cap is needed.
    """

    def __init__(self):
        self._res: Dict[str, Dict[int, str]] = {}
        self._seq: Dict[str, int] = {}

    def seq(self, service: str) -> int:
        return self._seq.get(service, 0)

    def needs_sync(self, service: str, advertised_seq: int) -> bool:
        return int(advertised_seq) > self._seq.get(service, 0)

    def apply(self, service: str, doc: Dict[str, Any]) -> None:
        """Fold one ``advert_since`` payload into the index."""
        res = self._res.setdefault(service, {})
        if doc.get("reset"):
            res.clear()
        for item in doc.get("add", []):
            h, tier = item[0], item[1]
            res[int(h)] = str(tier)
        for h in doc.get("del", []):
            res.pop(int(h), None)
        self._seq[service] = max(self._seq.get(service, 0),
                                 int(doc.get("seq", 0)))

    def resident_depth(self, service: str, hashes: Iterable[int]) -> int:
        """Leading blocks of ``hashes`` resident on ``service``, any tier."""
        res = self._res.get(service)
        if not res:
            return 0
        depth = 0
        for h in hashes:
            if h not in res:
                break
            depth += 1
        return depth

    def best_source(self, hashes, exclude: Iterable[str] = ()
                    ) -> Tuple[Optional[str], int]:
        """Backend holding the deepest prefix of ``hashes``; ties break
        lexicographically so placement stays deterministic."""
        hashes = list(hashes)
        skip = set(exclude)
        best: Optional[str] = None
        best_depth = 0
        for service in sorted(self._res):
            if service in skip:
                continue
            depth = self.resident_depth(service, hashes)
            if depth > best_depth:
                best, best_depth = service, depth
        return best, best_depth

    def drop_backend(self, service: str) -> int:
        """Forget a replica wholesale (evicted / failed health checks)."""
        dropped = len(self._res.pop(service, {}))
        self._seq.pop(service, None)
        return dropped

    def size(self, service: Optional[str] = None) -> int:
        if service is not None:
            return len(self._res.get(service, {}))
        return sum(len(r) for r in self._res.values())

    def stats(self) -> Dict[str, Any]:
        return {svc: {"blocks": len(res), "seq": self._seq.get(svc, 0)}
                for svc, res in self._res.items()}
