"""Continuous batching over a paged KV cache with prefix reuse.

Extends ServeEngine with vLLM-style memory management (see
serve/paged_kv.py): a shared physical block pool replaces the per-slot
``max_len`` cache rows, so HBM is sized by LIVE tokens instead of
``slots * max_len``, and block-aligned prompt prefixes are shared across
requests (system prompts, few-shot preambles prefill once).

Supports the same model families as the dense engine (Llama and
Mixtral — the MoE FFN is orthogonal to the cache layout since both run
through forward_with_cache's kv_update strategy).

Scheduling changes vs the dense engine:
- admission additionally requires enough free blocks for the prompt plus
  one decode block; otherwise the request waits in queue (paged engines
  admit by memory, not just by slot);
- each decode step that crosses a block boundary appends a block to the
  slot's table; if the pool is exhausted mid-decode the engine finishes
  the request with ``finish_reason="preempted"`` (the caller may resubmit
  — with the prefix cache warm, its re-prefill is nearly free);
- on finish, the request's blocks are refcount-released; full prompt
  blocks stay published in the prefix cache until cannibalized.
"""

from __future__ import annotations

import base64
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kuberay_tpu.models.llama import LlamaConfig
from kuberay_tpu.serve.engine import Request, ServeEngine, _bucket
from kuberay_tpu.serve.kv_tiers import KvTierStore
from kuberay_tpu.serve.paged_kv import (
    BlockAllocator,
    init_paged_cache,
    make_paged_forward,
)


class PagedServeEngine(ServeEngine):
    USES_BASE_FORWARD = False      # all kernels route through _paged_fwd

    def __init__(self, cfg: LlamaConfig, params: Dict[str, Any],
                 max_slots: int = 8, max_len: int = 2048,
                 num_blocks: int = 0, block_size: int = 16,
                 rng_seed: int = 0, decode_impl: str = "auto",
                 prefill_chunk: int = 0, speculative: int = 0,
                 kv_quant: str = "none", mesh=None,
                 weight_quant: str = "none",
                 donate_params: bool = False,
                 metrics=None, tracer=None, clock=None,
                 host_blocks: int = 0, spill_blocks: int = 0):
        # Default pool = the dense engine's footprint; callers shrink it
        # to realize the memory win (e.g. slots * expected_len).
        num_blocks = num_blocks or (max_slots * max_len) // block_size
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_blocks = (max_len + block_size - 1) // block_size
        from kuberay_tpu.models.mixtral import MixtralConfig
        base = None
        # Prefix sharing is sound for Mixtral too now that serving prefill
        # routes droplessly (kv_cache.forward_with_cache_mixtral): each
        # token's experts depend only on its own hidden state, so running
        # just the un-cached suffix reproduces exactly what full prefill
        # would have written.  (The old capacity-routed prefill was not
        # reuse-invariant and forced sharing off for MoE.)
        self._share_prefixes = True
        if isinstance(cfg, MixtralConfig):
            from kuberay_tpu.serve.kv_cache import forward_with_cache_mixtral
            base = forward_with_cache_mixtral
        if kv_quant == "int8":
            from kuberay_tpu.serve.paged_kv import make_paged_quant_forward
            self._paged_fwd = make_paged_quant_forward(
                block_size, base_forward=base, decode_impl=decode_impl,
                mesh=mesh)
        else:
            self._paged_fwd = make_paged_forward(
                block_size, base_forward=base, decode_impl=decode_impl,
                mesh=mesh)
        # super().__init__ jits self._prefill_impl/_decode_impl, which
        # resolve to the paged overrides below, and builds the cache via
        # the _init_cache hook (sharded over the mesh when given).
        super().__init__(cfg, params, max_slots=max_slots, max_len=max_len,
                         rng_seed=rng_seed, prefill_chunk=prefill_chunk,
                         speculative=speculative, kv_quant=kv_quant,
                         mesh=mesh, weight_quant=weight_quant,
                         donate_params=donate_params, metrics=metrics,
                         tracer=tracer, clock=clock)
        if weight_quant == "int8":
            # Paged kernels route through _paged_fwd (USES_BASE_FORWARD
            # False skipped the base wrap): dequantize outermost here.
            from kuberay_tpu.serve.weight_quant import (
                make_weight_dequant_forward,
            )
            self._paged_fwd = make_weight_dequant_forward(self._paged_fwd)
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.tables = np.zeros((max_slots, self.max_blocks), dtype=np.int32)
        self.owned: List[List[int]] = [[] for _ in range(max_slots)]
        self._wait_state = None        # (request id, num_free) at last block
        # Optional host/spill tiers behind the device pool: blocks freed
        # off-device demote asynchronously (step pump), admissions
        # promote tier-resident prefix blocks back instead of
        # recomputing them (serve/kv_tiers.py).
        self.tiers: Optional[KvTierStore] = None
        self.tier_fetch_blocks = 0
        self.tier_demoted_blocks = 0
        if host_blocks > 0 or spill_blocks > 0:
            if kv_quant != "none":
                raise ValueError(
                    "KV tiering requires kv_quant='none' (tier payloads "
                    "ride the float32 export wire format)")
            self.tiers = KvTierStore(host_blocks, spill_blocks,
                                     metrics=metrics)
            self.allocator.on_register = self._on_device_register
            self.allocator.on_evict = self._on_device_evict

    def _init_cache(self):
        return init_paged_cache(self.cfg, self.num_blocks, self.block_size,
                                quant=self.kv_quant)

    def _cache_sharding_tree(self, mesh):
        from kuberay_tpu.serve.sharding import paged_cache_shardings
        return paged_cache_shardings(mesh, self.kv_quant)

    # ------------------------------------------------------------------
    # jitted kernels (paged signatures)
    # ------------------------------------------------------------------

    def _prefill_impl(self, params, cache, tokens, tables, slot, start,
                      real_len, key, temperature, prompt_len,
                      filtered=False):
        """Prefill ``real_len`` NEW tokens of one request at cache offset
        ``start`` (start > 0 when a prefix was served from cache)."""
        B = self.max_slots
        row = jnp.zeros((B, prompt_len), dtype=jnp.int32).at[slot].set(tokens)
        starts = jnp.zeros((B,), jnp.int32).at[slot].set(start)
        write_mask = jax.nn.one_hot(slot, B, dtype=jnp.float32)
        token_mask = (write_mask[:, None] *
                      (jnp.arange(prompt_len)[None, :] < real_len))
        logits, new_cache = self._paged_fwd(
            self.cfg, params, row, cache, tables, starts, write_mask,
            token_mask=token_mask)
        last = logits[slot, real_len - 1]
        sample = self._sample if filtered else self._sample_plain
        tok = sample(last, key, temperature)
        return tok, new_cache

    def _decode_impl(self, params, cache, tokens, tables, lens, key,
                     temperatures, active_mask, filtered=False):
        logits, new_cache = self._paged_fwd(
            self.cfg, params, tokens[:, None], cache, tables, lens,
            active_mask, token_mask=active_mask[:, None])
        keys = jax.random.split(key, self.max_slots)
        sample = self._sample if filtered else self._sample_plain
        toks = jax.vmap(sample)(logits[:, 0], keys, temperatures)
        return toks, new_cache

    def _verify_impl(self, params, cache, tokens, tables, lens, ntok, key,
                     temperatures, active_mask, filtered=False):
        """Speculative verify over the block-table path.  The per-row
        ``ntok`` write gate is what makes this safe: a position past a
        slot's allocated blocks would resolve through the zero-filled
        table tail into block 0 — ANOTHER request's physical block
        (_build_drafts caps drafts to allocated capacity via
        _extra_draft_cap, and only real tokens write)."""
        T = tokens.shape[1]
        token_mask = (active_mask[:, None] *
                      (jnp.arange(T)[None, :] < ntok[:, None]))
        logits, new_cache = self._paged_fwd(
            self.cfg, params, tokens, cache, tables, lens, active_mask,
            token_mask=token_mask)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        keys = jax.random.split(key, self.max_slots)
        sample = self._sample if filtered else self._sample_plain
        sampled0 = jax.vmap(sample)(logits[:, 0], keys, temperatures)
        return greedy, sampled0, new_cache

    def _verify_device(self, toks, ntok, sub, temps, mask):
        greedy, sampled0, self.cache = self._verify(
            self.params, self.cache, jnp.asarray(toks),
            jnp.asarray(self.tables), jnp.asarray(self.lens),
            jnp.asarray(ntok), sub, jnp.asarray(temps), jnp.asarray(mask),
            filtered=self._filters_on(temps))
        return greedy, sampled0

    def _extra_draft_cap(self, slot: int) -> int:
        """Drafts may only extend into ALLOCATED blocks: positions
        lens..lens+cap must stay below the slot's block capacity
        (_decode_all grows headroom best-effort first; a full pool just
        shrinks the draft instead of corrupting the pool)."""
        capacity = len(self.owned[slot]) * self.block_size
        return capacity - int(self.lens[slot]) - 1

    # ------------------------------------------------------------------
    # block bookkeeping
    # ------------------------------------------------------------------

    def _blocks_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.block_size - 1) // self.block_size

    def _grow(self, slot: int, n_blocks: int) -> bool:
        """Append n fresh blocks to a slot's table; all-or-nothing."""
        got: List[int] = []
        for _ in range(n_blocks):
            bid = self.allocator.allocate()
            if bid is None:
                for b in got:
                    self.allocator.free(b)
                return False
            got.append(bid)
        base = len(self.owned[slot])
        self.owned[slot].extend(got)
        self.tables[slot, base:base + len(got)] = got
        return True

    def _release(self, slot: int):
        for bid in self.owned[slot]:
            self.allocator.free(bid)
            if self.tiers is not None and self.allocator.refcount[bid] == 0:
                h = self.allocator.hash_of(bid)
                if h is not None:
                    # Last reference dropped: queue the (still pool-
                    # resident) block for an async device->host copy.
                    self.tiers.note_freed(h)
        self.owned[slot] = []
        self.tables[slot] = 0

    def _shrink_headroom(self, slot: int) -> None:
        """Return unwritten draft-headroom tail blocks (beyond what
        lens+1 needs) to the pool.  Tail blocks are always slot-private
        (prefix-shared blocks sit at the front of ``owned``), and KV
        past ``lens`` is semantically dead, so freeing is safe."""
        keep = self._blocks_needed(int(self.lens[slot]) + 1)
        while len(self.owned[slot]) > keep:
            bid = self.owned[slot].pop()
            self.tables[slot, len(self.owned[slot])] = 0
            self.allocator.free(bid)

    # ------------------------------------------------------------------
    # tier hierarchy (device -> host -> spill; serve/kv_tiers.py)
    # ------------------------------------------------------------------

    def _on_device_register(self, h: int) -> None:
        self.tiers.note_device(h, True)

    def _on_device_evict(self, h: int) -> None:
        # The pool slot is being cannibalized; the hash leaves the device
        # tier.  Host/spill copies (if the pump got to them) survive.
        self.tiers.note_device(h, False)

    def step(self):
        out = super().step()
        if self.tiers is not None:
            self._pump_demotions()
        return out

    def _pump_demotions(self, limit: int = 4) -> int:
        """Copy up to ``limit`` freed blocks device->host per step.

        Bounded so demotion bandwidth never stalls the decode loop; a
        block evicted from the pool before its turn is simply lost to
        the hierarchy (next miss recomputes it).  Content is re-read
        from the allocator at copy time, so a racing eviction or
        re-registration can never demote stale bytes under a hash.
        """
        bs = self.block_size
        done = 0
        while done < limit:
            h = self.tiers.pop_pending()
            if h is None:
                break
            entry = self.allocator.lookup_block(h)
            if entry is None:
                continue               # evicted before the copy ran
            bid, toks = entry
            sl = slice(bid * bs, (bid + 1) * bs)
            k = np.asarray(self.cache["k"][:, :, sl, :], np.float32)
            v = np.asarray(self.cache["v"][:, :, sl, :], np.float32)
            if self.tiers.admit(h, toks, (k, v)):
                self.tier_demoted_blocks += 1
                if self.metrics is not None:
                    self.metrics.inc("tpu_kv_tier_demotions_total",
                                     {"src": "device", "dst": "host"})
            done += 1
        return done

    def _promote_from_tiers(self, req: Request) -> int:
        """Import the tier-resident run extending the device-resident
        prefix back into the pool, so admission's match_prefix serves it
        without recompute.  Records a ``tier-fetch`` span on the request
        trace when any block moved."""
        tokens = req.prompt_tokens
        bs = self.block_size
        t0 = self._now()
        resident = self.allocator.resident_prefix_blocks(tokens)
        hashes = self.allocator.block_hashes(tokens)
        promoted: List[tuple] = []         # (block id, (k, v))
        for i in range(resident, len(hashes)):
            toks = tuple(tokens[i * bs:(i + 1) * bs])
            payload = self.tiers.checkout(hashes[i], toks)
            if payload is None:
                break
            bid = self.allocator.import_block(hashes[i], toks)
            if bid is None:
                break                      # resident after all / pool full
            promoted.append((bid, payload))
        if not promoted:
            return 0
        pool_dtype = self.cache["k"].dtype
        idx = np.concatenate([np.arange(bid * bs, (bid + 1) * bs)
                              for bid, _ in promoted])
        k_all = np.concatenate([p[0] for _, p in promoted],
                               axis=2).astype(pool_dtype)
        v_all = np.concatenate([p[1] for _, p in promoted],
                               axis=2).astype(pool_dtype)
        self.cache["k"] = self.cache["k"].at[:, :, idx, :].set(k_all)
        self.cache["v"] = self.cache["v"].at[:, :, idx, :].set(v_all)
        for bid, _ in promoted:
            self.allocator.free(bid)       # refcount-0 cached, like import
        self.tier_fetch_blocks += len(promoted)
        if self.metrics is not None:
            self.metrics.inc("tpu_kv_tier_promotions_total",
                             {"src": "host"}, value=len(promoted))
        if req.trace is not None:
            self._tracer.record_span(
                req.trace, "tier-fetch", t0, self._now(),
                blocks=len(promoted))
        return len(promoted)

    def kv_advert(self, since: int = 0) -> Dict[str, Any]:
        """Residency advert for the fleet index (see KvTierStore)."""
        if self.tiers is None:
            return {"seq": 0, "reset": False, "add": [], "del": []}
        return self.tiers.advert_since(int(since))

    # ------------------------------------------------------------------
    # scheduling overrides
    # ------------------------------------------------------------------

    def _reserve(self, req: Request, slot: int):
        """Memory admission shared by whole-prompt and chunked prefill:
        prefix match + all-block reservation for prompt AND first decoded
        token.  Returns the number of tokens served from cache (int), or
        False when blocked on memory (request requeued), or None when the
        prompt can never fit (request cancelled)."""
        plen = len(req.prompt_tokens)
        # A prompt the pool can NEVER hold (even with every block free)
        # must be rejected, not retried — requeueing it would livelock
        # the engine and head-of-line-block everything behind it.
        if self._blocks_needed(plen + 1) > self.num_blocks:
            self._cancel(req)
            return None
        # While blocked on memory, nothing changes until some block is
        # freed — skip the O(plen) prefix re-match until num_free moves
        # (retried every engine step otherwise).
        if self._wait_state == (id(req), self.allocator.num_free):
            self.queue.insert(0, req)
            return False
        # Tier promotion first: blocks demoted to host/spill come back
        # into the pool so the match below serves them from cache (a
        # session resume pays a block copy instead of prefill).
        if self.tiers is not None and self._share_prefixes:
            self._promote_from_tiers(req)
        # Prefix cache: longest block-aligned cached prefix — but at
        # least one token must run through prefill to produce logits.
        cached = self.allocator.match_prefix(req.prompt_tokens) \
            if self._share_prefixes else []
        while cached and len(cached) * self.block_size >= plen:
            self.allocator.free(cached.pop())
        # Reserve capacity for the prompt AND the first decoded token
        # (prefill samples it; the first decode step writes it at
        # position plen) — actually allocating the headroom, instead of
        # merely checking free counts, keeps concurrent admissions in
        # one step() from consuming each other's spare and being
        # preempted after a single token.
        need = self._blocks_needed(plen + 1) - len(cached)
        if self.allocator.num_free < need:
            for b in cached:
                self.allocator.free(b)
            self._wait_state = (id(req), self.allocator.num_free)
            self.queue.insert(0, req)       # wait for memory, keep order
            return False
        self._wait_state = None
        self.owned[slot] = list(cached)
        self.tables[slot, :len(cached)] = cached
        ok = self._grow(slot, need)
        assert ok, "free-count check guaranteed allocation"
        self.allocator.count_prefix_stats(plen, len(cached))
        return len(cached) * self.block_size

    def _register_full_prompt(self, req: Request, slot: int) -> None:
        """Publish the prompt's full blocks for future requests.  Cached
        blocks re-register as no-ops.  Bucket/chunk padding past the
        prompt is never written at all — make_paged_forward's per-token
        write gate drops padding lanes (their table lookups could alias
        other requests' physical blocks), and only positions < lens are
        ever read — so shared content is exactly the real tokens."""
        plen = len(req.prompt_tokens)
        if self._share_prefixes:
            self.allocator.register_prefix(
                req.prompt_tokens[:plen - plen % self.block_size],
                self.owned[slot])

    def _admit(self, req: Request, slot: int):
        a0 = self._now()
        reserved = self._reserve(req, slot)
        if reserved is None:
            return True                     # cancelled; slot stays free
        if reserved is False:
            return False                    # blocked on memory
        ncached = reserved
        self._phase_mark(req.request_id, "admitted")
        if req.trace is not None:
            self._tracer.record_span(
                req.trace, "kv-alloc", a0, self._now(),
                cached_tokens=ncached, blocks=len(self.owned[slot]))
        plen = len(req.prompt_tokens)
        new_tokens = plen - ncached

        bucket = _bucket(new_tokens, self.max_len)
        padded = np.zeros(bucket, dtype=np.int32)
        padded[:new_tokens] = req.prompt_tokens[ncached:]
        self.key, sub = jax.random.split(self.key)
        tok = self._prefill_device(padded, slot, new_tokens, sub,
                                   self._samp(req), bucket,
                                   start_pos=ncached)
        self._register_full_prompt(req, slot)
        self._finalize_admit(req, slot, tok)
        return True

    # -- chunked prefill over the block-table path ----------------------

    def _begin_chunked(self, req: Request, slot: int):
        a0 = self._now()
        reserved = self._reserve(req, slot)
        if reserved is None:
            return None
        if reserved is False:
            return False
        self._phase_mark(req.request_id, "admitted")
        if req.trace is not None:
            self._tracer.record_span(
                req.trace, "kv-alloc", a0, self._now(),
                cached_tokens=reserved, blocks=len(self.owned[slot]))
        # Blocks are fully reserved; start past the cache-served prefix
        # (the in-flight offset is absolute into the prompt).
        self._inflight = (req, slot, reserved)
        self._chunk_step()
        return True

    def _prefill_chunk_call(self, req, slot, off, padded, real_len, sub):
        return self._prefill_device(padded, slot, real_len, sub,
                                    self._samp(req), self.prefill_chunk,
                                    start_pos=off)

    def _prefill_device(self, padded, slot, real_len, sub, temperature,
                        bucket, start_pos=0):
        """Paged prefill funnel (same signature as the dense engine's so
        the multi-host plan protocol covers both; ``start_pos`` is the
        absolute prompt offset — past a cache-served prefix or the chunk
        offset).  Block tables ride ``self.tables``, which the follower
        loop synchronizes from the broadcast plan."""
        tok, self.cache = self._prefill(
            self.params, self.cache, jnp.asarray(padded),
            jnp.asarray(self.tables), jnp.int32(slot),
            jnp.int32(start_pos), jnp.int32(real_len), sub,
            jnp.asarray(temperature, jnp.float32), prompt_len=bucket,
            filtered=self._filters_on(temperature))
        return tok

    def _chunk_finalize(self, req, slot, tok) -> None:
        self._register_full_prompt(req, slot)
        self._finalize_admit(req, slot, tok)

    def _decode_call(self, last, temps, mask, sub):
        toks, self.cache = self._decode(
            self.params, self.cache, jnp.asarray(last),
            jnp.asarray(self.tables), jnp.asarray(self.lens), sub,
            jnp.asarray(temps), jnp.asarray(mask),
            filtered=self._filters_on(temps))
        return toks

    def _decode_all(self):
        # Grow tables for slots whose next write crosses a block
        # boundary; preempt (finish early) when the pool is exhausted.
        # With speculation on, grow best-effort headroom for γ draft
        # positions too — failure just shrinks that slot's draft
        # (_extra_draft_cap), only the NEXT-token block is mandatory.
        # Pass 1 — MANDATORY next-token blocks for every slot.  Optional
        # draft headroom must never starve another slot's required block
        # (that would preempt a request the non-speculative engine keeps).
        for i, req in enumerate(self.active):
            if req is None:
                continue
            if self.lens[i] >= len(self.owned[i]) * self.block_size:
                if not self._grow(i, 1):
                    self._finish(i, "preempted")
        # Pass 2 — best-effort draft headroom for draft-eligible slots
        # (sampling/backed-off slots would hold blocks they never write).
        for i, req in enumerate(self.active):
            if req is None or self.speculative <= 0:
                continue
            if req.temperature > 0 or \
                    self._spec_miss[i] >= self.SPEC_MISS_LIMIT:
                # Slot became draft-ineligible (sampling / backed off):
                # give its idle headroom back so other slots' mandatory
                # blocks don't preempt while this one hoards capacity.
                self._shrink_headroom(i)
                continue
            want = int(self.lens[i]) + 1 + self.speculative
            while len(self.owned[i]) * self.block_size < want:
                if not self._grow(i, 1):
                    break
        if self.num_active:
            super()._decode_all()

    def _finish(self, slot: int, reason: str) -> None:
        super()._finish(slot, reason)
        self._release(slot)

    # ------------------------------------------------------------------
    # KV-block transfer seam (disaggregated prefill/decode serving)
    # ------------------------------------------------------------------
    #
    # A prefill-tier replica exports the registered prefix blocks of a
    # completed prompt; a decode-tier replica imports them into its own
    # BlockAllocator + pool, after which its normal admission path
    # (_reserve -> match_prefix) serves the prompt from cache and only
    # the partial tail block runs through prefill.  Blocks are keyed by
    # the chained block hashes (serve/prefix.py) — the same chain the
    # gateway's PrefixIndex shadows — so the transfer is content-
    # addressable and delta-only: blocks already resident on the
    # importer are skipped, never re-shipped.
    #
    # NOT thread-safe against a running engine loop: callers must
    # serialize with step() (ServeFrontend.call_engine does exactly
    # that) — an import racing a step would lose its pool write when the
    # step publishes its own new cache array.

    def resident_prefix_blocks(self, prompt_tokens: Sequence[int]) -> int:
        """Delta probe: longest cached block-aligned prefix (blocks)."""
        return self.allocator.resident_prefix_blocks(prompt_tokens)

    def export_kv_blocks(self, prompt_tokens: Sequence[int],
                         skip_blocks: int = 0,
                         max_blocks: int = 0) -> List[Dict[str, Any]]:
        """Read the registered prefix blocks of ``prompt_tokens`` out of
        the pool, skipping the first ``skip_blocks`` (already resident on
        the importer).  Returns wire records ``{index, hash, k, v}`` with
        float32 base64 payloads of shape [L, Hkv, block_size, D]; stops
        at the first block this replica no longer holds in ANY tier
        (device eviction falls back to the host/spill copy when tiering
        is on; past that, the importer prefills the remainder).
        ``max_blocks`` > 0 caps the record count: the importer still
        holds a contiguous resident prefix (skip + cap blocks) and
        recomputes the rest, so a transfer-cost budget never breaks the
        hash-chain invariant."""
        if self.kv_quant != "none":
            raise NotImplementedError(
                "KV-block export requires kv_quant='none' (int8 pools "
                "carry per-position scales the wire format omits)")
        bs = self.block_size
        # bid None = the block left the pool but a tier copy serves the
        # export (the chain stays contiguous across device eviction).
        picks: List[tuple] = []            # (index, hash, block id | None)
        tier_payloads: Dict[int, tuple] = {}
        for i, h in enumerate(self.allocator.block_hashes(prompt_tokens)):
            toks = tuple(prompt_tokens[i * bs:(i + 1) * bs])
            entry = self.allocator.lookup_block(h)
            if entry is not None and entry[1] == toks:
                bid: Optional[int] = entry[0]
            elif self.tiers is not None:
                payload = self.tiers.checkout(h, toks)
                if payload is None:
                    break
                bid = None
                tier_payloads[i] = payload
            else:
                break
            if i >= skip_blocks:
                picks.append((i, h, bid))
            if max_blocks > 0 and len(picks) >= max_blocks:
                break
        if not picks:
            return []
        # One gather per pool: only the exported positions leave the
        # device, never the whole pool.
        dev = [(i, h, bid) for i, h, bid in picks if bid is not None]
        k = v = None
        if dev:
            idx = np.concatenate([np.arange(bid * bs, (bid + 1) * bs)
                                  for _, _, bid in dev])
            k = np.asarray(self.cache["k"][:, :, idx, :], np.float32)
            v = np.asarray(self.cache["v"][:, :, idx, :], np.float32)
        dev_pos = {i: j for j, (i, _, _) in enumerate(dev)}
        out = []
        for i, h, bid in picks:
            if bid is not None:
                sl = slice(dev_pos[i] * bs, (dev_pos[i] + 1) * bs)
                kb, vb = k[:, :, sl, :], v[:, :, sl, :]
            else:
                kb, vb = tier_payloads[i]
            out.append({
                "index": i, "hash": h,
                "k": base64.b64encode(kb.tobytes()).decode(),
                "v": base64.b64encode(vb.tobytes()).decode(),
            })
        return out

    def import_kv_blocks(self, prompt_tokens: Sequence[int],
                         blocks: List[Dict[str, Any]]) -> Dict[str, int]:
        """Adopt shipped prefix blocks into this replica's pool.  Walks
        the prompt's hash chain from block 0: resident blocks count as
        ``skipped`` (the delta contract), shipped ones are allocated,
        written, and published refcount-0 cached; the walk stops at the
        first chain gap or pool exhaustion (a non-contiguous suffix is
        unusable — match_prefix only serves contiguous prefixes).
        Returns ``{"imported": n, "skipped": m}``."""
        if self.kv_quant != "none":
            raise NotImplementedError(
                "KV-block import requires kv_quant='none'")
        bs = self.block_size
        shape = (self.cfg.n_layers, self.cfg.n_kv_heads, bs,
                 self.cfg.head_dim)
        by_index = {int(b["index"]): b for b in blocks}
        imported = skipped = 0
        adopted: List[tuple] = []          # (block id, k array, v array)
        for i, h in enumerate(self.allocator.block_hashes(prompt_tokens)):
            toks = tuple(prompt_tokens[i * bs:(i + 1) * bs])
            entry = self.allocator.lookup_block(h)
            if entry is not None and entry[1] == toks:
                skipped += 1
                continue
            rec = by_index.get(i)
            if rec is None or rec.get("hash", h) != h:
                break
            try:
                k = np.frombuffer(base64.b64decode(rec["k"]),
                                  np.float32).reshape(shape)
                v = np.frombuffer(base64.b64decode(rec["v"]),
                                  np.float32).reshape(shape)
            except (KeyError, ValueError, TypeError):
                break                      # malformed payload: stop clean
            bid = self.allocator.import_block(h, toks)
            if bid is None:
                break                      # pool exhausted
            adopted.append((bid, k, v))
            imported += 1
        if adopted:
            pool_dtype = self.cache["k"].dtype
            idx = np.concatenate([np.arange(bid * bs, (bid + 1) * bs)
                                  for bid, _, _ in adopted])
            k_all = np.concatenate([k for _, k, _ in adopted],
                                   axis=2).astype(pool_dtype)
            v_all = np.concatenate([v for _, _, v in adopted],
                                   axis=2).astype(pool_dtype)
            self.cache["k"] = self.cache["k"].at[:, :, idx, :].set(k_all)
            self.cache["v"] = self.cache["v"].at[:, :, idx, :].set(v_all)
            # Content is in the pool: release to refcount-0 cached, the
            # same state a locally prefilled + finished prompt leaves.
            for bid, _, _ in adopted:
                self.allocator.free(bid)
        return {"imported": imported, "skipped": skipped}

    # ------------------------------------------------------------------

    @property
    def stats(self) -> Dict[str, Any]:
        a = self.allocator
        out = {
            **ServeEngine.stats.fget(self),
            "num_blocks": a.num_blocks,
            "free_blocks": a.num_free,
            "prefix_hit_tokens": a.prefix_hits,
            "prefix_query_tokens": a.prefix_queries,
        }
        if self.tiers is not None:
            out.update(self.tiers.stats())
            out["tier_fetch_blocks"] = self.tier_fetch_blocks
            out["tier_demoted_blocks"] = self.tier_demoted_blocks
        return out
