"""Serve-group failure detection: heartbeats + step watchdog.

A multi-host serve slice runs in lockstep (serve/multihost.py); a dead
follower leaves host 0 blocked inside a collective with **no in-process
way to unblock** — the recovery unit is the whole slice, exactly the
invariant the cluster controller already enforces for unhealthy slices
(reference: unhealthy multi-host groups deleted whole,
raycluster_controller.go:1269-1289).  What the serve layer must supply
is *detection + drain + surfacing*:

- every follower runs a :func:`heartbeat_loop` daemon thread beating a
  tiny TCP listener on host 0 (address from the same
  ``TPU_WORKER_HOSTNAMES`` env contract the engines already use);
- host 0's :class:`GroupMonitor` declares the group **degraded** when a
  follower misses beats (process death) or a device step exceeds the
  watchdog budget (hang inside a collective — the failure mode a dead
  peer actually produces);
- on degradation the serve frontend fails pending waiters immediately
  (no hanging clients), flips ``/healthz`` to 503, and reports the app
  ``DEGRADED`` to the coordinator so the TpuService controller sets the
  ``ServeGroupDegraded`` condition and prepares a replacement cluster —
  whole-slice replacement, never partial repair.

Single-host groups never degrade through this module (no peers, and a
stuck step without peers is a model bug, not a group failure).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from kuberay_tpu.utils.quantiles import quantile as _quantile

HEARTBEAT_INTERVAL = 1.0


class GroupMonitor:
    """Host-0 side: follower liveness + step watchdog.

    ``expected``: follower worker ids (1..n-1).  ``miss_timeout``: beats
    older than this mark the follower lost.  ``step_timeout``: a single
    device call running longer than this marks the group stuck (dead
    peer mid-collective).  Degradation is one-way; recovery is slice
    replacement, not rejoin.
    """

    # Adaptive-budget shape: once MIN_SAMPLES completed steps have been
    # observed, the budget becomes multiplier x the rolling p99 (floored
    # at miss_timeout) instead of the static cold-start default — a
    # model with legitimately long steps (big chunked-prefill batches)
    # raises its own budget, and a fast model gets far quicker hang
    # detection than any one-size constant.
    #
    # The feedback loop is bounded three ways (a slow-but-alive step
    # would otherwise enter the window, inflate p99, and ratchet the
    # budget upward without limit — each near-budget step buying the
    # next one a bigger allowance):
    # - samples are clamped to the budget that was in force when the
    #   step ran (a step can't teach the window more than it was given);
    # - the small-window p99 is interpolated, not a truncating index
    #   that collapses to the max sample;
    # - the adaptive budget is hard-capped at BUDGET_CAP_MULTIPLIER x
    #   step_timeout (the operator-set order of magnitude stays law).
    WINDOW = 256
    MIN_SAMPLES = 20
    BUDGET_CAP_MULTIPLIER = 2.0

    def __init__(self, expected: List[int], miss_timeout: float = 10.0,
                 step_timeout: float = 60.0,
                 on_degraded: Optional[Callable[[str], None]] = None,
                 grace: float = 30.0, compile_timeout: float = 900.0,
                 budget_multiplier: float = 20.0, clock=None):
        # Injectable monotonic clock (object with .now()) for the
        # timeout arithmetic — tests drive staleness/watchdog math with
        # a fake clock instead of real sleeps (the wire loops below stay
        # on real time regardless; they pace I/O, not verdicts).
        self._now = clock.now if clock is not None else time.monotonic
        self.expected = list(expected)
        self.miss_timeout = miss_timeout
        # Cold-start default only: used until the rolling window has
        # MIN_SAMPLES observations, then the adaptive budget takes over.
        self.step_timeout = step_timeout
        # Budget for steps flagged as compiling (first occurrence of a
        # program shape): XLA compilation of a large model can dwarf
        # step_timeout, and a false DEGRADED here would put the slice in
        # an infinite replace-recompile-replace loop.
        self.compile_timeout = compile_timeout
        self.budget_multiplier = budget_multiplier
        self.on_degraded = on_degraded
        self._lock = threading.Lock()
        now = self._now()
        # Followers get a startup grace: they begin beating only once
        # their engine is constructed (compile time included).
        self._last_beat: Dict[int, float] = {
            w: now + grace for w in self.expected}
        self._step_started: Optional[float] = None
        self._step_budget: float = step_timeout
        self._step_compiling: bool = False
        self._durations: List[float] = []     # rolling window (WINDOW)
        self._degraded: Optional[str] = None
        self._server: Optional[socket.socket] = None
        self._stop = threading.Event()

    # -- state ----------------------------------------------------------

    @property
    def degraded(self) -> Optional[str]:
        with self._lock:
            return self._degraded

    def _mark(self, reason: str) -> None:
        fire = False
        with self._lock:
            if self._degraded is None:
                self._degraded = reason
                fire = True
        if fire and self.on_degraded is not None:
            try:
                self.on_degraded(reason)
            except Exception:
                pass

    def mark_degraded(self, reason: str) -> None:
        """External degradation signal (e.g. a collective raised on the
        scheduling thread before any heartbeat missed)."""
        self._mark(reason)

    def beat(self, worker_id: int) -> None:
        with self._lock:
            # Only EXPECTED ids: a stray beat (misconfigured worker id,
            # stale process from a prior incarnation, any writer on the
            # unauthenticated port) must not create an entry that goes
            # stale and trips a bogus degradation.
            if worker_id in self._last_beat:
                self._last_beat[worker_id] = self._now()

    def current_step_budget(self) -> float:
        """The live (non-compile) step budget: adaptive once enough
        steps have been observed, the static cold-start default before
        that.  Never below miss_timeout — follower death is the
        heartbeat's job; the step watchdog exists for wedged-but-
        connected peers, where a few extra seconds is the right price
        for never degrading a slow-but-alive group.  Never above
        BUDGET_CAP_MULTIPLIER x step_timeout — the adaptive loop must
        not be able to ratchet itself arbitrarily high (see the class
        comment)."""
        with self._lock:
            samples = list(self._durations)
        if len(samples) < self.MIN_SAMPLES:
            return self.step_timeout
        p99 = _quantile(samples, 0.99)
        budget = min(self.budget_multiplier * p99,
                     self.BUDGET_CAP_MULTIPLIER * self.step_timeout)
        return max(self.miss_timeout, budget)

    def step_begin(self, compiling: bool = False) -> None:
        # Budget computed before taking the lock (current_step_budget
        # locks internally; threading.Lock is not reentrant).
        budget = (self.compile_timeout if compiling
                  else self.current_step_budget())
        with self._lock:
            self._step_budget = budget
            self._step_compiling = compiling
            self._step_started = self._now()

    def step_end(self) -> None:
        with self._lock:
            started = self._step_started
            budget = self._step_budget
            compiling = self._step_compiling
            self._step_started = None
            # Compile steps stay out of the distribution: one 10-minute
            # XLA compile would inflate p99 (and thus the budget) for
            # the next WINDOW steps.
            if started is None or compiling:
                return
            # Clamp at the budget that was in force while the step ran:
            # a long-but-allowed step must not teach the window a larger
            # tail than the watchdog had actually granted (the unbounded
            # feedback loop this clamp + the hard cap exist to prevent).
            dur = min(self._now() - started, budget)
            self._durations.append(dur)
            if len(self._durations) > self.WINDOW:
                del self._durations[:len(self._durations) - self.WINDOW]

    def check(self) -> Optional[str]:
        """One watchdog pass; returns the degradation reason (sticky)."""
        now = self._now()
        with self._lock:
            if self._degraded:
                return self._degraded
            stale = [w for w, t in self._last_beat.items()
                     if now - t > self.miss_timeout]
            started, budget = self._step_started, self._step_budget
        if stale:
            self._mark(f"follower(s) {sorted(stale)} missed heartbeats "
                       f"for >{self.miss_timeout:.0f}s")
        elif started is not None and now - started > budget:
            self._mark(f"device step stuck for >{budget:.0f}s "
                       "(peer dead mid-collective?)")
        return self.degraded

    def status(self) -> Dict[str, object]:
        now = self._now()
        with self._lock:
            ages = {str(w): round(max(0.0, now - t), 1)
                    for w, t in self._last_beat.items()}
            degraded = self._degraded
        return {"degraded": degraded, "beat_age_seconds": ages,
                "followers": self.expected,
                "step_budget_seconds": round(self.current_step_budget(),
                                             3)}

    # -- wire -----------------------------------------------------------

    def listen(self, host: str = "0.0.0.0", port: int = 0) -> int:
        """Start the heartbeat listener + watchdog thread; returns the
        bound port.  Protocol: followers hold one persistent connection
        and write a ``beat <worker_id>\\n`` line per interval."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(8)
        srv.settimeout(0.5)
        self._server = srv

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                threading.Thread(target=self._conn_loop, args=(conn,),
                                 daemon=True,
                                 name="group-health-conn").start()

        def watchdog_loop():
            while not self._stop.is_set():
                self.check()
                self._stop.wait(min(1.0, self.miss_timeout / 3))

        threading.Thread(target=accept_loop, daemon=True,
                         name="group-health-accept").start()
        threading.Thread(target=watchdog_loop, daemon=True,
                         name="group-health-watchdog").start()
        return srv.getsockname()[1]

    def _conn_loop(self, conn: socket.socket) -> None:
        conn.settimeout(self.miss_timeout)
        buf = b""
        try:
            while not self._stop.is_set():
                chunk = conn.recv(256)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    parts = line.decode(errors="replace").split()
                    if len(parts) == 2 and parts[0] == "beat":
                        try:
                            self.beat(int(parts[1]))
                        except ValueError:
                            pass
        except OSError:
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass


def heartbeat_loop(host: str, port: int, worker_id: int,
                   interval: float = HEARTBEAT_INTERVAL,
                   stop: Optional[threading.Event] = None) -> None:
    """Follower side: beat host 0 forever (daemon thread).  Connection
    failures retry — host 0 may restart its listener; a follower must
    not die because the monitor blinked (the monitor's job is to notice
    *us* dying, not vice versa)."""
    stop = stop or threading.Event()
    while not stop.is_set():
        try:
            with socket.create_connection((host, port), timeout=5) as s:
                while not stop.is_set():
                    s.sendall(f"beat {worker_id}\n".encode())
                    if stop.wait(interval):
                        return
        except OSError:
            if stop.wait(interval):
                return


def start_heartbeat(host: str, port: int, worker_id: int,
                    interval: float = HEARTBEAT_INTERVAL
                    ) -> threading.Event:
    """Spawn the follower heartbeat daemon; returns its stop event."""
    stop = threading.Event()
    threading.Thread(target=heartbeat_loop,
                     args=(host, port, worker_id, interval, stop),
                     daemon=True, name="group-health-beat").start()
    return stop
