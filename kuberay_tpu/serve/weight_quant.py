"""int8 weight quantization for serving (W8A16).

Weights dominate serving HBM and decode is bandwidth-bound: storing the
matmul weights as int8 with per-output-channel scales halves both the
resident footprint (a bigger model fits the chip) and the bytes each
decode step streams from HBM.  Activations stay bf16 — the dequantize
(convert + broadcast-multiply) feeds straight into each dot and XLA
fuses it into the matmul's operand read, so no bf16 weight copy is ever
materialized.

Scope: the layer matmul weights (attention projections, FFN, lm_head) —
the bulk of parameters.  Embeddings stay bf16 (they are read by gather,
not matmul: a fused dequant there buys little, and quantizing the
gather source would materialize a full dequantized table), as do the
tiny norm vectors.

Composes with everything: the wrapper has the forward signature
``(cfg, params, ...)`` shared by the dense forward, the kv-quant
wrapper, and the paged forwards, so it simply runs outermost and hands
a dequantized tree down the existing chain.  Under a tp mesh the
per-output-channel scale reduction follows the weight's sharding (one
collective at quantize time when the reduction axis is sharded).

Ref parity: vLLM's quantization support (the serving runtime role,
SURVEY.md §2.3); no reference counterpart in the operator itself.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

# Leaves quantized: matmul weights by name (everything else passes
# through untouched — norms, embed, biases).
# Covers llama (wq/wk/wv/wo + FFN + lm_head) and Mixtral's expert FFN
# (same w_gate/w_up/w_down names, layer+expert stacked).  The Mixtral
# ROUTER stays bf16 deliberately: it is tiny, and routing decisions are
# the most quantization-sensitive computation in an MoE.
_QUANT_LEAVES = frozenset({
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
})


def _quantize_leaf(w: jax.Array) -> Dict[str, jax.Array]:
    """Per-output-channel symmetric int8, scaled over the CONTRACTION
    axis only (w.ndim-2 in the ``x @ w`` layouts used throughout):
    layer/expert stack axes keep their own scales — one loud layer must
    not crush another layer's resolution."""
    axes = (w.ndim - 2,)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    return {"q8": q, "s8": scale.astype(jnp.float32)}


def _is_quant_leaf(obj: Any) -> bool:
    return isinstance(obj, dict) and set(obj) == {"q8", "s8"}


def quantize_weights(params: Dict[str, Any]) -> Dict[str, Any]:
    """Returns the params tree with matmul weights replaced by
    {"q8": int8, "s8": f32 per-channel} pairs.  Jit-compatible; run it
    once at engine construction (sharded inputs stay sharded)."""
    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if k in _QUANT_LEAVES and not isinstance(v, dict):
                out[k] = _quantize_leaf(v)
            else:
                out[k] = walk(v)
        return out
    return walk(params)


def dequantize_weights(params: Dict[str, Any],
                       dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Inverse transform, applied INSIDE the jitted forward: the
    convert*scale chain fuses into each consuming matmul."""
    def walk(node):
        if _is_quant_leaf(node):
            return (node["q8"].astype(dtype)
                    * node["s8"].astype(dtype))
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node
    return walk(params)


def make_weight_dequant_forward(base_forward):
    """Forward adapter: dequantize the weight tree, delegate down the
    existing chain (kv-quant wrapper, paged forward, base forward all
    share the ``(cfg, params, ...)`` head)."""
    def fwd(cfg, params, *args, **kwargs):
        return base_forward(cfg, dequantize_weights(params), *args,
                            **kwargs)
    return fwd


def quantization_error(params: Dict[str, Any]) -> float:
    """Max relative round-trip error over quantized leaves (diagnostic
    + tests): per-channel int8 should sit near 1/254 of the channel
    amplitude."""
    q = quantize_weights(params)
    d = dequantize_weights(q, dtype=jnp.float32)
    worst = 0.0
    flat_o, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_d = dict(jax.tree_util.tree_flatten_with_path(d)[0])
    for path, orig in flat_o:
        deq = flat_d.get(path)
        if deq is None or orig.shape != getattr(deq, "shape", None):
            continue
        amax = float(jnp.max(jnp.abs(orig.astype(jnp.float32))))
        if amax == 0:
            continue
        err = float(jnp.max(jnp.abs(orig.astype(jnp.float32) - deq)))
        worst = max(worst, err / amax)
    return worst
