"""Continuous-batching inference engine.

The serving payload a TpuService runs (BASELINE config #4: continuous
batching on v5e-16) — the role Ray Serve + vLLM play for the reference,
built TPU-first:

- fixed slot count + static-shape KV cache: exactly two compiled programs
  (prefill, decode) regardless of traffic;
- continuous batching: new requests prefill into free slots while existing
  slots keep decoding; no generation stalls behind a long prompt;
- prompt-length bucketing bounds prefill recompilation;
- greedy or temperature sampling per request.

Pure-Python scheduling around jitted steps: the host loop does bookkeeping
only; every FLOP is inside jit.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kuberay_tpu.models.llama import LlamaConfig
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.serve.kv_cache import (
    forward_with_cache,
    forward_with_cache_mixtral,
    init_kv_cache,
)
from kuberay_tpu.utils.metrics import SERVE_LATENCY_BUCKETS


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_tokens: List[int]
    max_new_tokens: int = 64
    temperature: float = 0.0          # 0 = greedy
    top_p: float = 1.0                # nucleus sampling (1 = off)
    top_k: int = 0                    # top-k sampling (0 = off)
    eos_token: Optional[int] = None
    # Additional stop tokens (any match ends generation, reason "eos").
    stop_token_ids: Optional[List[int]] = None
    # Distributed-trace context (obs.trace.TraceContext) minted by the
    # gateway and carried over the replica hop as ``traceparent``; the
    # engine attaches engine-queue / prefill / decode / kv-alloc child
    # spans to it.  None = untraced request.
    trace: Optional[Any] = None


@dataclasses.dataclass
class Response:
    request_id: str
    tokens: List[int]                 # generated tokens (no prompt)
    finish_reason: str = "length"     # length|eos|cancelled|preempted
    prompt_len: int = 0
    created: float = 0.0
    # Exact enqueue->first-token seconds (None for cancelled requests).
    # Flows through the serve HTTP surface as ``ttft_ms`` so gateway-side
    # clients and the traffic benchmark measure TTFT without streaming.
    ttft_s: Optional[float] = None


def _bucket(n: int, max_len: int = 2048) -> int:
    """Smallest power-of-two bucket >= n, capped at max_len."""
    b = 32
    while b < n and b < max_len:
        b *= 2
    return min(b, max_len)


def prompt_lookup_draft(hist: List[int], gamma: int, ngram: int = 3,
                        window: int = 4096) -> List[int]:
    """Prompt-lookup drafting: if the current suffix n-gram occurred
    earlier in the token history, propose the tokens that followed it.
    Free (no draft model), and highly effective on the repetitive spans
    (code, quotes, structured text) where speculation pays off.

    O(len(hist)) reference scan; the engine hot loop uses the
    incremental ``NgramIndex`` (same semantics, O(gamma) per draft)."""
    lo = max(0, len(hist) - window)
    for n in range(min(ngram, len(hist) - 1), 0, -1):
        pat = hist[-n:]
        for k in range(len(hist) - n - 1, lo - 1, -1):
            if hist[k:k + n] == pat:
                cont = hist[k + n:k + n + gamma]
                if cont:
                    return list(cont)
    return []


class NgramIndex:
    """Incremental n-gram -> latest-start-position index over one slot's
    token history.  ``extend`` amortizes to O(new tokens); ``draft`` is
    O(gamma) — replacing the per-step O(history) rescan in the decode
    host loop.  Matches ``prompt_lookup_draft`` exactly: longest n-gram
    first, latest occurrence wins, occurrences end strictly before the
    history's last position (so the suffix never matches itself)."""

    def __init__(self, ngram: int = 3, window: int = 4096):
        self.n_max = ngram
        self.window = window
        self.maps = {n: {} for n in range(1, ngram + 1)}
        self.indexed = 0         # history length already processed

    PRUNE_EVERY = 1024         # amortized out-of-window eviction cadence

    def extend(self, hist: List[int]) -> None:
        L = len(hist)
        for n, m in self.maps.items():
            # Previously covered k <= indexed-n-1; ascending order keeps
            # "latest occurrence wins".
            for k in range(max(0, self.indexed - n), L - n):
                m[tuple(hist[k:k + n])] = k
        # Evict entries whose latest occurrence fell behind the lookup
        # window — draft() already ignores them, so dropping them only
        # bounds memory (ADVICE r2: the maps otherwise grow with the
        # full history).  Amortized: one scan per PRUNE_EVERY tokens.
        if L // self.PRUNE_EVERY > self.indexed // self.PRUNE_EVERY:
            floor = L - self.window
            for m in self.maps.values():
                for key in [t for t, k in m.items() if k < floor]:
                    del m[key]
        self.indexed = L

    def draft(self, hist: List[int], gamma: int) -> List[int]:
        for n in range(min(self.n_max, len(hist) - 1), 0, -1):
            k = self.maps[n].get(tuple(hist[-n:]))
            # Latest-wins index: a latest occurrence older than the
            # window means no occurrence is within it (reference
            # semantics: fall through to a shorter n-gram).
            if k is not None and k >= len(hist) - self.window:
                return list(hist[k + n:k + n + gamma])
        return []


class ServeEngine:
    # Subclasses that route every jitted kernel through their own forward
    # (the paged engine's _paged_fwd) set this False so __init__ doesn't
    # wrap self._forward with DENSE-layout tp attention specs — wrong
    # against their cache layout if anything ever called it.
    USES_BASE_FORWARD = True

    SPEC_MISS_LIMIT = 3        # consecutive full-rejects before backoff
    SPEC_PROBE_EVERY = 8       # steps between probes while backed off
    # Batch-level gate: verify costs every ACTIVE slot a (γ+1)-token
    # forward, so one repetitive request must not tax the whole batch —
    # speculate only when at least this fraction of active slots drafted
    # (ADVICE r2: bounds the amplification a single slot can cause).
    SPEC_MIN_DRAFT_FRACTION = 0.25

    def __init__(self, cfg: LlamaConfig, params: Dict[str, Any],
                 max_slots: int = 8, max_len: int = 2048,
                 rng_seed: int = 0, prefill_chunk: int = 0,
                 speculative: int = 0, kv_quant: str = "none",
                 decode_impl: str = "auto", mesh=None,
                 weight_quant: str = "none",
                 donate_params: bool = False,
                 metrics=None, tracer=None, clock=None):
        self.cfg = cfg
        self.params = params
        # Request-phase latency decomposition: ``metrics`` is a
        # MetricsRegistry (utils/metrics.py); each finished request
        # observes tpu_serve_request_duration_seconds once per phase —
        # queue (enqueue -> admission), prefill (admission -> first
        # token), decode (first token -> finish) — so a p99 regression
        # points at the phase that moved, not just "the server is slow".
        self.metrics = metrics
        # Per-request tracing: requests carrying a TraceContext get
        # engine-queue / prefill / decode child spans recorded against
        # the gateway-minted trace.  ``clock`` (an object with .now())
        # makes phase timestamps and spans virtual-clock exact in sim.
        self._tracer = tracer if tracer is not None else NOOP_TRACER
        self._now = clock.now if clock is not None else time.time
        if metrics is not None:
            metrics.describe(
                "tpu_serve_request_duration_seconds",
                "Per-request wall time by phase (queue | prefill | decode)")
        self._req_phase_ts: Dict[str, Dict[str, float]] = {}
        # Tensor-parallel serving: a jax.sharding.Mesh with a "tp" axis.
        # Params/cache shard over it (serve/sharding.py) and every jitted
        # step runs SPMD; the host scheduling loop is unchanged.
        self.mesh = mesh
        self.max_slots = max_slots
        self.max_len = max_len
        # Chunked prefill (vLLM-style): >0 caps how many prompt tokens one
        # engine step may prefill, interleaving decode steps between
        # chunks so a long prompt never stalls other slots' generation —
        # and every prefill call shares ONE compiled shape (the chunk).
        self.prefill_chunk = prefill_chunk
        self._inflight = None        # (req, slot, offset) mid-chunking
        # Speculative decoding (greedy, prompt-lookup drafts): >0 sets the
        # draft length γ — one verify forward of T=γ+1 tokens can emit up
        # to γ+1 tokens for slots whose drafts hit.  Exact: greedy
        # longest-prefix acceptance reproduces sequential decoding.
        self.speculative = speculative
        self.spec_stats = {"drafted": 0, "accepted": 0, "verify_steps": 0}
        # Dynamic backoff: a slot whose last SPEC_MISS_LIMIT drafts were
        # fully rejected pauses drafting for SPEC_PROBE_EVERY steps, then
        # probes again (text can ENTER a repetitive regime later); any
        # acceptance re-arms it fully.  Bounds the worst case near
        # sequential cost instead of paying (γ+1)x forever.
        self._spec_miss = np.zeros(max_slots, dtype=np.int32)
        self._spec_cooldown = np.zeros(max_slots, dtype=np.int32)
        self._spec_index: List[Optional[NgramIndex]] = [None] * max_slots
        # Streaming hook: called as token_callback(request_id, [tokens])
        # the moment tokens are emitted (first prefill token, each decode
        # token, accepted speculative runs) — the serve frontend uses it
        # for chunked streaming responses.  Runs on the engine thread;
        # must be cheap and never raise.
        self.token_callback = None
        self.kv_quant = kv_quant
        # With a mesh the cache materializes sharded below (a flagship
        # cache does not fit one chip); without one, build it here.
        self.cache = self._init_cache() if mesh is None else None
        # Model dispatch: Llama-family vs Mixtral MoE share the cache
        # plumbing but differ in the FFN.
        from kuberay_tpu.models.mixtral import MixtralConfig
        if isinstance(cfg, MixtralConfig):
            self._forward = forward_with_cache_mixtral
        else:
            self._forward = forward_with_cache
        if kv_quant != "none" and self.USES_BASE_FORWARD:
            from kuberay_tpu.serve.kv_cache import make_quantized_forward
            # decode_impl is the operational escape hatch: "xla" routes
            # the int8 decode read around the Pallas kernel.  (Paged
            # engines bring their own quant forward — paged_kv.)
            self._forward = make_quantized_forward(self._forward,
                                                   decode_impl=decode_impl,
                                                   mesh=mesh)
        elif mesh is not None and self.USES_BASE_FORWARD:
            # Pallas kernels are invisible to the SPMD partitioner; route
            # attention through the shard_map wrapper so each chip runs
            # the stock kernel on its local head shard.
            from kuberay_tpu.serve.sharding import make_tp_attention
            base_fwd = self._forward
            tp_attn = make_tp_attention(mesh)

            def fwd(cfg_, params_, tokens_, cache_, start_, write_mask=None,
                    token_mask=None):
                return base_fwd(cfg_, params_, tokens_, cache_, start_,
                                write_mask, token_mask=token_mask,
                                attention=tp_attn)
            self._forward = fwd
        if mesh is not None:
            from kuberay_tpu.serve.sharding import (
                param_shardings, validate_tp)
            validate_tp(cfg, mesh)
            self._cache_sh = self._cache_sharding_tree(mesh)
            self.params = jax.device_put(self.params,
                                         param_shardings(cfg, mesh))
            # jit the INITIALIZER with sharded outputs — a flagship-sized
            # cache must come into existence sharded, never whole.
            self.cache = jax.jit(self._init_cache,
                                 out_shardings=self._cache_sh)()
        # W8A16 serving: matmul weights live as int8 + per-channel
        # scales (half the HBM, half the decode weight bandwidth); the
        # dequant runs inside the jitted forwards where XLA fuses it
        # into each matmul's operand read.  Applied AFTER the mesh
        # device_put so sharded trees quantize shard-local.
        self.weight_quant = weight_quant
        if weight_quant == "int8":
            from kuberay_tpu.serve.weight_quant import (
                make_weight_dequant_forward,
                quantize_weights,
            )
            # donate_params frees the bf16 tree as it quantizes — the
            # startup-peak fix for models that only fit BECAUSE of int8
            # (without it the device briefly holds bf16 + int8 + cache).
            # Off by default: donation invalidates the caller's tree.
            self.params = jax.jit(
                quantize_weights,
                donate_argnums=(0,) if donate_params else ())(self.params)
            if self.USES_BASE_FORWARD:
                self._forward = make_weight_dequant_forward(self._forward)
        elif weight_quant != "none":
            raise ValueError(f"unknown weight_quant {weight_quant!r}")
        self.key = jax.random.PRNGKey(rng_seed)

        # Slot bookkeeping (host side).
        self.lens = np.zeros(max_slots, dtype=np.int32)       # cache length
        self.active: List[Optional[Request]] = [None] * max_slots
        self.generated: List[List[int]] = [[] for _ in range(max_slots)]
        self.budget = np.zeros(max_slots, dtype=np.int32)
        self.queue: List[Request] = []
        self._finished: List[Response] = []
        # TTFT bookkeeping (always on, metrics or not): enqueue instants
        # by request id, first-token latency by slot until finish.
        self._arrival: Dict[str, float] = {}
        self._ttft: List[Optional[float]] = [None] * max_slots

        # With a mesh, pin output shardings so the cache round-trips
        # sharded (no surprise all-gathers) and sampled tokens come back
        # replicated for the host loop.
        pf_kw, dc_kw, vf_kw = {}, {}, {}
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(mesh, P())
            cs = self._cache_sh
            pf_kw = dc_kw = {"out_shardings": (rep, cs)}
            vf_kw = {"out_shardings": (rep, rep, cs)}
        self._prefill = jax.jit(self._prefill_impl,
                                static_argnames=("prompt_len", "filtered"),
                                donate_argnames=("cache",), **pf_kw)
        self._decode = jax.jit(self._decode_impl,
                               static_argnames=("filtered",),
                               donate_argnames=("cache",), **dc_kw)
        self._verify = jax.jit(self._verify_impl,
                               static_argnames=("filtered",),
                               donate_argnames=("cache",), **vf_kw)

    def _init_cache(self):
        return init_kv_cache(self.cfg, self.max_slots, self.max_len,
                             quant=self.kv_quant)

    def _cache_sharding_tree(self, mesh):
        """Shardings matching _init_cache's layout (paged overrides)."""
        from kuberay_tpu.serve.sharding import cache_shardings
        return cache_shardings(self.cfg, mesh, self.kv_quant)

    # ------------------------------------------------------------------
    # jitted kernels
    # ------------------------------------------------------------------

    def _prefill_impl(self, params, cache, tokens, slot, real_len, key,
                      temperature, prompt_len, start_pos=0,
                      filtered=False):
        """Prefill one chunk of one request into one slot.
        tokens: [prompt_len] padded; start_pos: tokens already in the
        slot's cache (0 for whole-prompt prefill; the chunk offset when
        chunked — attention masks keys at col <= query position, so a
        chunk attends to everything the slot prefilled before it)."""
        B = self.max_slots
        row = jnp.zeros((B, prompt_len), dtype=jnp.int32).at[slot].set(tokens)
        start = jnp.zeros((B,), jnp.int32).at[slot].set(start_pos)
        # Only the target slot's cache row may be written — other slots are
        # mid-decode and their caches must be untouched.
        write_mask = jax.nn.one_hot(slot, B, dtype=jnp.float32)
        # Token mask: only the target slot's REAL tokens participate in
        # routing FFNs (padding/other slots must not claim MoE capacity).
        token_mask = (write_mask[:, None] *
                      (jnp.arange(prompt_len)[None, :] < real_len))
        logits, new_cache = self._forward(
            self.cfg, params, row, cache, start, write_mask,
            token_mask=token_mask)
        last = logits[slot, real_len - 1]                     # [V]
        sample = self._sample if filtered else self._sample_plain
        tok = sample(last, key, temperature)
        return tok, new_cache

    def _decode_impl(self, params, cache, tokens, lens, key, temperatures,
                     active_mask, filtered=False):
        """One decode step for every active slot.  tokens: [slots]."""
        logits, new_cache = self._forward(
            self.cfg, params, tokens[:, None], cache, lens, active_mask,
            token_mask=active_mask[:, None])
        keys = jax.random.split(key, self.max_slots)
        sample = self._sample if filtered else self._sample_plain
        toks = jax.vmap(sample)(logits[:, 0], keys, temperatures)
        return toks, new_cache

    def _verify_impl(self, params, cache, tokens, lens, ntok, key,
                     temperatures, active_mask, filtered=False):
        """Speculative verify: run T = γ+1 tokens (last emitted + γ draft)
        for every active slot in ONE forward.  greedy[b, j] is the model's
        next token after consuming tokens[b, :j+1] — the host accepts the
        longest prefix where greedy agrees with the draft.  Draft KV lands
        at positions lens..lens+γ; rejected positions stay masked behind
        ``lens`` and are overwritten by later steps.

        ``ntok[b]`` = 1 + draft length: only each row's REAL tokens write
        KV.  For the paged engine this is load-bearing — a position past
        a slot's allocated blocks would alias another request's physical
        block through the zero-filled table tail."""
        T = tokens.shape[1]
        token_mask = (active_mask[:, None] *
                      (jnp.arange(T)[None, :] < ntok[:, None]))
        logits, new_cache = self._forward(
            self.cfg, params, tokens, cache, lens, active_mask,
            token_mask=token_mask)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        keys = jax.random.split(key, self.max_slots)
        sampled0 = jax.vmap(self._sample)(logits[:, 0], keys, temperatures)
        return greedy, sampled0, new_cache

    @staticmethod
    def _samp(req: Request) -> np.ndarray:
        """Pack a request's sampling params as the [temp, top_p, top_k]
        row every device call carries (one operand, stable arity through
        the multihost plan and all engine funnels)."""
        return np.array([req.temperature, req.top_p, float(req.top_k)],
                        np.float32)

    @staticmethod
    def _filters_on(samp) -> bool:
        """Host-side: does this step need the filtered sampler?  Decides
        which COMPILED variant runs (static arg), so pure-greedy/plain
        traffic never pays the full-vocab sort.  Deterministic from the
        samp arrays alone — multihost followers recompute it from the
        broadcast plan and trace the same program."""
        s = np.asarray(samp)
        if s.ndim == 1:
            return bool(s[1] < 1.0 or s[2] > 0)
        return bool(np.any(s[:, 1] < 1.0) or np.any(s[:, 2] > 0))

    @staticmethod
    def _sample_plain(logits, key, samp):
        """Greedy / plain-temperature sampling (no filters): argmax plus
        one categorical — the decode hot path for default traffic."""
        temperature = samp[0]
        greedy = jnp.argmax(logits, -1).astype(jnp.int32)
        scaled = logits / jnp.maximum(temperature, 1e-6)
        sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
        return jnp.where(temperature <= 0.0, greedy, sampled)

    @staticmethod
    def _sample(logits, key, samp):
        """Greedy / temperature / top-p (nucleus) / top-k sampling.
        ``samp`` = [temperature, top_p, top_k]; temperature<=0 is greedy
        regardless of the filters; top_p=1 and top_k=0 disable theirs.
        Filtering sorts the scaled logits once (full-vocab lax.top_k),
        masks tokens outside the nucleus/top-k, and samples in sorted
        space — all static shapes, vmap-able per slot."""
        temperature, top_p, top_k = samp[0], samp[1], samp[2]
        greedy = jnp.argmax(logits, -1).astype(jnp.int32)
        V = logits.shape[-1]
        scaled = logits / jnp.maximum(temperature, 1e-6)
        sorted_l, sorted_idx = jax.lax.top_k(scaled, V)
        probs = jax.nn.softmax(sorted_l, -1)
        cum = jnp.cumsum(probs, -1)
        # Nucleus: keep tokens whose cumulative mass BEFORE them is
        # < top_p (the best token always survives).
        keep = (cum - probs) < top_p
        ranks = jnp.arange(V, dtype=jnp.float32)
        keep &= jnp.where(top_k > 0, ranks < top_k, True)
        keep = keep.at[0].set(True)
        filt = jnp.where(keep, sorted_l, -jnp.inf)
        choice = jax.random.categorical(key, filt)
        sampled = sorted_idx[choice].astype(jnp.int32)
        return jnp.where(temperature <= 0.0, greedy, sampled)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def add_request(self, req: Request) -> None:
        self._arrival[req.request_id] = self._now()
        self._phase_mark(req.request_id, "queued")
        if len(req.prompt_tokens) >= self.max_len or req.max_new_tokens <= 0:
            self._cancel(req)
            return
        self.queue.append(req)

    def _cancel(self, req: Request) -> None:
        self._arrival.pop(req.request_id, None)
        self._req_phase_ts.pop(req.request_id, None)
        self._finished.append(Response(
            req.request_id, [], "cancelled",
            prompt_len=len(req.prompt_tokens), created=self._now()))

    # -- request-phase latency accounting ------------------------------

    def _phase_mark(self, rid: str, phase: str) -> None:
        # Phase timestamps feed both the metrics decomposition and the
        # per-request span tree — stamp when either consumer is live.
        if self.metrics is None and not self._tracer.enabled:
            return
        self._req_phase_ts.setdefault(rid, {})[phase] = self._now()

    def _phase_observe(self, rid: str, terminal: bool = True) -> None:
        """Emit the queue/prefill/decode decomposition for one request.
        queue+prefill land at first token (so a long-running decode
        still shows its admission cost live); decode lands at finish."""
        if self.metrics is None and not self._tracer.enabled:
            return
        ts = self._req_phase_ts.get(rid)
        if ts is None:
            return
        now = self._now()
        if not terminal:
            if self.metrics is not None:
                if "queued" in ts and "admitted" in ts:
                    self.metrics.observe(
                        "tpu_serve_request_duration_seconds",
                        ts["admitted"] - ts["queued"], {"phase": "queue"})
                if "admitted" in ts:
                    self.metrics.observe(
                        "tpu_serve_request_duration_seconds",
                        now - ts["admitted"], {"phase": "prefill"})
            if "admitted" in ts:
                ts["first_token"] = now
            return
        if "first_token" in ts and self.metrics is not None:
            self.metrics.observe(
                "tpu_serve_request_duration_seconds",
                now - ts["first_token"], {"phase": "decode"})
        self._req_phase_ts.pop(rid, None)

    @property
    def num_active(self) -> int:
        return sum(1 for r in self.active if r is not None)

    @property
    def stats(self) -> Dict[str, Any]:
        """Scheduling-state snapshot: what the serve frontend folds into
        /stats and reports to the gateway via response headers (the
        continuous-batching admission feedback).  The paged engine
        extends this with KV pool occupancy."""
        return {"queue_depth": len(self.queue),
                "active_slots": self.num_active}

    def has_work(self) -> bool:
        # _finished counts: instantly-cancelled admissions must still be
        # drained by the driving loop or their callers would never wake.
        return (bool(self.queue) or self.num_active > 0
                or bool(self._finished) or self._inflight is not None)

    def step(self) -> List[Response]:
        """One engine iteration: admit (prefill) if possible, then decode
        all active slots.  Returns finished responses.

        With ``prefill_chunk`` set, at most one chunk of prompt is
        prefilled per step and a decode pass runs in between — other
        slots keep generating while a long prompt streams in.
        """
        chunked_this_step = False
        if self._inflight is not None:
            self._chunk_step()
            chunked_this_step = True
        # Admission: continuous batching — fill every free slot before the
        # decode pass (an underfilled batch wastes a full device step).
        # In chunked mode at most ONE chunk runs per step, even when the
        # in-flight admission finished above — that bound IS the feature.
        while self.queue and self._inflight is None \
                and not chunked_this_step:
            free = next((i for i, r in enumerate(self.active) if r is None),
                        None)
            if free is None:
                break
            req = self.queue.pop(0)
            if self.prefill_chunk > 0:
                started = self._begin_chunked(req, free)
                if started is None:
                    continue    # request cancelled outright; slot still free
                break           # one chunk per step bounds this step's cost
            elif not self._admit(req, free):
                break           # admission blocked (e.g. paged memory)

        if self.num_active:
            self._decode_all()

        out, self._finished = self._finished, []
        return out

    def _begin_chunked(self, req: Request, slot: int):
        """Start a chunked admission.  Returns True when the first chunk
        ran, False when blocked (request requeued), None when the request
        was cancelled.  The paged subclass reserves KV blocks here."""
        self._phase_mark(req.request_id, "admitted")
        self._inflight = (req, slot, 0)
        self._chunk_step()
        return True

    def _chunk_step(self) -> None:
        """Prefill the next chunk of the in-flight admission; the final
        chunk samples the first generated token and activates the slot.
        The in-flight offset is ABSOLUTE into the prompt (a cached-prefix
        admission starts past zero), so this skeleton is shared with the
        paged engine — only `_prefill_chunk_call` differs."""
        req, slot, off = self._inflight
        chunk = self.prefill_chunk
        toks = req.prompt_tokens[off:off + chunk]
        padded = np.zeros(chunk, dtype=np.int32)
        padded[:len(toks)] = toks
        self.key, sub = jax.random.split(self.key)
        tok = self._prefill_chunk_call(req, slot, off, padded, len(toks),
                                       sub)
        off += len(toks)
        if off >= len(req.prompt_tokens):
            self._inflight = None
            self._chunk_finalize(req, slot, tok)
        else:
            self._inflight = (req, slot, off)

    def _prefill_chunk_call(self, req, slot, off, padded, real_len, sub):
        return self._prefill_device(padded, slot, real_len, sub,
                                    self._samp(req), self.prefill_chunk,
                                    start_pos=off)

    def _prefill_device(self, padded, slot, real_len, sub, temperature,
                        bucket, start_pos=0):
        """The prefill device call — single funnel so the multi-host
        engine can broadcast the step plan before launching (every
        process must execute the same SPMD program in lockstep)."""
        tok, self.cache = self._prefill(
            self.params, self.cache, jnp.asarray(padded),
            jnp.int32(slot), jnp.int32(real_len), sub,
            jnp.asarray(temperature, jnp.float32), prompt_len=bucket,
            start_pos=jnp.int32(start_pos),
            filtered=self._filters_on(temperature))
        return tok

    def _chunk_finalize(self, req, slot, tok) -> None:
        self._finalize_admit(req, slot, tok)

    def run(self, max_steps: int = 10_000) -> List[Response]:
        """Drain: run until all queued + active requests finish."""
        out: List[Response] = list(self._finished)   # e.g. cancelled on add
        self._finished = []
        for _ in range(max_steps):
            if not self.has_work():
                break
            out.extend(self.step())
        return out

    # ------------------------------------------------------------------

    def _admit(self, req: Request, slot: int):
        self._phase_mark(req.request_id, "admitted")
        plen = len(req.prompt_tokens)
        bucket = _bucket(plen, self.max_len)
        padded = np.zeros(bucket, dtype=np.int32)
        padded[:plen] = req.prompt_tokens
        self.key, sub = jax.random.split(self.key)
        tok = self._prefill_device(padded, slot, plen, sub,
                                   self._samp(req), bucket)
        # Cache now contains bucket tokens for the slot; only plen are real.
        self._finalize_admit(req, slot, tok)
        return True

    def _finalize_admit(self, req: Request, slot: int, tok) -> None:
        self._phase_observe(req.request_id, terminal=False)
        ts = self._req_phase_ts.get(req.request_id) or {}
        arrival = self._arrival.pop(req.request_id, None)
        # Use the first-token stamp when one exists so the span tree,
        # the TTFT observation, and its exemplar share one instant —
        # the virtual-clock exactness contract (tests/test_serve_trace).
        now = ts.get("first_token", self._now())
        ttft = (now - arrival) if arrival is not None else None
        self._ttft[slot] = ttft
        if self.metrics is not None and ttft is not None:
            # The SLO autoscaler's primary signal (controlplane/slo.py):
            # sub-second buckets, unlike the coarse reconcile-scale
            # defaults the queue/prefill/decode phases use.
            self.metrics.observe(
                "tpu_serve_request_duration_seconds", ttft,
                {"phase": "ttft"}, buckets=SERVE_LATENCY_BUCKETS,
                exemplar=(req.trace.trace_id if req.trace is not None
                          else None),
                exemplar_ts=now)
        if req.trace is not None and arrival is not None:
            admitted = ts.get("admitted", arrival)
            self._tracer.record_span(req.trace, "engine-queue",
                                     arrival, admitted)
            self._tracer.record_span(req.trace, "prefill", admitted, now,
                                     prompt_len=len(req.prompt_tokens))
        self.lens[slot] = len(req.prompt_tokens)
        self.active[slot] = req
        self.generated[slot] = [int(tok)]
        self.budget[slot] = req.max_new_tokens - 1
        self._spec_miss[slot] = 0
        self._spec_index[slot] = None      # fresh history for the new slot
        self._emit_tokens(req, [int(tok)])
        self._maybe_finish(slot)

    def _emit_tokens(self, req: Request, tokens: List[int]) -> None:
        cb = self.token_callback
        if cb is not None and tokens:
            try:
                cb(req.request_id, tokens)
            except Exception:
                pass       # a streaming consumer must never stall decode

    def _decode_all(self):
        last = np.zeros(self.max_slots, dtype=np.int32)
        # Per-slot [temperature, top_p, top_k] rows; idle slots keep the
        # no-op defaults (greedy, filters off).
        temps = np.zeros((self.max_slots, 3), dtype=np.float32)
        temps[:, 1] = 1.0
        mask = np.zeros(self.max_slots, dtype=np.float32)
        for i, req in enumerate(self.active):
            if req is not None and self.generated[i]:
                last[i] = self.generated[i][-1]
                temps[i] = self._samp(req)
                mask[i] = 1.0
        if self.speculative > 0:
            drafts = self._build_drafts()
            drafting = sum(1 for d in drafts if d)
            active = max(1, self.num_active)
            if drafting and \
                    drafting >= active * self.SPEC_MIN_DRAFT_FRACTION:
                return self._spec_decode_all(last, temps, mask, drafts)
        self.key, sub = jax.random.split(self.key)
        toks = np.asarray(self._decode_call(last, temps, mask, sub))
        for i, req in enumerate(self.active):
            if req is None:
                continue
            self.lens[i] += 1
            self.generated[i].append(int(toks[i]))
            self.budget[i] -= 1
            self._emit_tokens(req, [int(toks[i])])
            self._maybe_finish(i)

    # -- speculative decoding ------------------------------------------

    def _build_drafts(self) -> List[List[int]]:
        """Per-slot prompt-lookup drafts.  Sampling slots (temperature
        > 0) never draft — greedy acceptance would bias their
        distribution; they fall through to one sampled token."""
        gamma = self.speculative
        drafts: List[List[int]] = [[] for _ in range(self.max_slots)]
        for i, req in enumerate(self.active):
            if req is None or req.temperature > 0 or not self.generated[i]:
                continue
            if self._spec_miss[i] >= self.SPEC_MISS_LIMIT:
                if self._spec_cooldown[i] > 0:
                    self._spec_cooldown[i] -= 1
                    continue            # backed off; probe when it hits 0
            # Cache head-room: positions lens..lens+γ must stay < max_len
            # (and, for paged engines, within the slot's allocated
            # blocks — _extra_draft_cap).
            cap = min(gamma, self.max_len - int(self.lens[i]) - 2,
                      int(self.budget[i]), self._extra_draft_cap(i))
            if cap <= 0:
                continue
            hist = list(req.prompt_tokens) + self.generated[i]
            idx = self._spec_index[i]
            if idx is None:
                idx = self._spec_index[i] = NgramIndex()
            idx.extend(hist)
            drafts[i] = idx.draft(hist, cap)
        return drafts

    def _extra_draft_cap(self, slot: int) -> int:
        """Engine-specific extra bound on draft length (paged: block
        capacity)."""
        return self.speculative

    def _spec_decode_all(self, last, temps, mask, drafts):
        gamma = self.speculative
        toks = np.zeros((self.max_slots, gamma + 1), dtype=np.int32)
        toks[:, 0] = last
        ntok = np.zeros(self.max_slots, dtype=np.int32)
        for i, d in enumerate(drafts):
            toks[i, 1:1 + len(d)] = d
            ntok[i] = (1 + len(d)) if mask[i] > 0 else 0
        self.key, sub = jax.random.split(self.key)
        greedy, sampled0 = self._verify_device(toks, ntok, sub, temps, mask)
        greedy = np.asarray(greedy)
        sampled0 = np.asarray(sampled0)
        self.spec_stats["verify_steps"] += 1
        for i, req in enumerate(self.active):
            if req is None:
                continue
            if req.temperature > 0:
                emitted = [int(sampled0[i])]
            else:
                # Longest-prefix acceptance: greedy[i, j] both checks
                # draft[j] and IS the correction/bonus token on exit.
                emitted = []
                for j in range(len(drafts[i]) + 1):
                    emitted.append(int(greedy[i, j]))
                    if j >= len(drafts[i]) or greedy[i, j] != drafts[i][j]:
                        break
                self.spec_stats["drafted"] += len(drafts[i])
                self.spec_stats["accepted"] += len(emitted) - 1
                if drafts[i]:
                    if len(emitted) > 1:
                        self._spec_miss[i] = 0
                    else:
                        self._spec_miss[i] += 1
                        if self._spec_miss[i] >= self.SPEC_MISS_LIMIT:
                            self._spec_cooldown[i] = self.SPEC_PROBE_EVERY
            take: List[int] = []
            for t in emitted:
                take.append(t)
                self.budget[i] -= 1
                if self.budget[i] <= 0 or self._is_stop(req, t) \
                        or self.lens[i] + len(take) + 1 >= self.max_len:
                    break
            self.lens[i] += len(take)
            self.generated[i].extend(take)
            self._emit_tokens(req, take)
            self._maybe_finish(i)

    def _verify_device(self, toks, ntok, sub, temps, mask):
        """The speculative-verify device call (multi-host funnel)."""
        greedy, sampled0, self.cache = self._verify(
            self.params, self.cache, jnp.asarray(toks),
            jnp.asarray(self.lens), jnp.asarray(ntok), sub,
            jnp.asarray(temps), jnp.asarray(mask),
            filtered=self._filters_on(temps))
        return greedy, sampled0

    def _decode_call(self, last, temps, mask, sub):
        """The device decode step; paged subclass passes block tables."""
        toks, self.cache = self._decode(
            self.params, self.cache, jnp.asarray(last),
            jnp.asarray(self.lens), sub, jnp.asarray(temps),
            jnp.asarray(mask), filtered=self._filters_on(temps))
        return toks

    @staticmethod
    def _is_stop(req: Request, tok: int) -> bool:
        if req.eos_token is not None and tok == req.eos_token:
            return True
        return bool(req.stop_token_ids) and tok in req.stop_token_ids

    def _maybe_finish(self, slot: int):
        req = self.active[slot]
        if req is None:
            return
        gen = self.generated[slot]
        reason = None
        if gen and self._is_stop(req, gen[-1]):
            reason = "eos"
        elif self.budget[slot] <= 0:
            reason = "length"
        elif self.lens[slot] + 1 >= self.max_len:
            reason = "length"
        if reason:
            self._finish(slot, reason)

    def _finish(self, slot: int, reason: str) -> None:
        """The single finish path (normal, eos, or preemption) — all
        slot-teardown bookkeeping lives here; the paged engine hooks it
        to release blocks."""
        req = self.active[slot]
        ts = self._req_phase_ts.get(req.request_id) or {}
        now = self._now()
        if req.trace is not None and "first_token" in ts:
            self._tracer.record_span(
                req.trace, "decode", ts["first_token"], now,
                tokens=len(self.generated[slot]), reason=reason)
        self._phase_observe(req.request_id)
        self._finished.append(Response(
            req.request_id, list(self.generated[slot]), reason,
            prompt_len=len(req.prompt_tokens), created=now,
            ttft_s=self._ttft[slot]))
        self.active[slot] = None
        self.generated[slot] = []
        self.lens[slot] = 0
        self._ttft[slot] = None
