"""CLI: run seeded chaos simulations and replay violations.

    python -m kuberay_tpu.sim --seed 0..9              # all scenarios
    python -m kuberay_tpu.sim --scenario cronjob-burst --seed 7 --steps 20
    python -m kuberay_tpu.sim --list-scenarios
    python -m kuberay_tpu.sim --list-invariants

Exit codes: 0 clean, 1 invariant violation (the failure report includes
the exact replay command and the journal tail), 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from kuberay_tpu.sim.harness import SimHarness, SimResult
from kuberay_tpu.sim.invariants import DESCRIPTIONS
from kuberay_tpu.sim.scenarios import SCENARIOS, get_scenario


def parse_seeds(spec: str) -> List[int]:
    """``"7"`` -> [7]; ``"0..9"`` -> [0, 1, ..., 9] (inclusive)."""
    if ".." in spec:
        lo, hi = spec.split("..", 1)
        start, end = int(lo), int(hi)
        if end < start:
            raise ValueError(f"empty seed range {spec!r}")
        return list(range(start, end + 1))
    return [int(spec)]


def _report_violation(result: SimResult, journal_tail: int,
                      journal: list, out) -> None:
    print(f"FAIL scenario={result.scenario} seed={result.seed} "
          f"steps={result.steps}", file=out)
    for v in result.violations:
        print(f"  {v}", file=out)
    print(f"  replay: {result.replay_command()}", file=out)
    if journal_tail > 0:
        print(f"  journal tail ({min(journal_tail, len(journal))} of "
              f"{len(journal)} events):", file=out)
        for rec in journal[-journal_tail:]:
            print(f"    {json.dumps(rec, sort_keys=True)}", file=out)


def _report_profile(profile_doc: dict, out) -> None:
    """Violation forensics: where the violating run's wall time went,
    per trace shape — the top span kinds by total exclusive self time,
    so 'which component ate the window' is answered without opening the
    trace export."""
    for shape, body in sorted(profile_doc.get("shapes", {}).items()):
        kinds = sorted(body.get("kinds", {}).items(),
                       key=lambda kv: -kv[1]["total_s"])[:5]
        if not kinds:
            continue
        parts = ", ".join(
            f"{k} {v['total_s']:.3f}s ({v['fraction'] * 100:.0f}%)"
            for k, v in kinds)
        print(f"  critical path [{shape}] over {body['traces']} "
              f"windows: {parts}", file=out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kuberay_tpu.sim",
        description="Deterministic chaos simulation for the TPU control "
                    "plane: seeded fault schedules + invariant checkers.")
    parser.add_argument("--seed", default="0",
                        help="single seed (7) or inclusive range (0..9)")
    parser.add_argument("--steps", type=int, default=0,
                        help="inject->drain->check cycles per run "
                             "(default: the scenario's default)")
    parser.add_argument("--scenario", default="all",
                        help="scenario name, or 'all' "
                             f"({', '.join(sorted(SCENARIOS))})")
    parser.add_argument("--journal-tail", type=int, default=20,
                        help="journal events to dump on violation "
                             "(0 disables)")
    parser.add_argument("--trace", action="store_true",
                        help="record causal spans (kuberay_tpu.obs): "
                             "queue-wait/reconcile/store-write/pod-start/"
                             "slice-ready per reconcile chain; the replay "
                             "hash is unaffected")
    parser.add_argument("--trace-out", default="",
                        help="write the trace export (spans + journal + "
                             "flight timelines) to this JSON file; "
                             "implies --trace.  With a seed range, the "
                             "last run wins — use a single seed for "
                             "forensics")
    parser.add_argument("--profile-out", default="",
                        help="write the run's critical-path profile "
                             "(tpu-profile/v1: per-span-kind exclusive "
                             "self-time percentiles) to this JSON file; "
                             "implies --trace.  Byte-identical across "
                             "re-runs of a seed")
    parser.add_argument("--alerts", action="store_true",
                        help="evaluate SLO burn-rate alerts each settle "
                             "round (kuberay_tpu.obs.alerts); the replay "
                             "hash is unaffected")
    parser.add_argument("--step-telemetry", action="store_true",
                        help="mount the training-step straggler "
                             "microscope (kuberay_tpu.obs.steps) on the "
                             "run's synthetic heartbeats; the replay "
                             "hash is unaffected")
    parser.add_argument("--incidents", action="store_true",
                        help="mount the incident forensics engine "
                             "(kuberay_tpu.obs.incident): rollbacks, "
                             "preemption notices, straggler verdicts, "
                             "quota reclaims and invariant violations "
                             "become ranked tpu-incident/v1 bundles; "
                             "the replay hash is unaffected")
    parser.add_argument("--incidents-out", default="",
                        help="write the run's incident bundles "
                             "(tpu-incident-export/v1) to this JSON "
                             "file; implies --incidents.  Byte-identical "
                             "across re-runs of a seed.  With a seed "
                             "range, the last run wins")
    parser.add_argument("--json", action="store_true",
                        help="one JSON result object per run on stdout")
    parser.add_argument("--list-scenarios", action="store_true")
    parser.add_argument("--list-invariants", action="store_true")
    args = parser.parse_args(argv)

    if args.list_scenarios:
        for name in sorted(SCENARIOS):
            s = SCENARIOS[name]
            print(f"{name}: {s.description} "
                  f"(default {s.default_steps} steps)")
        return 0
    if args.list_invariants:
        for name in sorted(DESCRIPTIONS):
            print(f"{name}: {DESCRIPTIONS[name]}")
        return 0

    try:
        seeds = parse_seeds(args.seed)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.scenario == "all":
        names = sorted(SCENARIOS)
    elif args.scenario in SCENARIOS:
        names = [args.scenario]
    else:
        print(f"error: unknown scenario {args.scenario!r}; known: "
              f"{', '.join(sorted(SCENARIOS))}", file=sys.stderr)
        return 2

    trace = args.trace or bool(args.trace_out) or bool(args.profile_out)
    incidents = args.incidents or bool(args.incidents_out)
    failed = False
    for name in names:
        scenario = get_scenario(name)
        steps = args.steps or scenario.default_steps
        for seed in seeds:
            with SimHarness(seed, scenario=scenario, trace=trace,
                            alerts=args.alerts,
                            steps=args.step_telemetry,
                            incidents=incidents) as h:
                result = h.run(steps)
                journal = list(h.journal)
                trace_doc = h.export_trace() if trace else None
                profile_doc = h.export_profile() if trace else None
                incident_doc = h.export_incidents() if incidents else None
            if args.trace_out and trace_doc is not None:
                with open(args.trace_out, "w") as f:
                    json.dump(trace_doc, f, sort_keys=True)
                print(f"trace: {len(trace_doc['spans'])} spans -> "
                      f"{args.trace_out}")
            if args.profile_out and profile_doc is not None:
                with open(args.profile_out, "w") as f:
                    json.dump(profile_doc, f, sort_keys=True)
                shapes = profile_doc.get("shapes", {})
                windows = sum(s["traces"] for s in shapes.values())
                print(f"profile: {windows} windows across "
                      f"{len(shapes)} shapes -> {args.profile_out}")
            if args.incidents_out and incident_doc is not None:
                with open(args.incidents_out, "w") as f:
                    json.dump(incident_doc, f, sort_keys=True)
                print(f"incidents: {len(incident_doc['incidents'])} "
                      f"bundles -> {args.incidents_out}")
            if args.json:
                print(json.dumps({
                    "scenario": result.scenario, "seed": result.seed,
                    "steps": result.steps, "ok": result.ok,
                    "violations": [str(v) for v in result.violations],
                    "events": result.journal_len,
                    "journal_hash": result.journal_hash,
                    "faults": result.faults_injected,
                }, sort_keys=True))
            if result.ok:
                if not args.json:
                    faults = sum(result.faults_injected.values())
                    print(f"ok   scenario={result.scenario} seed={seed} "
                          f"steps={result.steps} events={result.journal_len} "
                          f"faults={faults} "
                          f"hash={result.journal_hash[:12]}")
            else:
                failed = True
                _report_violation(result, args.journal_tail, journal,
                                  sys.stderr)
                if trace_doc is not None:
                    where = (f"written to {args.trace_out}" if args.trace_out
                             else "rerun with --trace-out PATH to save")
                    print(f"  trace: {len(trace_doc['spans'])} causal "
                          f"spans recorded ({where})", file=sys.stderr)
                if profile_doc is not None:
                    _report_profile(profile_doc, sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
