"""Named simulation scenarios: workload scripts the fault plan attacks.

A scenario owns three things: the initial object graph (``setup``), the
per-step workload mutation (``tick`` — spec edits a real user would
make, driven by the harness's seeded rng so replays are exact), and the
fault profile (mean injections per step, see faults.DEFAULT_PROFILE).

The four shipped scenarios map to the paper's four dynamic guarantees:

- ``scale-up-storm``      -> whole-slice scaling + warm-pool accounting
- ``rolling-upgrade``     -> RayService-style upgrades never break a ring
- ``leader-failover``     -> snapshot-rv discipline under takeover races
- ``cronjob-burst``       -> gang admission under bursty job churn
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from kuberay_tpu.api.common import (
    Container,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
)
from kuberay_tpu.api.tpucluster import (
    HeadGroupSpec,
    TpuCluster,
    TpuClusterSpec,
    WorkerGroupSpec,
)
from kuberay_tpu.controlplane.store import Conflict
from kuberay_tpu.sim import faults as F
from kuberay_tpu.utils import constants as C


def _template(image: str = "tpu-runtime:v1") -> PodTemplateSpec:
    return PodTemplateSpec(
        spec=PodSpec(containers=[Container(name="worker", image=image)]))


def make_cluster_obj(name: str = "storm", accelerator: str = "v5p",
                     topology: str = "2x2x2", replicas: int = 1,
                     max_replicas: int = 8, image: str = "tpu-runtime:v1"):
    return TpuCluster(
        metadata=ObjectMeta(name=name),
        spec=TpuClusterSpec(
            headGroupSpec=HeadGroupSpec(template=_template(image)),
            workerGroupSpecs=[WorkerGroupSpec(
                groupName="workers", accelerator=accelerator,
                topology=topology, replicas=replicas,
                maxReplicas=max_replicas, template=_template(image))],
        )).to_dict()


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    profile: Dict[str, float]
    setup: Callable
    tick: Callable
    default_steps: int = 12
    # Reconcile shard count the harness builds its Manager with (1 =
    # the classic single pool; shard-restart exercises the sharded
    # router + bookmark resume).
    shards: int = 1
    # Mount the deterministic serve-traffic pump (harness
    # _pump_serve_traffic): synthetic weighted requests against the
    # TrafficRoute every settle round, feeding the burn-rate gate and
    # the zero-failed-requests checker.  Off for the classic scenarios
    # so their journals stay byte-identical.
    serve_traffic: bool = False
    # Extra feature gates merged over the harness baseline (e.g. the
    # incremental-upgrade gate); empty for the classic scenarios.
    extra_gates: Dict[str, bool] = dataclasses.field(default_factory=dict)
    # Mount the hierarchical QuotaManager + GangScheduler as the
    # capacity seam for cluster/job/cron admission.  Off for the
    # classic scenarios so their journals stay byte-identical (no
    # PodGroup objects, no admission verdict writes).
    quota: bool = False


SCENARIOS: Dict[str, Scenario] = {}


def scenario(name: str, description: str, profile: Dict[str, float],
             default_steps: int = 12, shards: int = 1,
             serve_traffic: bool = False,
             extra_gates: Optional[Dict[str, bool]] = None,
             quota: bool = False):
    def register(cls):
        inst = cls()
        SCENARIOS[name] = Scenario(
            name=name, description=description, profile=profile,
            setup=inst.setup, tick=inst.tick, default_steps=default_steps,
            shards=shards, serve_traffic=serve_traffic,
            extra_gates=dict(extra_gates or {}), quota=quota)
        return cls
    return register


def get_scenario(name: str) -> Optional[Scenario]:
    return SCENARIOS.get(name)


# ---------------------------------------------------------------------------
# scale-up storm
# ---------------------------------------------------------------------------

@scenario(
    "scale-up-storm",
    "one multi-host cluster + a warm pool under aggressive replica "
    "thrash, pod kills and slice drains: scaling must stay whole-slice",
    profile={F.POD_KILL: 0.8, F.SLICE_DRAIN: 0.4, F.DELETE_RACE: 0.5,
             F.SLOW_START: 0.5, F.STORE_CONFLICT: 0.8, F.WATCH_DROP: 0.5,
             F.WATCH_DUP: 0.5, F.WATCH_DELAY: 0.5, F.LEADER_FAILOVER: 0.0})
class _ScaleUpStorm:
    def setup(self, h):
        h.store.create(make_cluster_obj("storm", replicas=2))
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": "WarmSlicePool",
            "metadata": {"name": "standby"},
            "spec": {"accelerator": "v5e", "topology": "4x4",
                     "poolSize": 2},
            "status": {},
        })

    def tick(self, h, step):
        # A user (or autoscaler) thrashing replicas in whole-slice units.
        cluster = h.store.try_get(C.KIND_CLUSTER, "storm")
        if cluster is None:
            return
        group = cluster["spec"]["workerGroupSpecs"][0]
        group["replicas"] = h.plan.rng.randint(0, group["maxReplicas"])
        try:
            h.store.update(cluster)
        except Conflict:
            # Lost a race with an in-flight controller write: skip this
            # tick's scale edit, the next tick re-reads fresh state.
            return


# ---------------------------------------------------------------------------
# rolling upgrade under pod kills
# ---------------------------------------------------------------------------

@scenario(
    "rolling-upgrade",
    "a TpuService whose cluster spec keeps changing (image bumps) while "
    "pods die: upgrades must never strand the stable service or break a "
    "serving ring",
    profile={F.POD_KILL: 1.0, F.SLICE_DRAIN: 0.3, F.DELETE_RACE: 0.3,
             F.SLOW_START: 0.4, F.STORE_CONFLICT: 0.6, F.WATCH_DROP: 0.3,
             F.WATCH_DUP: 0.3, F.WATCH_DELAY: 0.4, F.LEADER_FAILOVER: 0.0})
class _RollingUpgrade:
    def setup(self, h):
        cluster_spec = make_cluster_obj("tmpl", replicas=1,
                                        max_replicas=4)["spec"]
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_SERVICE,
            "metadata": {"name": "inference"},
            "spec": {
                "clusterSpec": cluster_spec,
                "serveConfig": {"applications": [{"name": "app",
                                                  "rev": 0}]},
                # Short virtual-time thresholds so self-heal paths run
                # inside a settle horizon.
                "serviceUnhealthySecondThreshold": 20,
                "deploymentUnhealthySecondThreshold": 20,
                "clusterDeletionDelaySeconds": 5,
            },
            "status": {},
        })

    def tick(self, h, step):
        svc = h.store.try_get(C.KIND_SERVICE, "inference")
        if svc is None:
            return
        if step % 2 == 0:
            # Image bump: a real upgrade (hash changes -> pending cluster).
            rev = step // 2
            for g in ([svc["spec"]["clusterSpec"].get("headGroupSpec", {})]
                      + svc["spec"]["clusterSpec"].get("workerGroupSpecs",
                                                       [])):
                tmpl = g.get("template", {})
                for cont in tmpl.get("spec", {}).get("containers", []):
                    cont["image"] = f"tpu-runtime:v{rev}"
            try:
                h.store.update(svc)
            except Conflict:
                return


# ---------------------------------------------------------------------------
# upgrade under fire: burn-rate-gated blue/green ramp + live traffic + faults
# ---------------------------------------------------------------------------

@scenario(
    "upgrade-under-fire",
    "a burn-rate-gated incremental upgrade (waves, pre-warm, drain) with "
    "live weighted serve traffic while pods die and preemption notices "
    "land mid-wave: no TrafficRoute may ever weight a partial green "
    "ring, and no client request may fail",
    # SLICE_DRAIN/DELETE_RACE stay 0: a raw whole-slice kill of the only
    # blue ring would zero fleet capacity by construction — the drill is
    # about the upgrade surviving single-pod deaths and warned
    # preemptions, not about serving through total capacity loss.
    profile={F.POD_KILL: 0.5, F.PREEMPTION_NOTICE: 0.4, F.SLOW_START: 0.3,
             F.STORE_CONFLICT: 0.4, F.WATCH_DROP: 0.2, F.WATCH_DUP: 0.2,
             F.WATCH_DELAY: 0.3, F.SLICE_DRAIN: 0.0, F.DELETE_RACE: 0.0,
             F.LEADER_FAILOVER: 0.0},
    serve_traffic=True,
    extra_gates={"TpuServiceIncrementalUpgrade": True})
class _UpgradeUnderFire:
    def setup(self, h):
        # v5p 2x2x2 = 2 hosts per ICI ring, two rings: multi-host
        # atomicity is in play and one pod kill never zeros the fleet.
        cluster_spec = make_cluster_obj("tmpl", accelerator="v5p",
                                        topology="2x2x2", replicas=2,
                                        max_replicas=4)["spec"]
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_SERVICE,
            "metadata": {"name": "fleet"},
            "spec": {
                "clusterSpec": cluster_spec,
                "serveConfig": {"applications": [{"name": "app",
                                                  "rev": 0}]},
                "upgradeStrategy":
                    "NewClusterWithIncrementalUpgrade",
                # Short virtual-time ramp so a full gated cycle (prewarm
                # -> waves -> drain -> promote) fits inside a run.
                "upgradeOptions": {
                    "stepSizePercent": 25, "intervalSeconds": 5,
                    "maxRollbacks": 1, "holdSeconds": 10,
                    "waveSlices": 1, "prewarmPrompts": 4,
                    "drainTimeoutSeconds": 15,
                },
                "serviceUnhealthySecondThreshold": 20,
                "deploymentUnhealthySecondThreshold": 20,
                "clusterDeletionDelaySeconds": 5,
            },
            "status": {},
        })

    def tick(self, h, step):
        svc = h.store.try_get(C.KIND_SERVICE, "fleet")
        if svc is None:
            return
        if step in (2, 8):
            # Two image bumps per run: the second lands while the fleet
            # may still be mid-ramp/rolled-back from the first, so the
            # abandon-pending and fresh-budget paths run under fire too.
            for g in ([svc["spec"]["clusterSpec"].get("headGroupSpec", {})]
                      + svc["spec"]["clusterSpec"].get("workerGroupSpecs",
                                                       [])):
                tmpl = g.get("template", {})
                for cont in tmpl.get("spec", {}).get("containers", []):
                    cont["image"] = f"tpu-runtime:v{step}"
            try:
                h.store.update(svc)
            except Conflict:
                return


# ---------------------------------------------------------------------------
# dead green upgrade: a known-bad build behind a clean ramp (incident drill)
# ---------------------------------------------------------------------------

@scenario(
    "dead-green-upgrade",
    "an incremental upgrade whose green build is dead on arrival: rings "
    "come up, weights ramp, every green-routed request errors until the "
    "burn-rate gate rolls the ramp back — the forensics drill where the "
    "injected fault IS the new build, nothing else",
    # Zero ambient chaos by design: the incident ranker's hard gate is
    # that the top suspect names the dead green backend, so the drill
    # must not hand it a competing plausible cause.
    profile={F.POD_KILL: 0.0, F.PREEMPTION_NOTICE: 0.0, F.SLOW_START: 0.0,
             F.STORE_CONFLICT: 0.0, F.WATCH_DROP: 0.0, F.WATCH_DUP: 0.0,
             F.WATCH_DELAY: 0.0, F.SLICE_DRAIN: 0.0, F.DELETE_RACE: 0.0,
             F.LEADER_FAILOVER: 0.0},
    serve_traffic=True,
    extra_gates={"TpuServiceIncrementalUpgrade": True})
class _DeadGreenUpgrade:
    #: The known-bad build.  Marked dead in the harness pump BEFORE the
    #: bump lands, so whatever green cluster the upgrade controller
    #: mints for it is unserveable from its first routed request.
    DEAD_IMAGE = "tpu-runtime:v2-dead"

    def setup(self, h):
        h.dead_images = {self.DEAD_IMAGE}
        cluster_spec = make_cluster_obj("tmpl", accelerator="v5p",
                                        topology="2x2x2", replicas=2,
                                        max_replicas=4)["spec"]
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_SERVICE,
            "metadata": {"name": "fleet"},
            "spec": {
                "clusterSpec": cluster_spec,
                "serveConfig": {"applications": [{"name": "app",
                                                  "rev": 0}]},
                "upgradeStrategy":
                    "NewClusterWithIncrementalUpgrade",
                "upgradeOptions": {
                    "stepSizePercent": 25, "intervalSeconds": 5,
                    "maxRollbacks": 1, "holdSeconds": 10,
                    "waveSlices": 1, "prewarmPrompts": 4,
                    "drainTimeoutSeconds": 15,
                },
                "serviceUnhealthySecondThreshold": 20,
                "deploymentUnhealthySecondThreshold": 20,
                "clusterDeletionDelaySeconds": 5,
            },
            "status": {},
        })

    def tick(self, h, step):
        if step != 2:
            return
        svc = h.store.try_get(C.KIND_SERVICE, "fleet")
        if svc is None:
            return
        # One image bump to the dead build: its pods start fine
        # (readiness is not the fault) but every request the pump routes
        # to it errors on the green series (then fails over to blue — no
        # client-visible failure) until the burn-rate gate trips.
        for g in ([svc["spec"]["clusterSpec"].get("headGroupSpec", {})]
                  + svc["spec"]["clusterSpec"].get("workerGroupSpecs",
                                                   [])):
            tmpl = g.get("template", {})
            for cont in tmpl.get("spec", {}).get("containers", []):
                cont["image"] = self.DEAD_IMAGE
        try:
            h.store.update(svc)
        except Conflict:
            return


# ---------------------------------------------------------------------------
# leader failover mid-reconcile
# ---------------------------------------------------------------------------

@scenario(
    "leader-failover",
    "cluster + job workload with repeated leader takeovers landing "
    "mid-drain: every snapshot-rv write must 409 instead of clobbering "
    "the new leader's state",
    profile={F.LEADER_FAILOVER: 1.2, F.STORE_CONFLICT: 1.0,
             F.POD_KILL: 0.5, F.SLICE_DRAIN: 0.2, F.DELETE_RACE: 0.3,
             F.SLOW_START: 0.3, F.WATCH_DROP: 0.4, F.WATCH_DUP: 0.4,
             F.WATCH_DELAY: 0.4})
class _LeaderFailover:
    def setup(self, h):
        h.store.create(make_cluster_obj("primary", replicas=2))
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
            "metadata": {"name": "train"},
            "spec": {
                "entrypoint": "python -m train",
                "submissionMode": "HTTPMode",
                "clusterSpec": make_cluster_obj(
                    "train-cluster", replicas=1)["spec"],
            },
            "status": {},
        })

    def tick(self, h, step):
        # Jobs complete mid-run so terminal-state transitions interleave
        # with takeovers; a fresh job arrives every few steps.
        h.succeed_jobs()
        if step % 3 == 0:
            h.store.create({
                "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
                "metadata": {"name": f"train-{step}"},
                "spec": {
                    "entrypoint": "python -m train",
                    "submissionMode": "HTTPMode",
                    "shutdownAfterJobFinishes": True,
                    "ttlSecondsAfterFinished": 10,
                    "clusterSpec": make_cluster_obj(
                        "ignored", replicas=1)["spec"],
                },
                "status": {},
            })


# ---------------------------------------------------------------------------
# shard restart: bookmark/resume under a sharded manager
# ---------------------------------------------------------------------------

@scenario(
    "shard-restart",
    "a fleet of clusters on a 4-shard manager whose informer restarts "
    "mid-storm: every restart resumes from the last bookmark rv and "
    "replays only the missed delta — reconvergence must be exact",
    profile={F.POD_KILL: 0.6, F.SLICE_DRAIN: 0.2, F.DELETE_RACE: 0.3,
             F.SLOW_START: 0.4, F.STORE_CONFLICT: 0.6, F.WATCH_DROP: 0.4,
             F.WATCH_DUP: 0.3, F.WATCH_DELAY: 0.3, F.LEADER_FAILOVER: 0.0},
    shards=4)
class _ShardRestart:
    FLEET = 6

    def setup(self, h):
        # Enough clusters that the crc32 router populates several
        # shards (6 keys over 4 pools) — a restart always has foreign
        # shards to NOT disturb.
        for i in range(self.FLEET):
            h.store.create(make_cluster_obj(f"ring-{i}", replicas=1,
                                            max_replicas=4))

    def tick(self, h, step):
        # Every other step the informer dies mid-storm: the workload
        # keeps mutating while it is down, and the reconnect must catch
        # up from the bookmark high-water rv (O(delta) replay through
        # Manager.resume; an expired backlog degrades to the scoped
        # relist) — never by missing events.
        restart = step % 2 == 0
        if restart:
            h.manager.disconnect_informer()
        rng = h.plan.rng
        for _ in range(2):
            name = f"ring-{rng.randint(0, self.FLEET - 1)}"
            cluster = h.store.try_get(C.KIND_CLUSTER, name)
            if cluster is None:
                continue
            group = cluster["spec"]["workerGroupSpecs"][0]
            group["replicas"] = rng.randint(0, group["maxReplicas"])
            try:
                h.store.update(cluster)
            except Conflict:
                continue
        if restart:
            h.manager.reconnect_informer()


# ---------------------------------------------------------------------------
# preemption drill: advance-notice kills against a warm standby
# ---------------------------------------------------------------------------

@scenario(
    "preemption-drill",
    "advance-notice preemptions against a cluster with a warm standby "
    "pool: the controller must drain (checkpoint) and pre-provision the "
    "replacement before the kill, old slice whole until the new one is "
    "Ready",
    # DELETE_RACE stays 0: a raw harness delete of a noticed pod would
    # bypass the drain seam by construction and false-positive the
    # drain-before-delete checker; the drill is about the warned path.
    profile={F.PREEMPTION_NOTICE: 0.7, F.POD_KILL: 0.2, F.SLOW_START: 0.3,
             F.STORE_CONFLICT: 0.3, F.WATCH_DROP: 0.2, F.WATCH_DUP: 0.2,
             F.WATCH_DELAY: 0.2, F.DELETE_RACE: 0.0, F.SLICE_DRAIN: 0.0,
             F.LEADER_FAILOVER: 0.0})
class _PreemptionDrill:
    def setup(self, h):
        # Pool topology matches the worker group (v5e 4x4 = 4 hosts), so
        # a claimed warm slice is adoptable as-is.
        h.store.create(make_cluster_obj("drill", accelerator="v5e",
                                        topology="4x4", replicas=2,
                                        max_replicas=4))
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": "WarmSlicePool",
            "metadata": {"name": "reserve"},
            "spec": {"accelerator": "v5e", "topology": "4x4",
                     "poolSize": 1},
            "status": {},
        })

    def tick(self, h, step):
        # The workload holds still: the adversity is the notice schedule
        # itself (notice at t, kill at t+delta, warm claim in between).
        return


# ---------------------------------------------------------------------------
# dcn partition: cross-slice connectivity loss on a multi-slice cluster
# ---------------------------------------------------------------------------

@scenario(
    "dcn-partition",
    "a multi-slice cluster + HTTPMode job whose DCN connectivity drops "
    "for seeded windows: coordinator calls fail while severed, the job "
    "must recover when the window lifts, never wedge",
    profile={F.DCN_PARTITION: 0.6, F.POD_KILL: 0.3, F.SLOW_START: 0.3,
             F.STORE_CONFLICT: 0.5, F.WATCH_DROP: 0.3, F.WATCH_DUP: 0.3,
             F.WATCH_DELAY: 0.3, F.DELETE_RACE: 0.0, F.SLICE_DRAIN: 0.0,
             F.LEADER_FAILOVER: 0.0})
class _DcnPartition:
    def setup(self, h):
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
            "metadata": {"name": "multislice"},
            "spec": {
                "entrypoint": "python -m train",
                "submissionMode": "HTTPMode",
                "clusterSpec": make_cluster_obj(
                    "ignored", accelerator="v5e", topology="2x2",
                    replicas=2, max_replicas=4)["spec"],
            },
            "status": {},
        })

    def tick(self, h, step):
        # Jobs finish between partition windows so submit/poll/terminal
        # transitions interleave with severed coordinator links.
        h.succeed_jobs()


# ---------------------------------------------------------------------------
# straggler drill: slow-host windows against the step-telemetry microscope
# ---------------------------------------------------------------------------

@scenario(
    "straggler-drill",
    "a multi-slice training cluster emitting per-host step heartbeats "
    "while seeded slow-host windows strike one host at a time: the step "
    "tracker must flag the exact host within straggler_steps heartbeats "
    "and attribute the stall window to the goodput ledger exactly",
    # Disruptive faults stay 0: a pod kill mid-window would end the
    # stall by death rather than recovery and blur the exactness gate;
    # the drill keeps mild store/watch chaos so detection runs under
    # realistic reconcile noise.
    profile={F.SLOW_HOST: 0.45, F.STORE_CONFLICT: 0.3, F.WATCH_DROP: 0.2,
             F.WATCH_DUP: 0.2, F.WATCH_DELAY: 0.2, F.POD_KILL: 0.0,
             F.SLICE_DRAIN: 0.0, F.SLOW_START: 0.0, F.DELETE_RACE: 0.0,
             F.LEADER_FAILOVER: 0.0})
class _StragglerDrill:
    #: Heartbeats per sim step and the healthy per-step wall time; the
    #: slow host runs at plan.slow_host_factor (3x) of this.
    BEATS_PER_TICK = 3
    BASE_DUR = 1.0

    def setup(self, h):
        # v5e 4x4 = 4 hosts/slice, two slices: 8 reporting hosts, so
        # the fleet median stays at base speed with one straggler.
        h.store.create(make_cluster_obj("drill-train", accelerator="v5e",
                                        topology="4x4", replicas=2,
                                        max_replicas=4))

    def tick(self, h, step):
        # The workload IS the training loop: every tick the cluster
        # runs BEATS_PER_TICK synchronous steps, the clock advancing by
        # each step's wall time (rng-free — replay hashes stay
        # byte-identical with telemetry on or off).
        h.emit_training_steps("default", "drill-train",
                              count=self.BEATS_PER_TICK,
                              base_dur=self.BASE_DUR)


# ---------------------------------------------------------------------------
# cronjob burst
# ---------------------------------------------------------------------------

@scenario(
    "cronjob-burst",
    "an every-minute TpuCronJob with virtual time jumping minutes per "
    "step: catch-up, concurrency policy and history pruning under churn",
    profile={F.POD_KILL: 0.5, F.DELETE_RACE: 0.3, F.SLOW_START: 0.3,
             F.STORE_CONFLICT: 0.6, F.WATCH_DROP: 0.3, F.WATCH_DUP: 0.3,
             F.WATCH_DELAY: 0.3, F.SLICE_DRAIN: 0.2,
             F.LEADER_FAILOVER: 0.2})
class _CronJobBurst:
    def setup(self, h):
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_CRONJOB,
            "metadata": {"name": "nightly"},
            "spec": {
                "schedule": "* * * * *",
                "concurrencyPolicy": "Allow",
                "successfulJobsHistoryLimit": 2,
                "failedJobsHistoryLimit": 1,
                "jobTemplate": {
                    "entrypoint": "python -m batch",
                    "submissionMode": "HTTPMode",
                    "shutdownAfterJobFinishes": True,
                    "ttlSecondsAfterFinished": 30,
                    "clusterSpec": make_cluster_obj(
                        "ignored", topology="2x2", accelerator="v5e",
                        replicas=1)["spec"],
                },
            },
            "status": {},
        })

    def tick(self, h, step):
        # Minutes pass between steps: several schedule points fall due,
        # jobs launch, run, succeed, and get pruned.
        h.clock.advance(90.0)
        h.manager.enqueue((C.KIND_CRONJOB, "default", "nightly"))
        h.succeed_jobs()


# ---------------------------------------------------------------------------
# quota scenarios: the multi-tenant admission seam under contention
# ---------------------------------------------------------------------------

def make_quota_pool_obj(name: str, total: int, tenants,
                        starvation: float = 120.0, notice: float = 15.0):
    """``tenants`` = [(tenant, [(queue, guaranteed, ceiling, borrowable)])].
    A ceiling of 0 means "the pool total" (api/quotapool.py)."""
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_QUOTA_POOL,
        "metadata": {"name": name},
        "spec": {
            "totalChips": total,
            "starvationBoundSeconds": starvation,
            "reclaimNoticeSeconds": notice,
            "tenants": [
                {"name": tname,
                 "queues": [{"name": q, "guaranteedChips": g,
                             "ceilingChips": c, "borrowable": b}
                            for q, g, c, b in queues]}
                for tname, queues in tenants
            ],
        },
        "status": {},
    }


def _tenant_job(name: str, tenant: str, priority: int = 0,
                replicas: int = 1, ttl: int = 30):
    """A 4-chip (v5e 2x2 per slice) HTTPMode batch job owned by a tenant."""
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": name},
        "spec": {
            "entrypoint": "python -m batch",
            "submissionMode": "HTTPMode",
            "shutdownAfterJobFinishes": True,
            "ttlSecondsAfterFinished": ttl,
            "tenant": tenant,
            "priority": priority,
            "clusterSpec": make_cluster_obj(
                "ignored", accelerator="v5e", topology="2x2",
                replicas=replicas, max_replicas=4)["spec"],
        },
        "status": {},
    }


@scenario(
    "contention-storm",
    "three tenants flood an 8-chip pool with 4-chip gang jobs (the "
    "benchmark's 1k-job storm scaled to the sim budget): admission must "
    "stay all-or-nothing, guarantees reclaim borrowers through the "
    "notice seam, and nothing starves past the escalation bound",
    # DELETE_RACE/SLICE_DRAIN stay 0: quota reclaim stamps preemption
    # notices, and a raw harness delete of a noticed pod would bypass
    # the drain seam by construction (same rationale as
    # preemption-drill) — the storm is about admission under churn.
    profile={F.POD_KILL: 0.3, F.SLOW_START: 0.3, F.STORE_CONFLICT: 0.5,
             F.WATCH_DROP: 0.3, F.WATCH_DUP: 0.3, F.WATCH_DELAY: 0.3,
             F.DELETE_RACE: 0.0, F.SLICE_DRAIN: 0.0,
             F.LEADER_FAILOVER: 0.0},
    quota=True)
class _ContentionStorm:
    TENANTS = ("team-a", "team-b", "team-c")

    def setup(self, h):
        h.store.create(make_quota_pool_obj(
            "fleet", total=8,
            tenants=[("team-a", [("default", 4, 0, True)]),
                     ("team-b", [("default", 4, 0, True)]),
                     ("team-c", [("default", 0, 0, True)])],
            starvation=120.0, notice=15.0))

    def tick(self, h, step):
        # Minutes of backlog churn per step: jobs finish, claims free,
        # the next wave of the storm admits strictly through the ledger.
        h.clock.advance(30.0)
        rng = h.plan.rng
        for i in range(2):
            h.store.create(_tenant_job(
                f"storm-{step}-{i}",
                tenant=self.TENANTS[rng.randint(0, 2)],
                priority=rng.randint(0, 2)))
        h.succeed_jobs()


@scenario(
    "bursty-tenant",
    "a zero-guarantee batch tenant borrows the whole pool, then the "
    "prod tenant's guaranteed demand arrives: reclaim must warn the "
    "borrower through the notice seam and the borrower's elastic "
    "shrink must cancel the eviction — shrink before death",
    profile={F.POD_KILL: 0.2, F.SLOW_START: 0.2, F.STORE_CONFLICT: 0.4,
             F.WATCH_DROP: 0.2, F.WATCH_DUP: 0.2, F.WATCH_DELAY: 0.2,
             F.DELETE_RACE: 0.0, F.SLICE_DRAIN: 0.0,
             F.LEADER_FAILOVER: 0.0},
    quota=True)
class _BurstyTenant:
    def setup(self, h):
        # Notice window (120s) outlasts a tick + settle horizon so the
        # scripted elastic shrink lands INSIDE the window — the
        # eviction-cancelled-by-shrink path, not the teardown path
        # (contention-storm covers expiry-eviction with its 15s window).
        h.store.create(make_quota_pool_obj(
            "fleet", total=32,
            tenants=[("prod", [("default", 16, 0, True)]),
                     ("batch", [("default", 0, 0, True)])],
            starvation=90.0, notice=120.0))
        batch = make_cluster_obj("batch", accelerator="v5e",
                                 topology="2x2", replicas=4,
                                 max_replicas=8)
        batch["spec"]["tenant"] = "batch"
        h.store.create(batch)

    def _set_replicas(self, h, name, n):
        cluster = h.store.try_get(C.KIND_CLUSTER, name)
        if cluster is None:
            return
        cluster["spec"]["workerGroupSpecs"][0]["replicas"] = n
        try:
            h.store.update(cluster)
        except Conflict:
            return

    def tick(self, h, step):
        h.clock.advance(15.0)
        if step == 0:
            # Burst: borrow everything beyond the zero guarantee.
            self._set_replicas(h, "batch", 8)
        elif step == 2:
            # The guaranteed tenant arrives; its 16-chip demand is
            # within contract, so reclaim warns the borrower.
            prod = make_cluster_obj("prod", accelerator="v5e",
                                    topology="2x2", replicas=4,
                                    max_replicas=8)
            prod["spec"]["tenant"] = "prod"
            prod["spec"]["priority"] = 10
            h.store.create(prod)
        elif step == 3:
            # Elastic response inside the notice window: shrink to the
            # reclaim target cancels the eviction.
            self._set_replicas(h, "batch", 4)
        elif step == 6:
            # Prod releases half voluntarily (reclaim racing a
            # voluntary release, ledger-side).
            self._set_replicas(h, "prod", 2)
        elif step == 8:
            # The burster borrows the freed capacity right back.
            self._set_replicas(h, "batch", 6)
        elif step == 10:
            # One borrow too far: this grow stays pending.
            self._set_replicas(h, "batch", 8)


@scenario(
    "deadline-cron-fleet",
    "an every-minute guaranteed-tenant cron fleet vs a zero-guarantee "
    "hog borrowing the whole pool: due runs hold as catch-up instead "
    "of piling on denied jobs, reclaim evicts the hog through the "
    "drain seam, and the freed chips are reserved for the guaranteed "
    "waiter — not re-borrowed",
    profile={F.POD_KILL: 0.3, F.SLOW_START: 0.3, F.STORE_CONFLICT: 0.5,
             F.WATCH_DROP: 0.3, F.WATCH_DUP: 0.3, F.WATCH_DELAY: 0.3,
             F.DELETE_RACE: 0.0, F.SLICE_DRAIN: 0.0,
             F.LEADER_FAILOVER: 0.0},
    quota=True)
class _DeadlineCronFleet:
    def setup(self, h):
        h.store.create(make_quota_pool_obj(
            "fleet", total=8,
            tenants=[("pipeline", [("default", 4, 0, True)]),
                     ("adhoc", [("default", 0, 0, True)])],
            starvation=180.0, notice=10.0))
        hog = make_cluster_obj("hog", accelerator="v5e", topology="2x2",
                               replicas=2, max_replicas=4)
        hog["spec"]["tenant"] = "adhoc"
        h.store.create(hog)
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_CRONJOB,
            "metadata": {"name": "reports"},
            "spec": {
                "schedule": "* * * * *",
                "concurrencyPolicy": "Allow",
                "successfulJobsHistoryLimit": 2,
                "failedJobsHistoryLimit": 1,
                "jobTemplate": {
                    "entrypoint": "python -m report",
                    "submissionMode": "HTTPMode",
                    "shutdownAfterJobFinishes": True,
                    "ttlSecondsAfterFinished": 30,
                    "tenant": "pipeline",
                    "priority": 5,
                    "clusterSpec": make_cluster_obj(
                        "ignored", accelerator="v5e", topology="2x2",
                        replicas=1)["spec"],
                },
            },
            "status": {},
        })

    def tick(self, h, step):
        # Minutes pass between steps (the cronjob-burst cadence): runs
        # fall due, hold for quota, fire as catch-up once the hog is
        # reclaimed, succeed, and release their claims.
        h.clock.advance(90.0)
        h.manager.enqueue((C.KIND_CRONJOB, "default", "reports"))
        h.succeed_jobs()


# ---------------------------------------------------------------------------
# session churn: a real KvTierStore under multi-turn session traffic
# ---------------------------------------------------------------------------

@scenario(
    "session-churn",
    "a real KvTierStore (host+spill tiers, raw token payloads) under "
    "multi-turn session growth, capacity churn, stale re-admits and pod "
    "kills: a checkout hit must always serve the content its hash names, "
    "and never a discarded block",
    profile={F.POD_KILL: 0.5, F.PREEMPTION_NOTICE: 0.4, F.SLOW_START: 0.3,
             F.STORE_CONFLICT: 0.4, F.WATCH_DROP: 0.2, F.WATCH_DUP: 0.2,
             F.WATCH_DELAY: 0.3, F.DELETE_RACE: 0.0, F.SLICE_DRAIN: 0.0,
             F.LEADER_FAILOVER: 0.0})
class _SessionChurn:
    BLOCK = 8
    # Deliberately tight tiers: ~6 sessions of growing chains against 24
    # host + 8 spill blocks forces demotion-to-spill and hard eviction
    # every few ticks — the regimes where a stale serve would hide.
    HOST, SPILL = 24, 8
    MAX_SESSIONS = 6

    def setup(self, h):
        from kuberay_tpu.serve.kv_tiers import KvTierStore
        # The control-plane workload the fault profile bites on (pod
        # kills / notices need pods); the tier store itself is a data-
        # plane object the scenario drives directly.
        h.store.create(make_cluster_obj("churn", accelerator="v5e",
                                        topology="2x2", replicas=2,
                                        max_replicas=4))
        h.kv_store = KvTierStore(self.HOST, self.SPILL)
        h.kv_sessions = {}      # sid -> token list
        h.kv_block_tokens = {}  # hash -> (parent, block token tuple)

    def _chain(self, tokens):
        from kuberay_tpu.serve.prefix import chain_hash
        out, parent = [], 0
        for i in range(0, len(tokens) - len(tokens) % self.BLOCK,
                       self.BLOCK):
            blk = tuple(tokens[i:i + self.BLOCK])
            hsh = chain_hash(parent, blk)
            out.append((hsh, parent, blk))
            parent = hsh
        return out

    def tick(self, h, step):
        rng = h.plan.rng
        st, sessions = h.kv_store, h.kv_sessions
        # 1. Grow (or open) a few sessions: each turn appends tokens,
        #    the replica "frees" the new full blocks (decode moved on),
        #    and the demotion pump parks them in the host tier.
        for _ in range(rng.randint(1, 3)):
            sid = f"s{rng.randint(0, self.MAX_SESSIONS - 1)}"
            toks = sessions.setdefault(sid, [])
            toks.extend(rng.randint(1, 255)
                        for _ in range(rng.randint(4, 20)))
            for hsh, parent, blk in self._chain(toks):
                if hsh in h.kv_block_tokens:
                    continue
                h.kv_block_tokens[hsh] = (parent, blk)
                st.note_device(hsh, True)
                st.note_device(hsh, False)   # device copy cannibalized
                st.note_freed(hsh)
        while True:
            pending = st.pop_pending()
            if pending is None:
                break
            parent, blk = h.kv_block_tokens[pending]
            st.admit(pending, blk, tuple(blk))
            h.kv_tier_log.append({"op": "admit", "hash": pending})
        # 2. A stale re-admit: a buggy peer re-offers an evicted hash
        #    with a payload whose content it is NOT.  Admit is content-
        #    blind by design (hashes are the contract between honest
        #    peers), so the wrong entry lands — checkout's content
        #    check is the last line and must refuse to serve it.
        if h.kv_block_tokens and rng.random() < 0.5:
            victim = rng.choice(sorted(h.kv_block_tokens))
            if st.discard(victim):
                h.kv_tier_log.append({"op": "discard", "hash": victim})
            wrong = tuple(rng.randint(1, 255) for _ in range(self.BLOCK))
            st.admit(victim, wrong, wrong)
            h.kv_tier_log.append({"op": "admit", "hash": victim})
        # 3. Resume a session: walk its chain through checkout exactly
        #    like the engine's promotion path, logging ground truth for
        #    the no-stale-block checker.
        live = [s for s in sorted(sessions) if sessions[s]]
        if live:
            sid = rng.choice(live)
            for hsh, parent, blk in self._chain(sessions[sid]):
                payload = st.checkout(hsh, blk)
                if payload is None:
                    break   # promotion stops at the first tier miss
                h.kv_tier_log.append({
                    "op": "hit", "hash": hsh, "parent": parent,
                    "block_tokens": list(blk), "payload": list(payload),
                    "tier": st.tier_of(hsh) or "host"})
        # 4. Churn: a killed pod's sessions end; their blocks are
        #    discarded (the eviction-notice path PrefixIndex unlearning
        #    mirrors fleet-side).
        if live and rng.random() < 0.35:
            sid = rng.choice(live)
            for hsh, _, _ in self._chain(sessions.pop(sid)):
                if st.discard(hsh):
                    h.kv_tier_log.append({"op": "discard", "hash": hsh})
