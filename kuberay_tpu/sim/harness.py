"""SimHarness: the deterministic chaos loop.

Wires the existing deterministic trio — ``ObjectStore`` (with the fault
interposer installed), ``Manager.run_until_idle`` (on a virtual clock),
``FakeKubelet`` — plus all five controllers (TpuCluster, TpuJob,
TpuService, TpuCronJob, WarmSlicePool) into an

    inject -> drain -> check

step loop.  Each step: the scenario mutates the workload, the fault plan
arms and applies its seeded faults interleaved with partial queue
drains, the harness settles to quiescence in virtual time, and the
invariant checkers examine the converged state.  Every store event lands
in an append-only journal whose hash is the run's fingerprint: same seed
and scenario, same hash — the replay contract.

The harness is a context manager (it rebinds controlplane ``time`` to
the virtual clock and flips feature gates); always use ``with
SimHarness(...) as h`` or call ``close()``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import random
from typing import Any, Dict, List, Optional

from kuberay_tpu.controlplane.cluster_controller import TpuClusterController
from kuberay_tpu.controlplane.cronjob_controller import TpuCronJobController
from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.job_controller import TpuJobController
from kuberay_tpu.controlplane.quota import QuotaManager
from kuberay_tpu.scheduler.gang import GangScheduler
from kuberay_tpu.controlplane.manager import (
    Manager,
    originated_from_mapper,
    owned_pod_mapper,
)
from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.controlplane.service_controller import TpuServiceController
from kuberay_tpu.controlplane.store import Conflict, NotFound, ObjectStore
from kuberay_tpu.controlplane.upgrade import BurnRateGate
from kuberay_tpu.controlplane.warmpool_controller import (
    KIND_WARM_POOL,
    LABEL_WARM_POOL,
    WarmSlicePoolController,
)
from kuberay_tpu.obs import (
    AlertEngine,
    FlightRecorder,
    GoodputLedger,
    NOOP_TRACER,
    StepTracker,
    Tracer,
    TransitionRecorder,
)
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.sim.clock import VirtualClock, patch_time
from kuberay_tpu.sim.faults import (
    DCN_PARTITION,
    DELETE_RACE,
    LEADER_FAILOVER,
    POD_KILL,
    PREEMPTION_NOTICE,
    SLICE_DRAIN,
    SLOW_HOST,
    SLOW_START,
    FaultPlan,
)
from kuberay_tpu.sim.invariants import CheckContext, Violation, run_checkers
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.metrics import (
    SERVE_LATENCY_BUCKETS,
    ControlPlaneMetrics,
)
from kuberay_tpu.utils.names import serve_service_name

#: Kinds the simulated operator reconciles (the five controllers).
SIM_KINDS = (C.KIND_CLUSTER, C.KIND_JOB, C.KIND_SERVICE, C.KIND_CRONJOB,
             KIND_WARM_POOL)

#: Journal-excluded kinds: Events are telemetry, not state (and
#: excluding them keeps quiescence detection honest — a reconciler
#: re-emitting warnings forever must not look like progress).  Their
#: names/timestamps ARE deterministic under sim now (the harness threads
#: the virtual clock + a counter name-factory into EventRecorder), but
#: they stay excluded to preserve the PR-2 hash contract.
_JOURNAL_SKIP_KINDS = ("Event",)


@dataclasses.dataclass
class SimResult:
    scenario: str
    seed: int
    steps: int
    violations: List[Violation]
    journal_len: int
    journal_hash: str
    faults_injected: Dict[str, int]
    converged: bool = True

    @property
    def ok(self) -> bool:
        return not self.violations

    def replay_command(self) -> str:
        return (f"python -m kuberay_tpu.sim --scenario {self.scenario} "
                f"--seed {self.seed} --steps {self.steps}")


def _warm_pod_mapper(ev):
    """Warm pods carry the pool label; their churn re-reconciles it
    (same mapper the operator installs)."""
    if ev.kind != "Pod":
        return None
    md = ev.obj.get("metadata", {})
    pool = md.get("labels", {}).get(LABEL_WARM_POOL)
    if not pool:
        return None
    return (KIND_WARM_POOL, md.get("namespace", "default"), pool)


class SimHarness:
    def __init__(self, seed: int, scenario=None,
                 fault_profile: Optional[Dict[str, float]] = None,
                 settle_horizon: float = 45.0,
                 max_settle_rounds: int = 400,
                 trace: bool = False,
                 goodput: bool = False,
                 alerts: bool = False,
                 steps: bool = False,
                 incidents: bool = False,
                 shards: Optional[int] = None):
        self.seed = seed
        self.scenario = scenario
        # Reconcile shard count: explicit arg wins, else the scenario's
        # (shard-restart runs 4 pools), else the classic single queue —
        # whose processing order is the byte-identical replay contract.
        self.shards = (shards if shards is not None
                       else getattr(scenario, "shards", 1) or 1)
        self.settle_horizon = settle_horizon
        self.max_settle_rounds = max_settle_rounds
        self.converged = True

        self.clock = VirtualClock()
        self._patch = patch_time(self.clock)
        self._patch.__enter__()
        # Scenario gates ride on top of the baseline (e.g. upgrade
        # scenarios flip TpuServiceIncrementalUpgrade); classic
        # scenarios declare none, so their gate set — and therefore
        # their journal hashes — are unchanged.
        features.set_gates({"TpuCronJob": True, "WarmSlicePools": True,
                            **(getattr(scenario, "extra_gates", None)
                               or {})})

        profile = fault_profile
        if profile is None and scenario is not None:
            profile = scenario.profile
        self.plan = FaultPlan(seed, profile=profile)
        self.plan.bind_clock(self.clock.now)
        self.plan.on_inject = lambda fault: self.metrics.registry.inc(
            "sim_faults_injected_total", {"fault": fault})

        uid_counter = iter(range(1, 1 << 30))
        # dispatch="sync" is load-bearing, not a default-by-accident:
        # inline watch delivery on the mutating thread is what makes a
        # seed's event history a pure function of the fault plan — the
        # byte-identical journal-hash contract.  Guarded below so a
        # future store default flip cannot silently break replays.
        self.store = ObjectStore(
            uid_factory=lambda: f"sim-uid-{next(uid_counter):06d}",
            dispatch="sync")
        if self.store._dispatch_mode != "sync":
            raise RuntimeError(
                "SimHarness requires a sync-dispatch store: async watch "
                "fan-out would decouple delivery order from the seeded "
                "fault plan and break journal-hash determinism")
        self.metrics = ControlPlaneMetrics()
        self.metrics.registry.describe(
            "sim_faults_injected_total",
            "Faults injected by the simulation fault plan, per fault type")
        # Tracing is observational only (touches neither store nor rng),
        # so the journal hash is byte-identical with it on or off — the
        # replay-invariance contract tests/test_obs_trace.py enforces.
        self.tracer = Tracer(clock=self.clock) if trace else NOOP_TRACER
        # Flight rows recorded inside an active span carry its trace_id
        # (observational: the stamp reads the tracer's thread-local).
        self.flight = (FlightRecorder(clock=self.clock, tracer=self.tracer)
                       if trace else None)
        # SLO burn-rate alerting (obs.alerts): observational only — it
        # reads metric snapshots and the virtual clock, never the store
        # or rng, so the journal hash is byte-identical with the engine
        # on or off (the invariance contract in tests/test_alerts.py).
        self.alerts = (AlertEngine(self.metrics.registry, clock=self.clock)
                       if alerts else None)
        # Goodput ledger (obs.goodput): observational only — it reads
        # watch events and the virtual clock, never the store or rng, so
        # the journal hash is byte-identical with the ledger on or off
        # (the exactness + invariance contract in tests/test_goodput.py).
        self.goodput = (GoodputLedger(clock=self.clock,
                                      metrics=self.metrics)
                        if goodput else None)
        transitions = (TransitionRecorder(flight=self.flight,
                                          ledger=self.goodput,
                                          clock=self.clock)
                       if goodput else None)
        self._goodput_cancel = (self.store.watch(self.goodput.observe_event)
                                if goodput else None)
        # Step-telemetry microscope (obs.steps): observational only —
        # heartbeats are synthesized by emit_training_steps from state
        # the harness already owns, the tracker reads only the virtual
        # clock, so the journal hash is byte-identical with the
        # microscope on or off (tests/test_sim_steps.py).  Sim job ids
        # are "ns/cluster", so stall edges land on the cluster's own
        # goodput/flight key.
        self.steps = (StepTracker(
            clock=self.clock, metrics=self.metrics, flight=self.flight,
            goodput=self.goodput,
            goodput_key=lambda job_id: (C.KIND_CLUSTER,) + tuple(
                job_id.split("/", 1))) if steps else None)
        # Deterministic event emission (obs satellite): virtual-clock
        # eventTime + counter names replace wall time and uuid4, so a
        # seed replays with identical Event objects across processes.
        self._event_seq = itertools.count(1)
        self.recorder = EventRecorder(
            self.store, clock=self.clock,
            name_factory=lambda base:
                f"{base}.evt{next(self._event_seq):06d}")
        self.manager = Manager(self.store, clock=self.clock,
                               metrics=self.metrics, tracer=self.tracer,
                               flight=self.flight, shards=self.shards)

        self.clients: Dict[str, FakeCoordinatorClient] = {}

        def provider(status_or_name, status=None):
            # Job controller calls provider(status); service controller
            # calls provider(cluster_name, status).  Key clients by the
            # cluster name when given, else by the head service in status.
            if status is None:
                status = status_or_name or {}
                name = status.get("headServiceName", "") or "cluster"
            else:
                name = status_or_name
            return self.clients.setdefault(name, FakeCoordinatorClient())

        # Multi-tenant quota seam: when the scenario opts in, the
        # QuotaManager (clocked off the virtual clock, so starvation
        # bounds and reclaim notices replay exactly) backs a
        # GangScheduler mounted into the cluster/job/cron controllers.
        # Classic scenarios mount neither, so no PodGroup objects or
        # verdict writes appear and their journal hashes are unchanged.
        self.quota = None
        gang = None
        if scenario is not None and getattr(scenario, "quota", False):
            self.quota = QuotaManager(self.store, metrics=self.metrics,
                                      clock=self.clock.now)
            gang = GangScheduler(self.store, quota=self.quota,
                                 metrics=self.metrics,
                                 clock=self.clock.now)
        # Warm pool first: the cluster controller claims warm slices from
        # it on a preemption notice (warm pre-replacement), and fires the
        # checkpoint-drain hook through the coordinator client provider.
        self.warmpool_controller = WarmSlicePoolController(
            self.store, recorder=self.recorder, tracer=self.tracer)
        self.cluster_controller = TpuClusterController(
            self.store, expectations=self.manager.expectations,
            recorder=self.recorder, metrics=self.metrics,
            tracer=self.tracer, transitions=transitions,
            warmpool=self.warmpool_controller,
            scheduler=gang,
            client_provider=lambda status: provider(status))
        self.job_controller = TpuJobController(
            self.store, recorder=self.recorder,
            client_provider=lambda status: provider(status),
            metrics=self.metrics, tracer=self.tracer,
            transitions=transitions, scheduler=gang)
        # Burn-rate gate over the green fleet: observational (registry
        # snapshots + virtual clock only), fed by the serve-traffic pump
        # when a scenario mounts it; vacuously healthy otherwise.
        self.upgrade_gate = BurnRateGate(self.metrics.registry,
                                         clock=self.clock)
        # Upgrade/scale decision audit (autoscaler.DecisionAudit),
        # mounted UNCONDITIONALLY: it is ring-append-only (clock reads,
        # no store writes, no rng) so journal hashes are unchanged, and
        # the incident engine's rollback triggers need the ring whether
        # or not bundles are being captured this run.
        from kuberay_tpu.controlplane.autoscaler import DecisionAudit
        self.audit = DecisionAudit(clock=self.clock)
        self.service_controller = TpuServiceController(
            self.store, recorder=self.recorder,
            client_provider=lambda cname, status: provider(cname, status),
            tracer=self.tracer, transitions=transitions,
            clock=self.clock, upgrade_gate=self.upgrade_gate,
            flight=self.flight, metrics_registry=self.metrics.registry,
            audit=self.audit)
        self.cronjob_controller = TpuCronJobController(
            self.store, recorder=self.recorder, tracer=self.tracer,
            scheduler=gang)

        m = self.manager
        m.register(C.KIND_CLUSTER, self.cluster_controller.reconcile)
        m.register(C.KIND_JOB, self.job_controller.reconcile)
        m.register(C.KIND_SERVICE, self.service_controller.reconcile)
        m.register(C.KIND_CRONJOB, self.cronjob_controller.reconcile)
        m.register(KIND_WARM_POOL, self.warmpool_controller.reconcile)
        m.map_owned(owned_pod_mapper)
        m.map_owned(originated_from_mapper(C.KIND_JOB))
        m.map_owned(originated_from_mapper(C.KIND_SERVICE))
        m.map_owned(originated_from_mapper(C.KIND_CRONJOB))
        m.map_owned(_warm_pod_mapper)

        self.kubelet = FakeKubelet(self.store, now_fn=self.clock.now,
                                   tracer=self.tracer)
        self.store.set_interposer(self.plan)

        self.journal: List[Dict[str, Any]] = []
        self._journal_rv = 0
        # Upgrade-era observability feeds (invariants.CheckContext):
        # every TrafficRoute SPEC mutation is logged with the green
        # ring readiness observed at write time (the watcher is
        # read-only, so mounting it never perturbs journal hashes), and
        # the serve-traffic pump appends its per-round client outcomes.
        # Classic scenarios create no routes: both logs stay empty.
        self.route_weight_log: List[Dict[str, Any]] = []
        self.serve_traffic_log: List[Dict[str, Any]] = []
        # KV-tier seam feed (invariants no-stale-block): only the
        # session-churn scenario appends; classic scenarios leave it
        # empty so the checker is vacuous and journal hashes hold.
        self.kv_tier_log: List[Dict[str, Any]] = []
        self._route_specs: Dict[str, str] = {}
        self._route_watch_cancel = self.store.watch(
            self._observe_route_event)
        self._failover_count = 0
        self._step = 0
        # Preemption machinery: (kill deadline, ns, slice) for slices
        # under an advance notice, and (ns, cluster) -> partition-window
        # end for clusters whose DCN connectivity is severed.
        self._pending_kills: List[tuple] = []
        self._partitioned_until: Dict[tuple, float] = {}
        # Slow-host fault machinery: (ns, cluster, pod) -> remaining
        # slow training steps, plus the ground-truth log of every window
        # (first slow heartbeat ts -> first recovered heartbeat ts) the
        # straggler-detection checker and the goodput-exactness gate
        # compare the tracker's verdicts against.  Maintained whether or
        # not telemetry is mounted so the fault plan's rng stream cannot
        # depend on the telemetry flag.
        self._slow_hosts: Dict[tuple, int] = {}
        self._train_step_idx: Dict[tuple, int] = {}
        self.slow_host_log: List[Dict[str, Any]] = []
        # Preemption-notice ground truth (every notice delivered, fault-
        # injected or scripted) — the incident engine's preemption feed.
        # Maintained whether or not the engine is mounted, so the rng
        # stream and journal hash cannot depend on the incidents flag.
        self.notice_log: List[Dict[str, Any]] = []
        # Scenario-scripted dead backends: the serve pump treats these
        # services as unable to serve even with ready rings (a dead
        # green build whose pods run but whose server misbehaves).
        # Empty for every classic scenario, so their pump behavior —
        # and journal hashes — are unchanged.
        self.dead_backends: set = set()
        # Container images whose serve endpoint is dead on arrival: any
        # backend whose backing cluster runs one of these images is
        # unserveable regardless of ring readiness (the dead-green-
        # upgrade drill — the bad BUILD is the fault, so the marker
        # follows the image through whatever cluster the upgrade
        # controller mints for it).  Empty by default: hashes unchanged.
        self.dead_images: set = set()
        # Incident forensics engine (obs/incident.py): observational
        # only — it reads the virtual clock and the mounted evidence
        # surfaces, never the store or rng, so the journal hash is
        # byte-identical with the engine on or off (the invariance
        # contract in tests/test_incident.py).
        self.incidents = None
        if incidents:
            from kuberay_tpu.obs import IncidentEngine
            self.incidents = IncidentEngine(
                clock=self.clock, registry=self.metrics.registry,
                tracer=(self.tracer if trace else None),
                flight=self.flight, goodput=self.goodput,
                alerts=self.alerts, steps=self.steps,
                audit=self.audit, quota=self.quota)
            self.incidents.add_feed(lambda: [
                {"kind": "preemption-notice",
                 "key": f"{e['ns']}/{e['slice']}",
                 "ts": e["ts"], "trigger": True,
                 "summary": (f"preemption notice on slice {e['slice']} "
                             f"(kill deadline {e['deadline']:.1f}s)")}
                for e in self.notice_log])

        if scenario is not None:
            with self.plan.suspended():
                scenario.setup(self)
            self.settle()

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        self.store.set_interposer(None)
        if self._goodput_cancel is not None:
            self._goodput_cancel()
        self._route_watch_cancel()
        self.kubelet.close()
        features.reset()
        self._patch.__exit__(None, None, None)

    def __enter__(self) -> "SimHarness":
        return self

    def __exit__(self, *exc):
        self.close()
        return None

    # -- journal -----------------------------------------------------------

    def _drain_journal(self):
        events, latest, truncated = self.store.events_since(self._journal_rv)
        if truncated:
            # Only possible if a settle round emitted >10k events without
            # draining; record it so the hash can't silently lie.
            self.journal.append({"type": "JOURNAL-TRUNCATED",
                                 "rv": latest})
        for erv, ev in events:
            if ev.kind in _JOURNAL_SKIP_KINDS:
                continue
            md = ev.obj.get("metadata", {})
            rec = {
                "type": ev.type, "kind": ev.kind,
                "ns": md.get("namespace", "default"),
                "name": md.get("name", ""),
                "rv": erv, "uid": md.get("uid", ""),
            }
            # Preemption lifecycle keys, appended ONLY when present so
            # runs without notices keep their pre-extension record shape
            # (and therefore their byte-identical journal hashes).
            if ev.kind == "Pod":
                ann = md.get("annotations") or {}
                if C.ANNOTATION_PREEMPTION_NOTICE in ann:
                    rec["notice"] = ann[C.ANNOTATION_PREEMPTION_NOTICE]
                if C.ANNOTATION_DRAINED_AT in ann:
                    rec["drained"] = ann[C.ANNOTATION_DRAINED_AT]
            self.journal.append(rec)
        self._journal_rv = latest

    def journal_hash(self) -> str:
        h = hashlib.sha256()
        for rec in self.journal:
            h.update(json.dumps(rec, sort_keys=True).encode())
            h.update(b"\n")
        return h.hexdigest()

    def export_trace(self) -> Dict[str, Any]:
        """The run's causal timeline as one artifact: every recorded
        span (parent-linked; empty when tracing is off) plus the state
        journal as span-events — what a failure report ships so a
        violation replays WITH its decomposition (docs/observability.md).
        """
        return {
            "scenario": self.scenario.name if self.scenario else "adhoc",
            "seed": self.seed,
            "clock": self.clock.now(),
            "journal_hash": self.journal_hash(),
            "spans": self.tracer.export(),
            "events": list(self.journal),
            "flight": self.flight.to_dict() if self.flight else {},
            "goodput": self.goodput.to_dict() if self.goodput else {},
            "alerts": self.alerts.to_dict() if self.alerts else {},
            "steps": self.steps.to_dict() if self.steps else {},
        }

    def export_profile(self) -> Dict[str, Any]:
        """Critical-path profile of the run (obs/profile.py): per-span-
        kind exclusive self-time percentiles over every closed
        ``slice-ready`` (and, when serve traffic ran, ``serve-request``)
        window.  Pure function of the recorded spans — with the virtual
        clock and counter span ids the artifact is byte-identical across
        re-runs of a seed (tools/obs_smoke.sh holds that line)."""
        from kuberay_tpu.obs.profile import profile_spans
        return profile_spans(self.tracer.export(), meta={
            "scenario": self.scenario.name if self.scenario else "adhoc",
            "seed": self.seed,
            "journal_hash": self.journal_hash(),
        })

    def export_incidents(self) -> Dict[str, Any]:
        """Every incident bundle the run opened, oldest first, under the
        run's identity (scenario/seed/journal hash).  With the virtual
        clock, counter ids and lexicographic tie-breaks the document is
        byte-identical across re-runs of a (scenario, seed) pair —
        tools/sim_smoke.sh ``cmp``s two exports to hold that line."""
        return {
            "schema": "tpu-incident-export/v1",
            "scenario": self.scenario.name if self.scenario else "adhoc",
            "seed": self.seed,
            "journal_hash": self.journal_hash(),
            "incidents": (list(reversed(self.incidents.bundles()))
                          if self.incidents is not None else []),
        }

    # -- convergence -------------------------------------------------------

    def settle(self, horizon: Optional[float] = None) -> int:
        """Drain to quiescence in virtual time; returns rounds used.

        A round runs the manager queue, steps the kubelet, redelivers
        due deferred watch events, sweeps orphans (the GC controller's
        role), and auto-drives serve apps.  When nothing progressed, the
        virtual clock advances to the next scheduled wakeup (timed
        requeue, deferred event, slow-start release) within ``horizon``;
        past the horizon the state is declared converged.  A final
        full-resync round models the informers' periodic relist — it
        recovers anything a dropped watch event orphaned."""
        deadline = self.clock.now() + (horizon if horizon is not None
                                       else self.settle_horizon)
        resynced = False
        rounds = 0
        while rounds < self.max_settle_rounds:
            rounds += 1
            # Progress = journal growth (state-object events; the journal
            # skips Event telemetry, so a reconciler that only re-emits
            # warnings forever cannot defeat quiescence detection).
            journal_before = len(self.journal)
            self.manager.run_until_idle()
            self.kubelet.step()
            killed = self._fire_due_kills()
            parted = self._sync_partitions()
            due = self.plan.pop_due_deferred(self.clock.now())
            for ev in due:
                self.store.redeliver(ev)
            drove = self._drive_serve_apps()
            # Serve-traffic pump (scenario-gated): its request sends are
            # observational (metrics + pump log only) so they must NOT
            # count as progress — only its ack writes do, and those show
            # up as journal growth like any other store mutation.
            self._pump_serve_traffic()
            swept = self._gc_orphans()
            self._drain_journal()
            fired = (self.alerts.evaluate()
                     if self.alerts is not None else None)
            if self.incidents is not None:
                self.incidents.evaluate(fired)
            if len(self.journal) > journal_before or due or drove or swept \
                    or killed or parted:
                resynced = False
                continue
            nxt = self._next_wakeup()
            if nxt is not None and nxt <= deadline:
                self.clock.advance_to(nxt + 1e-6)
                continue
            if not resynced:
                # Informer relist: recovers state stranded by dropped
                # watch events.  One relist per quiet period — a second
                # quiet relist means the state is truly converged.
                self._resync_all()
                self.kubelet.resync()
                resynced = True
                continue
            return rounds
        self.converged = False
        return rounds

    def _next_wakeup(self) -> Optional[float]:
        candidates = [t for t in (self.manager.next_delayed_at(),
                                  self.plan.next_deferred_at(),
                                  self.kubelet.next_hold_at(),
                                  self._next_kill_at(),
                                  self._next_partition_end())
                      if t is not None]
        return min(candidates) if candidates else None

    def _next_kill_at(self) -> Optional[float]:
        return (min(t for t, _, _ in self._pending_kills)
                if self._pending_kills else None)

    def _next_partition_end(self) -> Optional[float]:
        return (min(self._partitioned_until.values())
                if self._partitioned_until else None)

    def _resync_all(self):
        for kind in SIM_KINDS:
            for obj in self.store.list(kind):
                md = obj["metadata"]
                self.manager.enqueue((kind, md.get("namespace", "default"),
                                      md.get("name", "")))

    def _gc_orphans(self) -> int:
        """Owner-reference GC sweep, level-triggered like the real GC
        controller: cascade deletes interrupted by injected faults are
        retried here instead of orphaning dependents forever."""
        live_uids = set()
        objs = []
        for kind in self.store.kinds():
            for obj in self.store.list(kind):
                live_uids.add(obj["metadata"].get("uid"))
                objs.append(obj)
        swept = 0
        for obj in objs:
            refs = obj["metadata"].get("ownerReferences") or []
            if not refs or any(r.get("uid") in live_uids for r in refs):
                continue
            try:
                self.store.delete(obj["kind"],
                                  obj["metadata"]["name"],
                                  obj["metadata"].get("namespace", "default"))
                swept += 1
            except (NotFound, Conflict):
                continue    # retried on the next sweep
        return swept

    def _drive_serve_apps(self) -> bool:
        """Stand-in for the serve runtime: once a cluster's serve config
        lands on its coordinator, the app reports RUNNING."""
        changed = False
        for name in sorted(self.clients):
            client = self.clients[name]
            if client.serve_config is not None and not client.serve_apps:
                client.set_serve_app("app", "RUNNING")
                changed = True
        return changed

    # -- upgrade traffic: route watcher + deterministic serve pump ---------

    def _cluster_for_serve_service(self, ns: str, svc_name: str) -> str:
        """Resolve a route backend's per-cluster serve Service back to
        the TpuCluster that owns it (names are derived, not labeled)."""
        for obj in self.store.list(C.KIND_CLUSTER, ns):
            cname = obj["metadata"]["name"]
            if serve_service_name(cname) == svc_name:
                return cname
        return ""

    def _cluster_runs_dead_image(self, ns: str, cname: str) -> bool:
        """True when any container image of the cluster's template is in
        ``dead_images`` (the dead-on-arrival build marker)."""
        if not self.dead_images:
            return False
        obj = self.store.try_get(C.KIND_CLUSTER, cname, ns)
        if obj is None:
            return False
        spec = obj.get("spec") or {}
        groups = [spec.get("headGroupSpec") or {}] + \
            list(spec.get("workerGroupSpecs") or [])
        for g in groups:
            tmpl = g.get("template") or {}
            for cont in (tmpl.get("spec") or {}).get("containers", []):
                if cont.get("image") in self.dead_images:
                    return True
        return False

    def _whole_ready_rings(self, ns: str, cname: str) -> int:
        """Fully-Ready ICI rings of a cluster right now: slices whose
        whole multi-host pod set is Running (the same whole-ring measure
        the service controller's wave/weight logic reads)."""
        obj = self.store.try_get(C.KIND_CLUSTER, cname, ns)
        if obj is None:
            return 0
        cluster = TpuCluster.from_dict(obj)
        hosts_per = {g.groupName: g.slice_topology().num_hosts
                     for g in cluster.spec.workerGroupSpecs}
        slices: Dict[tuple, List[dict]] = {}
        for p in self.store.list("Pod", ns,
                                 labels={C.LABEL_CLUSTER: cname,
                                         C.LABEL_NODE_TYPE:
                                         C.NODE_TYPE_WORKER}):
            if p["metadata"].get("deletionTimestamp"):
                continue
            labels = p["metadata"]["labels"]
            key = (labels.get(C.LABEL_GROUP),
                   labels.get(C.LABEL_SLICE_NAME))
            slices.setdefault(key, []).append(p)
        ready = 0
        for (gname, _sname), ps in slices.items():
            want = hosts_per.get(gname, 0)
            if want > 0 and len(ps) >= want and all(
                    p.get("status", {}).get("phase") == "Running"
                    for p in ps):
                ready += 1
        return ready

    def _observe_route_event(self, ev):
        """Read-only TrafficRoute watcher: snapshot every SPEC mutation
        together with the ring readiness at write time, for the
        weighted-ring-atomicity checker.  Status-only writes (gateway
        acks) are skipped — ring state may legitimately have moved on
        since the weights were chosen."""
        if ev.kind != "TrafficRoute":
            return
        md = ev.obj.get("metadata", {})
        name = md.get("name", "")
        if ev.type == "DELETED":
            self._route_specs.pop(name, None)
            return
        backends = (ev.obj.get("spec") or {}).get("backends") or []
        sig = json.dumps(backends, sort_keys=True)
        if self._route_specs.get(name) == sig:
            return
        self._route_specs[name] = sig
        ns = md.get("namespace", "default")
        svc_name = md.get("labels", {}).get(
            C.LABEL_ORIGINATED_FROM_CR_NAME, "")
        pending_cluster = ""
        desired = 0
        svc = (self.store.try_get(C.KIND_SERVICE, svc_name, ns)
               if svc_name else None)
        if svc is not None:
            pend = (svc.get("status") or {}).get(
                "pendingServiceStatus") or {}
            pending_cluster = pend.get("clusterName", "")
            desired = sum(
                int(g.get("replicas", 0) or 0)
                for g in (svc.get("spec", {}).get("clusterSpec", {})
                          .get("workerGroupSpecs") or []))
        entry = {"ts": round(self.clock.now(), 3), "route": name,
                 "backends": []}
        for b in backends:
            bsvc = b.get("service", "")
            cname = self._cluster_for_serve_service(ns, bsvc)
            entry["backends"].append({
                "service": bsvc,
                "weight": int(b.get("weight", 0) or 0),
                "role": ("green" if cname and cname == pending_cluster
                         else "blue"),
                "ready_rings": (self._whole_ready_rings(ns, cname)
                                if cname else 0),
                "desired_rings": desired,
            })
        self.route_weight_log.append(entry)

    #: Client requests the pump fires per settle round per route.
    PUMP_REQUESTS = 4

    def _pump_serve_traffic(self) -> int:
        """Stand-in for the serve gateway under live load, rng-free:
        every settle round it splits a fixed request count across the
        route's backends by weight, lands attempts/errors/latency on the
        per-backend series the burn-rate gate reads, fails over from a
        ringless backend to a healthy peer (client-visible failures only
        when NOBODY can serve), and acks the route's prewarm/drain
        handshake flags the way the real gateway would.  Mounted only
        when the scenario opts in (serve_traffic=True); ack writes are
        store mutations and therefore count as settle progress through
        the journal."""
        if self.scenario is None or \
                not getattr(self.scenario, "serve_traffic", False):
            return 0
        acks = 0
        for route in sorted(
                self.store.list("TrafficRoute"),
                key=lambda o: (o["metadata"].get("namespace", "default"),
                               o["metadata"].get("name", ""))):
            acks += self._pump_route(route)
        return acks

    def _pump_route(self, route: dict) -> int:
        ns = route["metadata"].get("namespace", "default")
        name = route["metadata"].get("name", "")
        backends = (route.get("spec") or {}).get("backends") or []
        if not backends:
            return 0
        reg = self.metrics.registry
        serveable: Dict[str, bool] = {}
        for b in backends:
            bsvc = b.get("service", "")
            cname = self._cluster_for_serve_service(ns, bsvc)
            serveable[bsvc] = bool(cname) and \
                self._whole_ready_rings(ns, cname) > 0 and \
                bsvc not in self.dead_backends and \
                not self._cluster_runs_dead_image(ns, cname)
        total_w = sum(int(b.get("weight", 0) or 0) for b in backends)
        sent = failed = failovers = 0
        if total_w > 0:
            # Largest-remainder split of the round's requests by weight,
            # remainder to earlier (higher-weight-first is the route's
            # own backend order for the active cluster) — deterministic.
            counts = [self.PUMP_REQUESTS * int(b.get("weight", 0) or 0)
                      // total_w for b in backends]
            pos = [j for j, b in enumerate(backends)
                   if int(b.get("weight", 0) or 0) > 0]
            for i in range(self.PUMP_REQUESTS - sum(counts)):
                counts[pos[i % len(pos)]] += 1
            for b, n in zip(backends, counts):
                bsvc = b.get("service", "")
                for _ in range(n):
                    sent += 1
                    reg.inc("tpu_gateway_backend_attempts_total",
                            {"backend": bsvc})
                    if serveable.get(bsvc):
                        reg.observe("tpu_gateway_backend_latency_seconds",
                                    0.05, {"backend": bsvc},
                                    buckets=SERVE_LATENCY_BUCKETS)
                        continue
                    # The weighted pick cannot serve (no whole ring):
                    # error lands on ITS series — the gate must see the
                    # bad backend — then the request fails over.
                    reg.inc("tpu_gateway_backend_errors_total",
                            {"backend": bsvc})
                    peer = next(
                        (o.get("service", "") for o in sorted(
                            backends,
                            key=lambda o: (-int(o.get("weight", 0) or 0),
                                           o.get("service", "")))
                         if o.get("service", "") != bsvc
                         and serveable.get(o.get("service", ""))), None)
                    if peer is None:
                        failed += 1
                        continue
                    failovers += 1
                    reg.inc("tpu_gateway_backend_attempts_total",
                            {"backend": peer})
                    reg.observe("tpu_gateway_backend_latency_seconds",
                                0.05, {"backend": peer},
                                buckets=SERVE_LATENCY_BUCKETS)
        if sent:
            self.serve_traffic_log.append({
                "ts": round(self.clock.now(), 3), "route": name,
                "requests": sent, "failed": failed,
                "failovers": failovers})
        # Gateway-side handshake acks: prewarm immediately (the sim has
        # no real KV cache to replay into), drain immediately (no real
        # in-flight set to wait out).
        status = route.get("status") or {}
        ack: Dict[str, Dict] = {}
        for b in backends:
            bsvc = b.get("service", "")
            if b.get("prewarm") and \
                    bsvc not in (status.get("prewarmed") or {}):
                ack.setdefault("prewarmed", {})[bsvc] = \
                    int(b.get("prewarm") or 0)
            if b.get("drain") and \
                    bsvc not in (status.get("drained") or {}):
                ack.setdefault("drained", {})[bsvc] = True
        if not ack:
            return 0
        try:
            self.store.patch("TrafficRoute", name, ns, {"status": ack},
                             subresource="status")
        except (NotFound, Conflict):
            return 0
        return 1

    def succeed_jobs(self) -> int:
        """Scenario helper: every non-terminal submitted job succeeds."""
        changed = 0
        for name in sorted(self.clients):
            client = self.clients[name]
            for jid in sorted(client.jobs):
                if client.jobs[jid].status not in ("SUCCEEDED", "FAILED",
                                                   "STOPPED"):
                    client.set_job_status(jid, "SUCCEEDED")
                    changed += 1
        return changed

    # -- training-step heartbeats / slow hosts -----------------------------

    def _open_slow_entry(self, ns: str, cluster: str,
                         host: str) -> Optional[Dict[str, Any]]:
        for entry in reversed(self.slow_host_log):
            if (entry["ns"] == ns and entry["cluster"] == cluster
                    and entry["host"] == host
                    and entry["clear_ts"] is None):
                return entry
        return None

    def emit_training_steps(self, namespace: str, cluster: str,
                            count: int = 1, base_dur: float = 1.0,
                            tokens: float = 2048.0) -> int:
        """Synthesize one synchronous training step per Running host of
        ``cluster``, ``count`` times: the virtual clock advances by the
        step's wall time (the slowest host's duration — synchronous
        data-parallel training runs at straggler speed), then every host
        reports its heartbeat.

        Runs UNCONDITIONALLY (telemetry on or off): the clock advance
        and the slow-window bookkeeping must be identical in both modes
        so the fault plan's rng stream — and therefore the journal
        hash — cannot depend on whether the tracker is mounted.  Only
        the ``observe()`` feed is gated.  RNG-free and store-free by
        construction.  Returns heartbeats emitted."""
        emitted = 0
        for _ in range(count):
            pods = sorted(
                p["metadata"]["name"]
                for p in self.store.list("Pod", namespace)
                if p["metadata"].get("labels", {}).get(C.LABEL_CLUSTER)
                == cluster
                and C.LABEL_SLICE_NAME in p["metadata"].get("labels", {})
                and not p["metadata"].get("deletionTimestamp")
                and p.get("status", {}).get("phase") == "Running")
            if not pods:
                continue
            key = (namespace, cluster)
            self._train_step_idx[key] = idx = \
                self._train_step_idx.get(key, 0) + 1
            durs = {
                pod: (base_dur * self.plan.slow_host_factor
                      if self._slow_hosts.get((namespace, cluster, pod),
                                              0) > 0
                      else base_dur)
                for pod in pods}
            wall = max(durs.values())
            self.clock.advance(wall)
            ts = self.clock.now()
            beats = []
            for pod in pods:
                pkey = (namespace, cluster, pod)
                dur = durs[pod]
                remaining = self._slow_hosts.get(pkey, 0)
                if remaining > 0:
                    if self._open_slow_entry(namespace, cluster,
                                             pod) is None:
                        self.slow_host_log.append({
                            "ns": namespace, "cluster": cluster,
                            "host": pod, "first_slow_step": idx,
                            "first_slow_ts": ts, "clear_step": None,
                            "clear_ts": None})
                    if remaining <= 1:
                        del self._slow_hosts[pkey]
                    else:
                        self._slow_hosts[pkey] = remaining - 1
                else:
                    entry = self._open_slow_entry(namespace, cluster, pod)
                    if entry is not None:
                        entry["clear_step"] = idx
                        entry["clear_ts"] = ts
                beats.append((pod, dur, tokens, wall - dur,
                              f"hb-{cluster}-{idx}-{pod}"))
                emitted += 1
            if self.steps is not None:
                # One fleet-synchronized ingestion call per step (the
                # batch seam the tracker amortizes its lock and fleet
                # recomputes across).
                self.steps.observe_fleet_step(
                    f"{namespace}/{cluster}", idx, beats, ts=ts,
                    n_params=1.0e9, device_count=len(pods) * 4,
                    peak_tflops=197.0)
        return emitted

    # -- preemption notices / DCN partitions -------------------------------

    def inject_preemption_notice(self, namespace: str, slice_name: str,
                                 delta: float) -> float:
        """Deliver an advance preemption warning for one slice: every
        pod of the slice gets the notice annotation (deadline = now +
        ``delta``), and the harness kills the slice at the deadline —
        the GKE maintenance-notice shape.  Returns the kill deadline."""
        deadline = self.clock.now() + delta
        with self.plan.suspended():
            self._notice_slice(namespace, slice_name, deadline)
        return deadline

    def _notice_slice(self, ns: str, sname: str, deadline: float) -> int:
        pods = self.store.list("Pod", ns,
                               labels={C.LABEL_SLICE_NAME: sname})
        stamped = 0
        for pod in pods:
            try:
                self.store.patch(
                    "Pod", pod["metadata"]["name"], ns,
                    {"metadata": {"annotations": {
                        C.ANNOTATION_PREEMPTION_NOTICE:
                            f"{deadline:.3f}"}}})
                stamped += 1
            except (NotFound, Conflict):
                continue
        if stamped:
            self._pending_kills.append((deadline, ns, sname))
            self.notice_log.append({
                "ts": round(self.clock.now(), 3), "ns": ns,
                "slice": sname, "deadline": round(deadline, 3)})
        return stamped

    def _fire_due_kills(self) -> int:
        """Preemption deadlines that have arrived: the warned slice dies
        now, whether or not the controller finished its drain."""
        now = self.clock.now()
        due = sorted(k for k in self._pending_kills if k[0] <= now)
        if not due:
            return 0
        self._pending_kills = [k for k in self._pending_kills
                               if k[0] > now]
        with self.plan.suspended():
            for _, ns, sname in due:
                self.kubelet.fail_slice(sname, ns)
        return len(due)

    def _partition_client_keys(self, ns: str, cname: str) -> List[str]:
        keys = {cname}
        obj = self.store.try_get(C.KIND_CLUSTER, cname, ns)
        if obj is not None:
            head_svc = (obj.get("status") or {}).get("headServiceName")
            if head_svc:
                keys.add(head_svc)
        return sorted(keys)

    def _sync_partitions(self) -> bool:
        """Reflect active DCN partition windows onto the cluster's
        coordinator clients (submit/poll/checkpoint raise while severed)
        and lift expired ones."""
        now = self.clock.now()
        changed = False
        for (ns, cname), until in sorted(self._partitioned_until.items()):
            severed = until > now
            for key in self._partition_client_keys(ns, cname):
                client = self.clients.get(key)
                if client is not None and client.partitioned != severed:
                    client.partitioned = severed
                    changed = True
        self._partitioned_until = {
            k: t for k, t in self._partitioned_until.items() if t > now}
        return changed

    # -- fault application -------------------------------------------------

    def _record_fault(self, fault: str):
        self.plan.record(fault)

    def _candidate_pods(self, phase: Optional[str] = None) -> List[dict]:
        pods = [p for p in self.store.list("Pod")
                if not p["metadata"].get("deletionTimestamp")]
        if phase is not None:
            pods = [p for p in pods
                    if p.get("status", {}).get("phase", "Pending") == phase]
        return pods

    def _apply_fault(self, fault: str) -> bool:
        rng = self.plan.rng
        with self.plan.suspended():
            if fault == POD_KILL:
                pods = self._candidate_pods()
                if not pods:
                    return False
                victim = rng.choice(pods)
                self.kubelet.fail_pod(victim["metadata"]["name"],
                                      victim["metadata"]["namespace"])
            elif fault == SLICE_DRAIN:
                slices = sorted({
                    (p["metadata"]["namespace"],
                     p["metadata"]["labels"][C.LABEL_SLICE_NAME])
                    for p in self._candidate_pods()
                    if C.LABEL_SLICE_NAME in p["metadata"]["labels"]})
                if not slices:
                    return False
                ns, sname = rng.choice(slices)
                self.kubelet.fail_slice(sname, ns)
            elif fault == SLOW_START:
                pods = self._candidate_pods(phase="Pending")
                if not pods:
                    return False
                victim = rng.choice(pods)
                self.kubelet.hold_pod(
                    victim["metadata"]["name"],
                    victim["metadata"]["namespace"],
                    until=self.clock.now() + self.plan.draw_slow_start())
            elif fault == DELETE_RACE:
                pods = self._candidate_pods()
                if not pods:
                    return False
                victim = rng.choice(pods)
                try:
                    self.store.delete("Pod", victim["metadata"]["name"],
                                      victim["metadata"]["namespace"])
                except NotFound:
                    return False
            elif fault == PREEMPTION_NOTICE:
                noticed = {(t[1], t[2]) for t in self._pending_kills}
                slices = sorted({
                    (p["metadata"]["namespace"],
                     p["metadata"]["labels"][C.LABEL_SLICE_NAME])
                    for p in self._candidate_pods()
                    if C.LABEL_SLICE_NAME in p["metadata"]["labels"]
                    and C.LABEL_CLUSTER in p["metadata"]["labels"]
                    and C.ANNOTATION_PREEMPTION_NOTICE not in
                    (p["metadata"].get("annotations") or {})})
                slices = [s for s in slices if s not in noticed]
                if not slices:
                    return False
                ns, sname = rng.choice(slices)
                deadline = self.clock.now() + self.plan.draw_notice_delta()
                if not self._notice_slice(ns, sname, deadline):
                    return False
            elif fault == DCN_PARTITION:
                clusters = sorted(
                    (c["metadata"].get("namespace", "default"),
                     c["metadata"]["name"])
                    for c in self.store.list(C.KIND_CLUSTER)
                    if not c["metadata"].get("deletionTimestamp"))
                if not clusters:
                    return False
                ns, cname = rng.choice(clusters)
                until = self.clock.now() + self.plan.draw_partition_window()
                try:
                    self.store.patch(
                        C.KIND_CLUSTER, cname, ns,
                        {"metadata": {"annotations": {
                            C.ANNOTATION_DCN_PARTITION_UNTIL:
                                f"{until:.3f}"}}})
                except (NotFound, Conflict):
                    return False
                key = (ns, cname)
                self._partitioned_until[key] = max(
                    until, self._partitioned_until.get(key, 0.0))
                self._sync_partitions()
            elif fault == SLOW_HOST:
                # One window at a time, and the previous window's
                # recovery heartbeat must have landed: overlapping
                # windows would blur the stall interval the
                # goodput-exactness gate measures.  Both guards read
                # harness state maintained identically with telemetry
                # on or off, so the rng stream stays mode-independent.
                if self._slow_hosts or any(e["clear_ts"] is None
                                           for e in self.slow_host_log):
                    return False
                hosts = sorted(
                    (p["metadata"]["namespace"],
                     p["metadata"]["labels"][C.LABEL_CLUSTER],
                     p["metadata"]["name"])
                    for p in self._candidate_pods(phase="Running")
                    if C.LABEL_CLUSTER in p["metadata"].get("labels", {})
                    and C.LABEL_SLICE_NAME in p["metadata"].get("labels",
                                                                {}))
                if not hosts:
                    return False
                ns, cname, pname = rng.choice(hosts)
                self._slow_hosts[(ns, cname, pname)] = \
                    self.plan.draw_slow_host_steps()
            elif fault == LEADER_FAILOVER:
                crs = []
                for kind in SIM_KINDS:
                    crs.extend(self.store.list(kind))
                if not crs:
                    return False
                # The new leader's informers replay every object (full
                # resync) and its first write races the old leader's
                # in-flight pass — modeled as a foreign no-op metadata
                # write that bumps the rv under every snapshot.
                target = rng.choice(crs)
                self._failover_count += 1
                md = target["metadata"]
                try:
                    self.store.patch(
                        target["kind"], md["name"],
                        md.get("namespace", "default"),
                        {"metadata": {"annotations": {
                            "tpu.dev/sim-failover":
                                str(self._failover_count)}}})
                except (NotFound, Conflict):
                    return False
                self._resync_all()
            else:
                return False
        self._record_fault(fault)
        return True

    def _partial_drain(self):
        """A bounded slice of work between injections, so faults land
        mid-convergence (not only at quiescent states)."""
        rng = self.plan.rng
        n = rng.randint(0, 12)
        if n:
            self.manager.run_until_idle(max_iterations=n)
        if rng.random() < 0.5:
            self.kubelet.step()

    # -- the loop ----------------------------------------------------------

    def check(self) -> List[Violation]:
        self._drain_journal()
        violations = run_checkers(CheckContext(
            self.store, self.journal, steps=self.steps,
            slow_host_log=self.slow_host_log,
            route_weight_log=self.route_weight_log,
            serve_traffic_log=self.serve_traffic_log,
            quota=self.quota, kv_tier_log=self.kv_tier_log))
        if not self.converged:
            violations.append(Violation(
                "convergence", f"step {self._step}",
                f"settle did not quiesce within {self.max_settle_rounds} "
                "rounds"))
        if self.incidents is not None and violations:
            self.incidents.observe_violations(violations)
        return violations

    def step(self) -> List[Violation]:
        """One inject -> drain -> check cycle; returns violations."""
        self._step += 1
        if self.scenario is not None:
            with self.plan.suspended():
                self.scenario.tick(self, self._step)
        for fault in self.plan.arm():
            self._partial_drain()
            self._apply_fault(fault)
        self.settle()
        # Final chaos-free settle: leftover interposer budgets must not
        # hold the state hostage at check time.
        self.plan.disarm()
        self.settle(horizon=10.0)
        return self.check()

    def run(self, steps: int, stop_on_violation: bool = True) -> SimResult:
        violations: List[Violation] = []
        ran = 0
        for _ in range(steps):
            ran += 1
            violations.extend(self.step())
            if violations and stop_on_violation:
                break
        return SimResult(
            scenario=self.scenario.name if self.scenario else "adhoc",
            seed=self.seed, steps=ran, violations=violations,
            journal_len=len(self.journal),
            journal_hash=self.journal_hash(),
            faults_injected={k: v for k, v in
                             sorted(self.plan.injected.items()) if v},
            converged=self.converged)
