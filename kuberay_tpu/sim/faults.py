"""Seeded fault schedules: every adversity a control plane meets, drawn
from one integer.

A :class:`FaultPlan` owns the only RNG in a simulation run, so the full
fault history — which mutations lose their rv race, which watch events
drop/duplicate/arrive late, which pods die, which slices drain, when the
leader fails over — is a pure function of ``seed``.  Replaying a seed
replays the exact interleaving that produced a violation (the
FoundationDB-style determinism contract).

Two delivery channels:

- the **store interposer** half (``on_mutation`` / ``on_event``) is
  installed via ``ObjectStore.set_interposer`` and fires inline on store
  traffic: injected ``Conflict`` models a lost optimistic-concurrency
  race; event filtering models informer drop/duplicate/latency;
- the **step faults** half (``draw_step_faults``) is consumed by the
  harness between drain rounds: pod kills, whole-slice drains, slow pod
  starts, delete races, leader failover.

Injection is budgeted per step (armed counts, decremented as consumed),
never open-ended probabilities — a run must eventually quiesce so the
invariant checkers examine a converged state.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from kuberay_tpu.controlplane.store import Conflict, Event

# Interposer-channel faults.
STORE_CONFLICT = "store_conflict"
WATCH_DROP = "watch_drop"
WATCH_DUP = "watch_dup"
WATCH_DELAY = "watch_delay"
# Step-channel faults (applied by the harness).
POD_KILL = "pod_kill"
SLICE_DRAIN = "slice_drain"
SLOW_START = "slow_start"
DELETE_RACE = "delete_race"
LEADER_FAILOVER = "leader_failover"

ALL_FAULTS = (STORE_CONFLICT, WATCH_DROP, WATCH_DUP, WATCH_DELAY,
              POD_KILL, SLICE_DRAIN, SLOW_START, DELETE_RACE,
              LEADER_FAILOVER)

# Extension step faults (preemption lifecycle).  Kept OUT of ALL_FAULTS
# on purpose: arm() draws one rng sample per ALL_FAULTS entry whether or
# not the profile enables it, so extending that tuple would shift the
# rng stream of every existing (scenario, seed) and break the
# byte-identical replay-hash contract.  EXT_FAULTS are drawn in a second
# loop only when a profile explicitly enables them.
PREEMPTION_NOTICE = "preemption_notice"
DCN_PARTITION = "dcn_partition"
SLOW_HOST = "slow_host"
EXT_FAULTS = (PREEMPTION_NOTICE, DCN_PARTITION, SLOW_HOST)

STEP_FAULTS = (POD_KILL, SLICE_DRAIN, SLOW_START, DELETE_RACE,
               LEADER_FAILOVER, PREEMPTION_NOTICE, DCN_PARTITION,
               SLOW_HOST)

#: Default per-step arming weights; a scenario overrides with its own
#: profile (fault -> mean injections per step; 0 disables).
DEFAULT_PROFILE: Dict[str, float] = {
    STORE_CONFLICT: 0.6,
    WATCH_DROP: 0.4,
    WATCH_DUP: 0.4,
    WATCH_DELAY: 0.4,
    POD_KILL: 0.5,
    SLICE_DRAIN: 0.2,
    SLOW_START: 0.3,
    DELETE_RACE: 0.3,
    LEADER_FAILOVER: 0.2,
}

# Mutations the conflict injector never touches: losing a *delete*'s rv
# race is modeled by DELETE_RACE instead, and label patches are the warm
# pool claim path whose caller deliberately has no retry loop.
_CONFLICT_VERBS = ("create", "update", "update_status", "patch",
                   "add_finalizer", "remove_finalizer")

# Kinds whose events chaos never filters: Event objects are telemetry,
# and Lease traffic belongs to the (real-time) elector, not the sim.
_EVENT_EXEMPT_KINDS = ("Event", "Lease")


class FaultPlan:
    """Seeded, budgeted fault source.  Install on a store with
    ``store.set_interposer(plan)``; arm each step with ``arm()``; drive
    step-channel faults from ``draw_step_faults``."""

    def __init__(self, seed: int,
                 profile: Optional[Dict[str, float]] = None,
                 watch_delay_seconds: Tuple[float, float] = (0.5, 8.0),
                 slow_start_seconds: Tuple[float, float] = (1.0, 20.0),
                 notice_delta_seconds: Tuple[float, float] = (10.0, 25.0),
                 partition_window_seconds: Tuple[float, float] = (5.0, 15.0),
                 slow_host_steps: Tuple[int, int] = (8, 16),
                 slow_host_factor: float = 3.0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.profile = dict(DEFAULT_PROFILE)
        if profile is not None:
            self.profile.update(profile)
        self.watch_delay_seconds = watch_delay_seconds
        self.slow_start_seconds = slow_start_seconds
        self.notice_delta_seconds = notice_delta_seconds
        self.partition_window_seconds = partition_window_seconds
        self.slow_host_steps = slow_host_steps
        self.slow_host_factor = slow_host_factor
        self._armed: Dict[str, int] = {f: 0
                                       for f in ALL_FAULTS + EXT_FAULTS}
        self._suspended = False
        self._deferred: List[Tuple[float, Event]] = []
        self._now = lambda: 0.0     # bound by the harness (virtual clock)
        self.injected: Dict[str, int] = {f: 0
                                         for f in ALL_FAULTS + EXT_FAULTS}
        # Observer for every injection (harness exports it as the
        # ``sim_faults_injected_total{fault}`` counter).
        self.on_inject = lambda fault: None

    # -- harness wiring ----------------------------------------------------

    def bind_clock(self, now_fn) -> None:
        self._now = now_fn

    def arm(self) -> List[str]:
        """Draw this step's fault budget from the profile (Poisson-ish:
        floor(rate) guaranteed + fractional part as a coin).  Returns the
        step-channel faults to apply, in draw order; interposer-channel
        budgets accumulate internally."""
        step_faults: List[str] = []
        for fault in ALL_FAULTS:        # fixed order -> deterministic draws
            rate = self.profile.get(fault, 0.0)
            count = int(rate)
            if self.rng.random() < rate - count:
                count += 1
            if count <= 0:
                continue
            if fault in STEP_FAULTS:
                step_faults.extend([fault] * count)
            else:
                self._armed[fault] += count
        # Extension faults draw ONLY when enabled: a profile that never
        # names them consumes zero extra rng samples, so pre-extension
        # scenarios keep their exact replay hashes.
        for fault in EXT_FAULTS:
            rate = self.profile.get(fault, 0.0)
            if rate <= 0.0:
                continue
            count = int(rate)
            if self.rng.random() < rate - count:
                count += 1
            if count > 0:
                step_faults.extend([fault] * count)
        return step_faults

    def disarm(self) -> None:
        """Drop remaining interposer budgets (end-of-step quiesce: the
        settle that follows must converge chaos-free)."""
        for fault in self._armed:
            self._armed[fault] = 0

    class _Suspend:
        def __init__(self, plan: "FaultPlan"):
            self._plan = plan

        def __enter__(self):
            self._plan._suspended = True
            return self

        def __exit__(self, *exc):
            self._plan._suspended = False
            return None

    def suspended(self) -> "FaultPlan._Suspend":
        """Context manager: the harness's own workload writes (scenario
        spec edits, direct fault application) must not themselves be
        chaos targets."""
        return FaultPlan._Suspend(self)

    def _consume(self, fault: str) -> bool:
        if self._suspended or self._armed.get(fault, 0) <= 0:
            return False
        self._armed[fault] -= 1
        self.injected[fault] += 1
        self.on_inject(fault)
        return True

    def record(self, fault: str) -> None:
        """Count a step-channel injection the harness applied."""
        self.injected[fault] += 1
        self.on_inject(fault)

    # -- ObjectStore interposer contract -----------------------------------

    def on_mutation(self, verb: str, kind: str, name: str, namespace: str):
        if verb not in _CONFLICT_VERBS or kind in _EVENT_EXEMPT_KINDS:
            return
        if self._consume(STORE_CONFLICT):
            raise Conflict(
                f"sim fault {STORE_CONFLICT}: {verb} {kind} "
                f"{namespace}/{name} lost the resourceVersion race")

    def on_event(self, ev: Event) -> List[Event]:
        if ev.kind in _EVENT_EXEMPT_KINDS:
            return [ev]
        if self._consume(WATCH_DROP):
            return []
        if self._consume(WATCH_DUP):
            return [ev, ev]
        if self._consume(WATCH_DELAY):
            lo, hi = self.watch_delay_seconds
            self._deferred.append((self._now() + self.rng.uniform(lo, hi),
                                   ev))
            return []
        return [ev]

    # -- deferred (delayed-delivery) events --------------------------------

    def next_deferred_at(self) -> Optional[float]:
        return min(t for t, _ in self._deferred) if self._deferred else None

    def pop_due_deferred(self, now: float) -> List[Event]:
        """Remove and return events whose delivery time has arrived, in
        original emission order (watch streams delay, they never reorder
        a single key's history here — redelivery order is emission
        order, which is itself adversarial enough: the state may have
        moved on)."""
        due = [ev for t, ev in self._deferred if t <= now]
        self._deferred = [(t, ev) for t, ev in self._deferred if t > now]
        return due

    def draw_slow_start(self) -> float:
        lo, hi = self.slow_start_seconds
        return self.rng.uniform(lo, hi)

    def draw_notice_delta(self) -> float:
        """Advance warning a preemption notice gives before the kill."""
        lo, hi = self.notice_delta_seconds
        return self.rng.uniform(lo, hi)

    def draw_partition_window(self) -> float:
        """How long a DCN partition severs cross-slice connectivity."""
        lo, hi = self.partition_window_seconds
        return self.rng.uniform(lo, hi)

    def draw_slow_host_steps(self) -> int:
        """How many consecutive training steps a slow host stays slow.
        Step-indexed (not wall-clock) so the straggler microscope's
        K-consecutive-step verdict has a crisp ground truth to match."""
        lo, hi = self.slow_host_steps
        return self.rng.randint(lo, hi)
