"""Virtual time for deterministic simulation.

The control plane's timed behavior — requeue-after, error backoff,
expectation timeouts, cron schedules, retirement delays — all reads
``time.time()``.  Under simulation that wall-clock coupling is replaced
two ways:

- ``Manager`` takes a ``clock`` parameter directly (the tentpole seam:
  ``enqueue(after=)`` and ``_pop`` schedule against ``clock.now()``), so
  timed requeues land at exact virtual instants instead of
  ``flush_delayed()``'s promote-everything distortion;
- every other controlplane module keeps its plain ``import time`` and is
  rebound to a :class:`TimeShim` for the duration of a harness run via
  :func:`patch_time` — reconcilers, the store's creation/deletion
  timestamps, cron catch-up, and scale expectations all see the same
  virtual instant, which is what makes a seed replay byte-identical even
  across processes and minutes apart.

The virtual epoch is fixed (not "now") so minute-aligned cron schedules
fire at the same virtual boundaries in every run of a seed.
"""

from __future__ import annotations

import threading
import time as _real_time
from typing import Iterable, List, Optional

# Fixed, minute-aligned epoch (2023-11-14T22:13:00Z falls mid-minute —
# use an exact minute boundary so cron scenarios are phase-stable).
SIM_EPOCH = 1_700_000_040.0


class WallClock:
    """The live-deployment clock: a thin ``time.time`` wrapper."""

    @staticmethod
    def now() -> float:
        return _real_time.time()


class VirtualClock:
    """Monotonic virtual time; advanced explicitly, never by sleeping."""

    def __init__(self, start: float = SIM_EPOCH):
        self._lock = threading.Lock()
        self._now = float(start)

    def now(self) -> float:
        with self._lock:
            return self._now

    def advance(self, seconds: float) -> float:
        """Move time forward (negative deltas are ignored: virtual time
        is monotonic, exactly like the deadline math downstream assumes)."""
        with self._lock:
            if seconds > 0:
                self._now += seconds
            return self._now

    def advance_to(self, deadline: float) -> float:
        with self._lock:
            if deadline > self._now:
                self._now = deadline
            return self._now


class TimeShim:
    """Stand-in for the ``time`` module inside patched controlplane
    modules: ``time()`` reads the virtual clock, ``sleep()`` advances it
    (a reconciler that sleeps must not stall the single-threaded
    harness), everything else proxies to the real module."""

    def __init__(self, clock: VirtualClock):
        self._clock = clock

    def time(self) -> float:
        return self._clock.now()

    def sleep(self, seconds: float) -> None:
        self._clock.advance(max(0.0, seconds))

    def __getattr__(self, name):
        return getattr(_real_time, name)


#: Modules whose ``time`` binding the harness virtualizes.  Manager is
#: absent on purpose — it takes the clock first-class.
DEFAULT_PATCH_MODULES = (
    "kuberay_tpu.api.common",
    "kuberay_tpu.controlplane.store",
    "kuberay_tpu.controlplane.cluster_controller",
    "kuberay_tpu.controlplane.job_controller",
    "kuberay_tpu.controlplane.service_controller",
    "kuberay_tpu.controlplane.cronjob_controller",
    "kuberay_tpu.controlplane.expectations",
    "kuberay_tpu.controlplane.events",
)


class patch_time:
    """Context manager rebinding ``module.time`` to a :class:`TimeShim`.

    Restores the real module on exit even when the body raises, so a
    failing sim run cannot leak virtual time into the rest of the
    process (other tests share these modules).
    """

    def __init__(self, clock: VirtualClock,
                 modules: Iterable[str] = DEFAULT_PATCH_MODULES):
        self._shim = TimeShim(clock)
        self._module_names = list(modules)
        self._saved: List[tuple] = []

    def __enter__(self) -> "patch_time":
        import importlib
        for name in self._module_names:
            mod = importlib.import_module(name)
            self._saved.append((mod, getattr(mod, "time", None)))
            mod.time = self._shim
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        for mod, orig in reversed(self._saved):
            mod.time = orig
        self._saved.clear()
        return None
