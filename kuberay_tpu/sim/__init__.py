"""kuberay_tpu.sim: deterministic chaos simulation for the control plane.

FoundationDB-style simulation testing + Jepsen-style fault/invariant
checking over the in-process control plane: a seeded
:class:`~kuberay_tpu.sim.faults.FaultPlan` injects adversarial
interleavings (write conflicts, watch drop/duplicate/delay, pod kills,
slice drains, slow starts, delete races, leader failover) into the
``ObjectStore``/``Manager``/``FakeKubelet`` trio running on a virtual
clock, and a registry of runtime invariant checkers
(:mod:`~kuberay_tpu.sim.invariants`) validates every converged state.
Any violation reproduces from ``--scenario NAME --seed N``.

See docs/chaos-sim.md; CLI: ``python -m kuberay_tpu.sim``.
"""

from kuberay_tpu.sim.clock import (
    SIM_EPOCH,
    TimeShim,
    VirtualClock,
    WallClock,
    patch_time,
)
from kuberay_tpu.sim.faults import ALL_FAULTS, DEFAULT_PROFILE, FaultPlan
from kuberay_tpu.sim.harness import SimHarness, SimResult
from kuberay_tpu.sim.invariants import (
    CHECKERS,
    CheckContext,
    Violation,
    run_checkers,
)
from kuberay_tpu.sim.scenarios import SCENARIOS, Scenario, get_scenario

__all__ = [
    "ALL_FAULTS", "CHECKERS", "CheckContext", "DEFAULT_PROFILE",
    "FaultPlan", "SCENARIOS", "SIM_EPOCH", "Scenario", "SimHarness",
    "SimResult", "TimeShim", "Violation", "VirtualClock", "WallClock",
    "get_scenario", "patch_time", "run_checkers",
]
