"""Runtime invariant checkers: what must hold whenever the control plane
is quiescent, no matter which faults just happened.

Each checker is a pure function over a :class:`CheckContext` (the store
plus the run's event journal) returning :class:`Violation` records; the
registry mirrors the static analyzer's rule registry
(kuberay_tpu.analysis) — same name/description discipline, but these
fire on *executions*, not source.  The catalog is documented in
docs/chaos-sim.md and cross-linked from docs/failure_semantics.md.

Checkers run after every settle (see harness.SimHarness.step), i.e. on
converged states: transient mid-reconcile shapes (a slice mid-repair)
are legitimate, the same shape *after* convergence is a bug.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.controlplane.warmpool_controller import (
    KIND_WARM_POOL,
    LABEL_WARM_CLAIMED,
    LABEL_WARM_POOL,
)
from kuberay_tpu.utils import constants as C


@dataclasses.dataclass
class Violation:
    invariant: str
    key: str        # "Kind ns/name" (or slice name) the violation anchors to
    message: str

    def __str__(self) -> str:
        return f"[{self.invariant}] {self.key}: {self.message}"


class CheckContext:
    """What checkers see: the store and the journal (the harness's record
    of every store event, in commit order — see harness.JournalRecord).
    When the harness mounts step telemetry it also hands over the
    tracker (``steps``) and its ground-truth slow-host fault log
    (``slow_host_log``) so detection checkers can compare verdicts
    against what was actually injected."""

    def __init__(self, store: ObjectStore,
                 journal: Optional[List[Dict[str, Any]]] = None,
                 steps=None,
                 slow_host_log: Optional[List[Dict[str, Any]]] = None,
                 route_weight_log: Optional[List[Dict[str, Any]]] = None,
                 serve_traffic_log: Optional[List[Dict[str, Any]]] = None,
                 quota=None,
                 kv_tier_log: Optional[List[Dict[str, Any]]] = None):
        self.store = store
        self.journal = journal or []
        self.steps = steps
        self.slow_host_log = slow_host_log or []
        # Upgrade-era observability feeds (harness-maintained, both
        # empty unless the scenario mounts them): every TrafficRoute
        # spec mutation with the ring readiness observed at write time,
        # and the serve-traffic pump's per-round client outcomes.
        self.route_weight_log = route_weight_log or []
        self.serve_traffic_log = serve_traffic_log or []
        # The QuotaManager when a scenario mounts the quota seam; the
        # quota-* checkers read its ledger snapshot and are vacuous
        # without it.
        self.quota = quota
        # KV-tier seam ops (session-churn scenario): every admit /
        # checkout-hit / discard against a real KvTierStore, with the
        # block tokens and payload that crossed the seam.  Empty for
        # every classic scenario, so the no-stale-block checker is
        # vacuous there and journal hashes are untouched.
        self.kv_tier_log = kv_tier_log or []

    # -- shared traversals -------------------------------------------------

    def live_pods(self, namespace=None, labels=None) -> List[dict]:
        return [p for p in self.store.list("Pod", namespace, labels=labels)
                if not p["metadata"].get("deletionTimestamp")]

    def clusters(self) -> List[TpuCluster]:
        return [TpuCluster.from_dict(o)
                for o in self.store.list(C.KIND_CLUSTER)]


CHECKERS: Dict[str, Callable[[CheckContext], List[Violation]]] = {}
DESCRIPTIONS: Dict[str, str] = {}


def checker(name: str, description: str):
    def register(fn):
        CHECKERS[name] = fn
        DESCRIPTIONS[name] = description
        return fn
    return register


def run_checkers(ctx: CheckContext,
                 only: Optional[List[str]] = None) -> List[Violation]:
    out: List[Violation] = []
    for name in sorted(CHECKERS):
        if only is not None and name not in only:
            continue
        out.extend(CHECKERS[name](ctx))
    return out


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _container_env(pod: dict) -> Dict[str, str]:
    containers = pod.get("spec", {}).get("containers", [])
    if not containers:
        return {}
    return {e.get("name", ""): e.get("value", "")
            for e in containers[0].get("env", [])}


def _pods_by_slice(pods: List[dict]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for p in pods:
        sname = p["metadata"]["labels"].get(C.LABEL_SLICE_NAME)
        if sname:
            out.setdefault(sname, []).append(p)
    return out


def _obj_key(kind: str, md: dict) -> str:
    return f"{kind} {md.get('namespace', 'default')}/{md.get('name', '')}"


# ---------------------------------------------------------------------------
# slice-identity: dense TPU_WORKER_ID + consistent TPU_WORKER_HOSTNAMES
# ---------------------------------------------------------------------------

@checker("slice-identity",
         "every slice's pods carry TPU_WORKER_ID dense in 0..n-1 matching "
         "their host-index label, and an identical n-entry "
         "TPU_WORKER_HOSTNAMES ring")
def check_slice_identity(ctx: CheckContext) -> List[Violation]:
    out: List[Violation] = []
    for sname, pods in sorted(_pods_by_slice(ctx.live_pods()).items()):
        ids = []
        hostnames = set()
        nproc = set()
        for p in pods:
            env = _container_env(p)
            labels = p["metadata"]["labels"]
            wid = env.get(C.ENV_TPU_WORKER_ID)
            if wid is None:
                out.append(Violation(
                    "slice-identity", sname,
                    f"pod {p['metadata']['name']} has no "
                    f"{C.ENV_TPU_WORKER_ID} env"))
                continue
            if wid != labels.get(C.LABEL_HOST_INDEX):
                out.append(Violation(
                    "slice-identity", sname,
                    f"pod {p['metadata']['name']}: {C.ENV_TPU_WORKER_ID}="
                    f"{wid} != host-index label "
                    f"{labels.get(C.LABEL_HOST_INDEX)}"))
            ids.append(wid)
            hostnames.add(env.get(C.ENV_TPU_WORKER_HOSTNAMES, ""))
            nproc.add(env.get(C.ENV_NUM_PROCESSES, ""))
        if len(hostnames) > 1:
            out.append(Violation(
                "slice-identity", sname,
                f"inconsistent {C.ENV_TPU_WORKER_HOSTNAMES} across hosts: "
                f"{sorted(hostnames)}"))
        want = {str(i) for i in range(len(pods))}
        if ids and len(pods) == len(ids) and set(ids) != want and \
                nproc == {str(len(pods))}:
            # Only meaningful when the slice is at its full host count
            # (TPU_NUM_PROCESSES == observed size); short slices are the
            # atomicity checker's finding, not a sparse-id one.
            out.append(Violation(
                "slice-identity", sname,
                f"TPU_WORKER_ID set {sorted(ids)} is not dense 0..{len(pods) - 1}"))
        if hostnames and nproc == {str(len(pods))}:
            ring = next(iter(hostnames))
            if ring and len(ring.split(",")) != len(pods):
                out.append(Violation(
                    "slice-identity", sname,
                    f"{C.ENV_TPU_WORKER_HOSTNAMES} names "
                    f"{len(ring.split(','))} hosts, slice has {len(pods)}"))
    return out


# ---------------------------------------------------------------------------
# slice-atomicity: no partial multi-host slice survives convergence
# ---------------------------------------------------------------------------

@checker("slice-atomicity",
         "after convergence every multi-host slice of a live worker group "
         "has all its hosts, with no slice mixing Running and non-Running "
         "pods")
def check_slice_atomicity(ctx: CheckContext) -> List[Violation]:
    out: List[Violation] = []
    for cluster in ctx.clusters():
        if cluster.metadata.deletionTimestamp or cluster.spec.suspend:
            continue
        ns = cluster.metadata.namespace
        pods = ctx.live_pods(ns, labels={
            C.LABEL_CLUSTER: cluster.metadata.name})
        workers = [p for p in pods if p["metadata"]["labels"].get(
            C.LABEL_NODE_TYPE) == C.NODE_TYPE_WORKER]
        for group in cluster.spec.workerGroupSpecs:
            if group.suspend:
                continue
            hosts = group.slice_topology().num_hosts
            gpods = [p for p in workers if p["metadata"]["labels"].get(
                C.LABEL_GROUP) == group.groupName]
            for sname, plist in sorted(_pods_by_slice(gpods).items()):
                if len(plist) != hosts:
                    out.append(Violation(
                        "slice-atomicity", sname,
                        f"slice has {len(plist)}/{hosts} hosts after "
                        "convergence"))
                    continue
                phases = {p.get("status", {}).get("phase", "Pending")
                          for p in plist}
                if "Running" in phases and phases != {"Running"}:
                    out.append(Violation(
                        "slice-atomicity", sname,
                        f"slice partially Running after convergence: "
                        f"{sorted(phases)}"))
    return out


# ---------------------------------------------------------------------------
# gang-admission: worker capacity moves in whole-slice quanta
# ---------------------------------------------------------------------------

@checker("gang-admission",
         "a worker group's pod count is always a whole number of slices "
         "(all-or-nothing admission) and never exceeds maxReplicas slices")
def check_gang_admission(ctx: CheckContext) -> List[Violation]:
    out: List[Violation] = []
    for cluster in ctx.clusters():
        if cluster.metadata.deletionTimestamp or cluster.spec.suspend:
            continue
        ns = cluster.metadata.namespace
        pods = ctx.live_pods(ns, labels={
            C.LABEL_CLUSTER: cluster.metadata.name})
        for group in cluster.spec.workerGroupSpecs:
            if group.suspend:
                continue
            hosts = group.slice_topology().num_hosts
            n = sum(1 for p in pods
                    if p["metadata"]["labels"].get(C.LABEL_GROUP)
                    == group.groupName)
            key = _obj_key(C.KIND_CLUSTER, {"namespace": ns,
                                            "name": cluster.metadata.name})
            if n % hosts:
                out.append(Violation(
                    "gang-admission", key,
                    f"group {group.groupName}: {n} pods is not a whole "
                    f"number of {hosts}-host slices"))
            elif group.maxReplicas and n // hosts > group.maxReplicas:
                out.append(Violation(
                    "gang-admission", key,
                    f"group {group.groupName}: {n // hosts} slices exceeds "
                    f"maxReplicas {group.maxReplicas}"))
    return out


# ---------------------------------------------------------------------------
# warm-pool-accounting
# ---------------------------------------------------------------------------

@checker("warm-pool-accounting",
         "warm pool counts are never negative, ready never exceeds warm, "
         "status matches the observed unclaimed slices, and no warm pod is "
         "double-assigned to a cluster while still unclaimed")
def check_warm_pool_accounting(ctx: CheckContext) -> List[Violation]:
    out: List[Violation] = []
    for pool in ctx.store.list(KIND_WARM_POOL):
        md = pool["metadata"]
        key = _obj_key(KIND_WARM_POOL, md)
        status = pool.get("status") or {}
        warm = status.get("warmSlices")
        ready = status.get("readySlices")
        if warm is not None and warm < 0:
            out.append(Violation("warm-pool-accounting", key,
                                 f"negative warmSlices {warm}"))
        if ready is not None and ready < 0:
            out.append(Violation("warm-pool-accounting", key,
                                 f"negative readySlices {ready}"))
        if warm is not None and ready is not None and ready > warm:
            out.append(Violation(
                "warm-pool-accounting", key,
                f"readySlices {ready} > warmSlices {warm}"))
        unclaimed = [
            p for p in ctx.live_pods(md.get("namespace", "default"),
                                     labels={LABEL_WARM_POOL: md["name"]})
            if not p["metadata"]["labels"].get(LABEL_WARM_CLAIMED)]
        observed = len({p["metadata"]["labels"].get(C.LABEL_SLICE_INDEX)
                        for p in unclaimed})
        if not md.get("deletionTimestamp") and warm is not None and \
                warm != observed:
            out.append(Violation(
                "warm-pool-accounting", key,
                f"status.warmSlices {warm} != observed unclaimed slices "
                f"{observed}"))
        for p in unclaimed:
            if p["metadata"]["labels"].get(C.LABEL_CLUSTER):
                out.append(Violation(
                    "warm-pool-accounting", key,
                    f"unclaimed warm pod {p['metadata']['name']} is "
                    "double-assigned (carries a cluster label)"))
    return out


# ---------------------------------------------------------------------------
# service-capacity: upgrades never strand the stable service
# ---------------------------------------------------------------------------

@checker("service-capacity",
         "a live TpuService's active/pending cluster references resolve, "
         "and a service once Running keeps at least one live serving pod "
         "behind the stable service")
def check_service_capacity(ctx: CheckContext) -> List[Violation]:
    out: List[Violation] = []
    for svc in ctx.store.list(C.KIND_SERVICE):
        md = svc["metadata"]
        if md.get("deletionTimestamp") or \
                svc.get("spec", {}).get("suspend"):
            continue
        key = _obj_key(C.KIND_SERVICE, md)
        ns = md.get("namespace", "default")
        status = svc.get("status") or {}
        for role in ("activeServiceStatus", "pendingServiceStatus"):
            cs = status.get(role)
            if not cs:
                continue
            cname = cs.get("clusterName", "")
            if cname and ctx.store.try_get(C.KIND_CLUSTER, cname,
                                           ns) is None:
                out.append(Violation(
                    "service-capacity", key,
                    f"{role} references cluster {cname} which does not "
                    "exist"))
        active = status.get("activeServiceStatus")
        if active and status.get("serviceStatus") == "Running":
            serving = [
                p for p in ctx.live_pods(ns, labels={
                    C.LABEL_CLUSTER: active.get("clusterName", "")})
                if p.get("status", {}).get("phase") == "Running"]
            if not serving:
                out.append(Violation(
                    "service-capacity", key,
                    f"service reports Running but active cluster "
                    f"{active.get('clusterName')} has zero live Running "
                    "pods"))
    return out


# ---------------------------------------------------------------------------
# no-resurrection: a deleted object's uid never reappears
# ---------------------------------------------------------------------------

@checker("drain-before-delete",
         "a slice pod deleted while carrying an active preemption notice "
         "must have been drained (checkpoint requested, drained-at "
         "stamped) before the delete — teardown routes through the drain "
         "seam")
def check_drain_before_delete(ctx: CheckContext) -> List[Violation]:
    out: List[Violation] = []
    flagged = set()
    for rec in ctx.journal:
        # The harness journals the notice/drained annotations onto every
        # Pod record that carries them; a DELETED record with a notice
        # but no drain acknowledgment is a teardown that bypassed the
        # checkpoint-drain seam.
        if rec.get("type") != "DELETED" or rec.get("kind") != "Pod":
            continue
        if "notice" not in rec or "drained" in rec:
            continue
        key = f"Pod {rec.get('ns')}/{rec.get('name')}"
        if key in flagged:
            continue
        flagged.add(key)
        out.append(Violation(
            "drain-before-delete", key,
            f"deleted at rv {rec.get('rv')} under preemption notice "
            f"(deadline {rec.get('notice')}) with no preceding "
            "drain/checkpoint acknowledgment"))
    return out


@checker("straggler-detection",
         "every completed slow-host fault window was flagged by the step "
         "tracker: a matching verdict names the injected host and detects "
         "within straggler_steps heartbeats of the first slow step")
def check_straggler_detection(ctx: CheckContext) -> List[Violation]:
    # Vacuous without the straggler microscope mounted (telemetry off,
    # or the benchmark's NoopStepTracker overhead leg) or without
    # injected slow-host windows to detect.
    from kuberay_tpu.obs import NoopStepTracker
    if ctx.steps is None or isinstance(ctx.steps, NoopStepTracker):
        return []
    out: List[Violation] = []
    verdicts = ctx.steps.stragglers()
    k = getattr(ctx.steps, "straggler_steps", 5)
    for entry in ctx.slow_host_log:
        if entry.get("clear_ts") is None:
            continue    # window still open: detection may be in flight
        key = f"{entry['ns']}/{entry['cluster']} host {entry['host']}"
        matches = [v for v in verdicts
                   if v["host"] == entry["host"]
                   and v["first_slow_step"] == entry["first_slow_step"]]
        if not matches:
            out.append(Violation(
                "straggler-detection", key,
                f"slow window injected at step {entry['first_slow_step']} "
                f"(cleared step {entry['clear_step']}) produced no "
                "straggler verdict"))
            continue
        v = matches[0]
        if v["detected_step"] - v["first_slow_step"] + 1 > k:
            out.append(Violation(
                "straggler-detection", key,
                f"detected at step {v['detected_step']}, "
                f"{v['detected_step'] - v['first_slow_step'] + 1} slow "
                f"steps after onset (budget {k})"))
    return out


@checker("weighted-ring-atomicity",
         "a TrafficRoute weight INCREASE on the green (pending) backend "
         "never outruns its whole-ring capacity: at write time the green "
         "cluster has at least one fully-Ready multi-host ring and the "
         "new weight stays within 100*ready/desired — traffic is never "
         "pointed at a partially-provisioned slice")
def check_weighted_ring_atomicity(ctx: CheckContext) -> List[Violation]:
    # Vacuous without the harness's route watcher mounted (classic
    # scenarios never create TrafficRoutes).  Only weight *increases*
    # are capped: a ring that degrades under a fault while weight holds
    # is the ramp's rollback/step-down problem, not a provisioning
    # atomicity breach.
    out: List[Violation] = []
    prev: Dict[tuple, int] = {}
    for entry in ctx.route_weight_log:
        for b in entry.get("backends", []):
            key = (entry.get("route", ""), b.get("service", ""))
            last = prev.get(key, 0)
            weight = int(b.get("weight", 0) or 0)
            prev[key] = weight
            if b.get("role") != "green" or weight <= last:
                continue
            ready = int(b.get("ready_rings", 0) or 0)
            desired = int(b.get("desired_rings", 0) or 0)
            vkey = f"TrafficRoute {entry.get('route')}/{b.get('service')}"
            if ready < 1:
                out.append(Violation(
                    "weighted-ring-atomicity", vkey,
                    f"weight raised {last}% -> {weight}% at ts "
                    f"{entry.get('ts')} with zero whole green rings"))
                continue
            cap = 100 if desired <= 0 else \
                (100 * min(ready, desired)) // desired
            if weight > cap:
                out.append(Violation(
                    "weighted-ring-atomicity", vkey,
                    f"weight raised {last}% -> {weight}% at ts "
                    f"{entry.get('ts')} but {ready}/{desired} whole rings "
                    f"support only {cap}%"))
    return out


@checker("zero-failed-requests",
         "no serve-traffic pump request ever fails client-visibly during "
         "an upgrade: a weighted backend without a whole serving ring "
         "must fail over to a healthy peer, never surface a 5xx")
def check_zero_failed_requests(ctx: CheckContext) -> List[Violation]:
    # Vacuous unless the scenario mounts the pump (serve_traffic=True).
    out: List[Violation] = []
    for entry in ctx.serve_traffic_log:
        failed = int(entry.get("failed", 0) or 0)
        if failed > 0:
            out.append(Violation(
                "zero-failed-requests",
                f"TrafficRoute {entry.get('route', '')}",
                f"{failed}/{entry.get('requests')} client requests "
                f"failed at ts {entry.get('ts')} (failovers="
                f"{entry.get('failovers', 0)})"))
    return out


@checker("no-resurrection",
         "once the journal records DELETED for a uid, no later ADDED or "
         "MODIFIED event carries that uid (a status write never "
         "resurrects a deleted object)")
def check_no_resurrection(ctx: CheckContext) -> List[Violation]:
    out: List[Violation] = []
    deleted: Dict[str, str] = {}    # uid -> "Kind ns/name"
    flagged = set()
    for rec in ctx.journal:
        uid = rec.get("uid")
        if not uid:
            continue
        key = f"{rec.get('kind')} {rec.get('ns')}/{rec.get('name')}"
        if rec.get("type") == "DELETED":
            deleted[uid] = key
        elif uid in deleted and uid not in flagged:
            flagged.add(uid)
            out.append(Violation(
                "no-resurrection", key,
                f"{rec.get('type')} at rv {rec.get('rv')} resurrects uid "
                f"{uid} deleted earlier as {deleted[uid]}"))
    return out


# ---------------------------------------------------------------------------
# quota-* (vacuous unless the scenario mounts the QuotaManager seam)
# ---------------------------------------------------------------------------

def _quota_pools(snapshot: Dict[str, Any]) -> List[dict]:
    return snapshot.get("pools", [])


def _pool_for_namespace(pools: List[dict], namespace: str) -> Optional[dict]:
    # Mirrors QuotaManager._resolve_pool: the namespace's own pool,
    # falling back to the "default" namespace pool.
    for ns in ((namespace,) if namespace == "default"
               else (namespace, "default")):
        matching = [p for p in pools if p.get("namespace") == ns]
        if matching:
            return sorted(matching, key=lambda p: p.get("name", ""))[0]
    return None


def _queue_spec(pool: dict, tenant: str, queue: str) -> Optional[dict]:
    for t in pool.get("spec", {}).get("tenants", []):
        if t.get("name") != tenant:
            continue
        for q in t.get("queues", []):
            if q.get("name") == queue:
                return q
    return None


@checker("quota-gang-atomicity",
         "(vacuous without the quota seam) every tenanted workload with "
         "live pods holds a full ledger claim — a gang is never partially "
         "admitted, and no tenanted pods run outside the ledger")
def check_quota_gang_atomicity(ctx: CheckContext) -> List[Violation]:
    if ctx.quota is None:
        return []
    out: List[Violation] = []
    snapshot = ctx.quota.debug_snapshot()
    pools = _quota_pools(snapshot)
    claimed = {tuple(c["key"]) for c in snapshot.get("claims", [])}
    for cluster in ctx.clusters():
        if not cluster.spec.tenant:
            continue
        ns = cluster.metadata.namespace
        if _pool_for_namespace(pools, ns) is None:
            continue    # no pool -> quota is a pass-through, no claims
        pods = ctx.live_pods(ns, labels={
            C.LABEL_CLUSTER: cluster.metadata.name})
        if not pods:
            continue
        # A job-originated cluster shares the job's claim key (one gang,
        # one claim) — same resolution as quota.claim_key.
        labels = cluster.metadata.labels or {}
        if labels.get(C.LABEL_ORIGINATED_FROM_CRD) == C.KIND_JOB and \
                labels.get(C.LABEL_ORIGINATED_FROM_CR_NAME):
            key = (C.KIND_JOB, ns, labels[C.LABEL_ORIGINATED_FROM_CR_NAME])
        else:
            key = (C.KIND_CLUSTER, ns, cluster.metadata.name)
        if key not in claimed:
            out.append(Violation(
                "quota-gang-atomicity",
                _obj_key(C.KIND_CLUSTER, {"namespace": ns,
                                          "name": cluster.metadata.name}),
                f"{len(pods)} live pods for tenant "
                f"{cluster.spec.tenant!r} but no ledger claim under "
                f"{key} — capacity held outside the quota seam"))
    for c in snapshot.get("claims", []):
        if c.get("chips", 0) < 0:
            out.append(Violation(
                "quota-gang-atomicity",
                f"{c['key'][0]} {c['key'][1]}/{c['key'][2]}",
                f"ledger claim holds negative chips ({c['chips']})"))
    return out


@checker("quota-conservation",
         "(vacuous without the quota seam) claimed chips never exceed a "
         "queue's ceiling and the pool totals never exceed totalChips")
def check_quota_conservation(ctx: CheckContext) -> List[Violation]:
    if ctx.quota is None:
        return []
    out: List[Violation] = []
    snapshot = ctx.quota.debug_snapshot()
    pools = _quota_pools(snapshot)
    used: Dict[tuple, int] = {}     # (pool ns, pool name, tenant, queue)
    pool_used: Dict[tuple, int] = {}
    for c in snapshot.get("claims", []):
        pool = _pool_for_namespace(pools, c["key"][1])
        if pool is None:
            out.append(Violation(
                "quota-conservation",
                f"{c['key'][0]} {c['key'][1]}/{c['key'][2]}",
                "ledger claim with no resolvable QuotaPool"))
            continue
        pk = (pool["namespace"], pool["name"])
        used[pk + (c["tenant"], c["queue"])] = \
            used.get(pk + (c["tenant"], c["queue"]), 0) + c["chips"]
        pool_used[pk] = pool_used.get(pk, 0) + c["chips"]
    for pool in pools:
        pk = (pool["namespace"], pool["name"])
        total = pool.get("spec", {}).get("totalChips", 0)
        if pool_used.get(pk, 0) > total:
            out.append(Violation(
                "quota-conservation",
                f"QuotaPool {pk[0]}/{pk[1]}",
                f"{pool_used[pk]} chips claimed exceeds totalChips "
                f"{total}"))
        for key, chips in used.items():
            if key[:2] != pk:
                continue
            q = _queue_spec(pool, key[2], key[3])
            if q is None:
                out.append(Violation(
                    "quota-conservation", f"QuotaPool {pk[0]}/{pk[1]}",
                    f"claims held under unknown tenant/queue "
                    f"{key[2]}/{key[3]}"))
                continue
            ceiling = q.get("ceilingChips", 0) or total
            if chips > ceiling:
                out.append(Violation(
                    "quota-conservation", f"QuotaPool {pk[0]}/{pk[1]}",
                    f"queue {key[2]}/{key[3]} holds {chips} chips over "
                    f"its ceiling {ceiling}"))
    return out


@checker("quota-starvation-bound",
         "(vacuous without the quota seam) no gang pends past the pool's "
         "starvation bound without the escalation override engaged")
def check_quota_starvation_bound(ctx: CheckContext) -> List[Violation]:
    if ctx.quota is None:
        return []
    out: List[Violation] = []
    snapshot = ctx.quota.debug_snapshot()
    pools = _quota_pools(snapshot)
    now = ctx.quota._clock()
    for p in snapshot.get("pending", []):
        pool = _pool_for_namespace(pools, p.get("namespace", "default"))
        if pool is None:
            continue
        bound = pool.get("spec", {}).get("starvationBoundSeconds", 300.0)
        # Escalation is stamped on the *next* level-triggered re-ask
        # after the bound; controllers requeue within ~5s, so a 15s
        # grace keeps the checker honest without false-flagging the
        # re-ask gap.
        if now - p["since"] > bound + 15.0 and not p.get("escalated"):
            out.append(Violation(
                "quota-starvation-bound",
                f"{p['key'][0]} {p['key'][1]}/{p['key'][2]}",
                f"pending {now - p['since']:.0f}s exceeds the "
                f"{bound:.0f}s starvation bound without escalation"))
    return out


@checker("no-stale-block",
         "(vacuous without the kv-tier seam) every checkout hit returns "
         "the payload whose content hashes to the requested block hash, "
         "and no discarded hash is served without a re-admit")
def check_no_stale_block(ctx: CheckContext) -> List[Violation]:
    """Content-addressing is the tier store's whole safety story: a hash
    names exactly one token-block, so a hit serving anything other than
    the content that hashes to it is KV corruption (the served tokens
    would decode against the wrong prefix).  The session-churn scenario
    logs every seam crossing with ground truth; this checker recomputes
    the chain link (prefix.chain_hash) and replays the admit/discard
    ledger per hash."""
    if not ctx.kv_tier_log:
        return []
    from kuberay_tpu.serve.prefix import chain_hash
    out: List[Violation] = []
    live: Dict[int, bool] = {}   # hash -> currently admitted somewhere
    for i, rec in enumerate(ctx.kv_tier_log):
        op, h = rec.get("op"), rec.get("hash")
        if op == "admit":
            live[h] = True
        elif op == "discard":
            live[h] = False
        elif op == "hit":
            want = chain_hash(rec.get("parent", 0),
                              rec.get("block_tokens", ()))
            if want != h:
                out.append(Violation(
                    "no-stale-block", f"op {i} hash {h}",
                    "checkout hit for a hash that does not match its "
                    "requested block content (chain link mismatch)"))
            if list(rec.get("payload", ())) != \
                    list(rec.get("block_tokens", ())):
                out.append(Violation(
                    "no-stale-block", f"op {i} hash {h}",
                    f"checkout served payload {rec.get('payload')!r} for "
                    f"block content {rec.get('block_tokens')!r} — stale "
                    "or corrupted tier entry crossed the seam"))
            if not live.get(h, False):
                out.append(Violation(
                    "no-stale-block", f"op {i} hash {h}",
                    "checkout hit on a hash with no live admit (served "
                    "after discard/eviction)"))
    return out
