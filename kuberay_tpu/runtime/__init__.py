"""Runtime-side components: what runs inside the pods the operator
launches (coordinator client/server, submitter, bootstrap) — the analogue
of the Ray runtime surface KubeRay talks to."""
