"""Coordinator server: the head pod's control process.

The head-side half of the runtime contract (the role Ray's dashboard +
GCS play for the reference — SURVEY.md §5.8): an HTTP API for job
submission/status/logs and serve-app config, plus cluster metadata that
survives head restarts via pluggable state backends (the
GcsFaultToleranceOptions analogue):

- memory: in-process only (workers die with the head)
- file:   JSON journal on a PVC path (embedded-RocksDB analogue)
- external: Redis-protocol store (SET/GET/DEL over TCP, no client dep)

Endpoints match what CoordinatorClient speaks (runtime/coordinator_client.py):
    POST/GET/DELETE /api/jobs/[{id}] , POST /api/jobs/{id}/stop
    PUT/GET  /api/serve/applications/
    GET      /api/healthz , /api/cluster
Jobs run as local subprocesses of the head (entrypoints launch the
distributed program via train/launcher.py on every host).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import threading
import time
from collections import deque
from http.server import ThreadingHTTPServer
from typing import Any, Dict, Optional

from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.httpjson import JsonHandler

#: Checkpoint-drain requests kept for inspection (ring, oldest dropped).
CHECKPOINT_REQUESTS_MAX = 256


class StateBackend:
    """Cluster-metadata persistence seam (§5.3 head-loss recovery)."""

    def save(self, key: str, value: Dict[str, Any]):  # pragma: no cover
        raise NotImplementedError

    def load_all(self) -> Dict[str, Dict[str, Any]]:  # pragma: no cover
        raise NotImplementedError

    def delete(self, key: str):  # pragma: no cover
        raise NotImplementedError


class MemoryBackend(StateBackend):
    def __init__(self):
        self._d: Dict[str, Dict[str, Any]] = {}

    def save(self, key, value):
        self._d[key] = json.loads(json.dumps(value))

    def load_all(self):
        return dict(self._d)

    def delete(self, key):
        self._d.pop(key, None)


class FileBackend(StateBackend):
    """Append-free JSON-per-key directory journal (PVC-backed)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key):
        safe = key.replace("/", "_")
        return os.path.join(self.root, f"{safe}.json")

    def save(self, key, value):
        tmp = self._path(key) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.replace(tmp, self._path(key))

    def load_all(self):
        out = {}
        for fn in os.listdir(self.root):
            if fn.endswith(".json"):
                try:
                    with open(os.path.join(self.root, fn)) as f:
                        out[fn[:-5]] = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
        return out

    def delete(self, key):
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass


class RedisBackend(StateBackend):
    """Minimal RESP client (SET/GET/DEL/KEYS) — no redis-py dependency."""

    def __init__(self, address: str, namespace: str = "tpu"):
        host, _, port = address.partition(":")
        self.host, self.port = host, int(port or 6379)
        self.ns = namespace
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _cmd(self, *parts: bytes):
        # This lock is a CONNECTION mutex, not shared-state protection:
        # it serializes request/reply pairs on the single RESP socket
        # (interleaved writers would mispair replies).  Holding it
        # across the I/O is the point — every caller is doing network
        # I/O anyway, and each command carries a 5 s socket timeout.
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(    # kuberay-lint: disable=blocking-under-lock -- connection mutex: serializing the whole request/reply I/O is the point (see comment above); 5 s socket timeout bounds the hold
                        (self.host, self.port), timeout=5)
                buf = b"*%d\r\n" % len(parts)
                for p in parts:
                    buf += b"$%d\r\n%s\r\n" % (len(p), p)
                self._sock.sendall(buf)    # kuberay-lint: disable=blocking-under-lock -- connection mutex: serializing the whole request/reply I/O is the point (see comment above); 5 s socket timeout bounds the hold
                return self._read_reply(self._sock.makefile("rb"))
            except (OSError, RuntimeError):
                # A failed/half-read exchange leaves the stream unusable;
                # drop the connection so the next command reconnects clean.
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                raise

    def _read_reply(self, f):
        line = f.readline()
        t, rest = line[:1], line[1:].strip()
        if t in (b"+", b":"):
            return rest
        if t == b"-":
            raise RuntimeError(rest.decode())
        if t == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = f.read(n)
            f.read(2)
            return data
        if t == b"*":
            return [self._read_reply(f) for _ in range(int(rest))]
        raise RuntimeError(f"bad RESP reply {line!r}")

    def save(self, key, value):
        self._cmd(b"SET", f"{self.ns}:{key}".encode(),
                  json.dumps(value).encode())

    def load_all(self):
        keys = self._cmd(b"KEYS", f"{self.ns}:*".encode()) or []
        out = {}
        for k in keys:
            v = self._cmd(b"GET", k)
            if v:
                out[k.decode().split(":", 1)[1]] = json.loads(v)
        return out

    def delete(self, key):
        self._cmd(b"DEL", f"{self.ns}:{key}".encode())


def backend_from_env() -> StateBackend:
    addr = os.environ.get("TPU_HEAD_EXTERNAL_STORAGE_ADDRESS")
    if addr:
        return RedisBackend(
            addr, os.environ.get("TPU_HEAD_EXTERNAL_STORAGE_NAMESPACE", "tpu"))
    path = os.environ.get("TPU_HEAD_STATE_PATH")
    if path:
        return FileBackend(path)
    return MemoryBackend()


class JobRecord:
    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[dict] = None,
                 metadata: Optional[dict] = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.metadata = metadata or {}
        self.status = "PENDING"
        self.message = ""
        self.start_time = time.time()
        self.end_time = 0.0
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = ""

    def to_dict(self):
        return {
            "submission_id": self.job_id, "entrypoint": self.entrypoint,
            "status": self.status, "message": self.message,
            "start_time": self.start_time, "end_time": self.end_time,
            "metadata": self.metadata,
        }


class CoordinatorServer:
    def __init__(self, state: Optional[StateBackend] = None,
                 log_dir: str = "/tmp/tpu-coordinator-logs",
                 spawn_jobs: bool = True,
                 auth_token: Optional[str] = None,
                 goodput=None,
                 on_checkpoint=None,
                 steps=None):
        # Bearer auth (ref cluster token auth): token comes from the
        # operator-minted Secret via the TPU_AUTH_TOKEN env.
        self.auth_token = (auth_token if auth_token is not None
                           else os.environ.get("TPU_AUTH_TOKEN", ""))
        # Optional obs.GoodputLedger: job lifecycle events feed per-job
        # wall-clock attribution, stamped with THIS server's clock
        # (received_at) — never the client's.
        self.goodput = goodput
        # Optional obs.StepTracker: "step_heartbeat" events feed the
        # per-(job, host) straggler microscope, attributed at
        # received_at like the goodput feed.
        self.steps = steps
        self.state = state or backend_from_env()
        self.log_dir = log_dir
        self.spawn_jobs = spawn_jobs
        os.makedirs(log_dir, exist_ok=True)
        self._lock = threading.Lock()
        self.jobs: Dict[str, JobRecord] = {}
        self.serve_config: Optional[Dict[str, Any]] = None
        self.serve_apps: Dict[str, Any] = {}
        # Structured task/step/profile events (ref eventserver.go:838
        # handleTaskProfileEvent): jobs/engines POST them here; the
        # history collector archives them for post-mortem replay.
        # Bounded ring — the archive, not this buffer, is durable.  Each
        # event gets a unique id (boot epoch + counter) so the archive
        # can merge scrapes across ring eviction and head restarts.
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=20000)
        self._event_boot = f"{int(time.time() * 1000):x}"
        self._event_seq = 0
        # Device profiling (ref: Ray dashboard profile capture; here a
        # jax.profiler trace written under log_dir so the history log
        # collector archives it like any node file).
        self.profile_dir = os.path.join(log_dir, "profiles")
        self._profiling: Optional[str] = None
        # Checkpoint-drain hook (docs/preemption.md): the operator POSTs
        # /api/checkpoint when a slice gets a preemption notice; the
        # training harness wires a callback that drives its
        # CheckpointWriter.  Requests are recorded either way so the
        # drain is observable even without a hook installed.
        self.on_checkpoint = on_checkpoint
        # Bounded like the event ring and the flight recorder: an
        # operator stuck in a notice->drain loop must not grow head
        # memory without bound.  Dropped (oldest) requests are counted
        # — the count is the signal that the ring was too small.
        self.checkpoint_requests: "deque[Dict[str, Any]]" = \
            deque(maxlen=CHECKPOINT_REQUESTS_MAX)
        self.checkpoint_requests_dropped = 0
        self._recover()

    # -- checkpoint drain --------------------------------------------------

    def request_checkpoint(self, tag: str = "",
                           reason: str = "preemption") -> Dict[str, Any]:
        """Fan a drain-time checkpoint request out to the training loop.

        The hook runs outside the lock (it may block on a real save);
        its failure is reported to the caller but never raises — the
        operator's drain path treats checkpointing as best-effort."""
        req = {"tag": tag, "reason": reason, "received_at": time.time()}
        with self._lock:
            if len(self.checkpoint_requests) == \
                    self.checkpoint_requests.maxlen:
                self.checkpoint_requests_dropped += 1
            self.checkpoint_requests.append(req)
        hook = self.on_checkpoint
        if hook is not None:
            try:
                hook(tag, reason)
            except Exception as e:
                return {"requested": True, "tag": tag,
                        "error": f"checkpoint hook failed: {e}"}
        return {"requested": True, "tag": tag}

    # -- device profiling --------------------------------------------------

    def start_profile(self, duration_s: float = 0.0) -> Dict[str, Any]:
        """Start a jax.profiler trace; auto-stops after duration_s if
        given.  Returns {"trace_dir": ...} or {"error": ...}."""
        with self._lock:
            if self._profiling:
                return {"error": "profile already running",
                        "trace_dir": self._profiling}
            trace_dir = os.path.join(self.profile_dir,
                                     f"trace-{int(time.time())}")
            try:
                import jax
                jax.profiler.start_trace(trace_dir)
            except Exception as e:   # jax unavailable / no device
                return {"error": f"profiler start failed: {e}"}
            self._profiling = trace_dir
        if duration_s > 0:
            # The timer only stops ITS OWN trace: a stale timer from an
            # earlier capture must not truncate a later one.
            t = threading.Timer(duration_s, self.stop_profile,
                                kwargs={"expected": trace_dir})
            t.daemon = True
            t.start()
        return {"trace_dir": trace_dir}

    def stop_profile(self, expected: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            if not self._profiling:
                return {"error": "no profile running"}
            if expected is not None and self._profiling != expected:
                return {"error": "profile generation mismatch (stale timer)"}
            trace_dir, self._profiling = self._profiling, None
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            return {"error": f"profiler stop failed: {e}",
                    "trace_dir": trace_dir}
        return {"trace_dir": trace_dir}

    def list_profiles(self) -> list:
        try:
            return sorted(d for d in os.listdir(self.profile_dir)
                          if d.startswith("trace-"))
        except OSError:
            return []

    # -- persistence -------------------------------------------------------

    def _persist_job(self, rec: JobRecord):
        self.state.save(f"job:{rec.job_id}", rec.to_dict())

    def _recover(self):
        """Head restart: reload job registry + serve config (workers and
        their ICI mesh survive; running subprocesses do not — they are
        marked FAILED for the operator's retry machinery to handle)."""
        for key, val in self.state.load_all().items():
            if key.startswith("job:"):
                rec = JobRecord(val["submission_id"], val.get("entrypoint", ""),
                                metadata=val.get("metadata"))
                rec.status = val.get("status", "PENDING")
                rec.start_time = val.get("start_time", 0.0)
                rec.end_time = val.get("end_time", 0.0)
                if rec.status in ("PENDING", "RUNNING"):
                    rec.status = "FAILED"
                    rec.message = "head restarted while job was running"
                    rec.end_time = time.time()
                self.jobs[rec.job_id] = rec
                self._persist_job(rec)
            elif key == "serve_config":
                self.serve_config = val

    # -- job lifecycle -----------------------------------------------------

    # -- structured events -------------------------------------------------

    def record_events(self, events) -> int:
        """Ingest task/step/profile events (a dict or list of dicts).

        Client timestamps (``ts``) are KEPT but never used for ordering
        or attribution: every event is stamped with a server-side
        ``received_at`` (this process's clock, overwriting anything the
        client sent) plus a monotonic ``received_seq`` — the authority
        downstream consumers (archive merge, goodput attribution) order
        and attribute by, so a skewed client clock cannot rewrite
        history."""
        if isinstance(events, dict):
            events = [events]
        n = 0
        now = time.time()
        feed = []
        beats = []
        with self._lock:
            for ev in events:
                if not isinstance(ev, dict):
                    continue
                ev.setdefault("ts", now)        # client clock, display only
                ev.setdefault("type", "task")
                self._event_seq += 1
                # Server-side stamps are authoritative: overwrite, never
                # setdefault — a client-supplied received_at is exactly
                # the clock-skew lie this field exists to prevent.
                ev["received_at"] = now
                ev["received_seq"] = self._event_seq
                # Honor a client-supplied id so a POST retried after a
                # lost response dedups in the collector's archive instead
                # of landing twice under distinct server-minted ids.
                # Only non-empty strings: anything else (non-hashable,
                # empty) would poison the collector's id-keyed dedup set.
                if not (isinstance(ev.get("id"), str) and ev["id"]):
                    ev["id"] = f"{self._event_boot}-{self._event_seq}"
                self.events.append(ev)
                if self.goodput is not None and ev.get("job_id"):
                    feed.append(ev)
                if self.steps is not None and \
                        ev.get("name") == "step_heartbeat" and \
                        ev.get("job_id") and ev.get("host"):
                    beats.append(ev)
                n += 1
        # Goodput feed outside the lock (the ledger has its own): job
        # lifecycle boundaries attributed at the server's receive time.
        for ev in feed:
            jid = ev["job_id"]
            if ev.get("name") == "job_started":
                self.goodput.transition("CoordinatorJob", "head", jid,
                                        "productive", ts=ev["received_at"])
            elif ev.get("name") == "job_finished":
                self.goodput.transition("CoordinatorJob", "head", jid,
                                        "teardown", ts=ev["received_at"])
                self.goodput.close("CoordinatorJob", "head", jid,
                                   ts=ev["received_at"])
        # Step-heartbeat feed, also outside the lock (the tracker has
        # its own) and also attributed at received_at: a skewed host
        # clock cannot shift its own straggler evidence.
        for ev in beats:
            args = ev.get("args") or {}
            try:
                self.steps.observe(
                    ev["job_id"], str(ev["host"]),
                    step=int(args.get("step", 0)),
                    dur_s=float(args.get("dur_s", 0.0)),
                    tokens=float(args.get("tokens", 0.0)),
                    collective_wait_s=float(
                        args.get("collective_wait_s", 0.0)),
                    ts=ev["received_at"],
                    n_params=args.get("n_params"),
                    device_count=args.get("device_count"),
                    peak_tflops=args.get("peak_tflops"),
                    exemplar=ev["id"])
            except (TypeError, ValueError):
                continue        # malformed heartbeat: keep the rest
        return n

    def list_events(self, job_id: Optional[str] = None,
                    etype: Optional[str] = None,
                    limit: int = 5000) -> list:
        with self._lock:
            out = [e for e in self.events
                   if (job_id is None or e.get("job_id") == job_id)
                   and (etype is None or e.get("type") == etype)]
        return out[-limit:]

    def submit(self, job_id: str, entrypoint: str, runtime_env=None,
               metadata=None) -> JobRecord:
        with self._lock:
            if job_id in self.jobs:
                # Idempotent resubmission: the existing record answers,
                # and the goodput ledger must NOT regress to queued.
                return self.jobs[job_id]
            rec = JobRecord(job_id, entrypoint, runtime_env, metadata)
            self.jobs[job_id] = rec
            self._persist_job(rec)
        if self.goodput is not None:
            self.goodput.transition("CoordinatorJob", "head", job_id,
                                    "queued")
        if self.spawn_jobs:
            self._spawn(rec)
        return rec

    def _spawn(self, rec: JobRecord):
        rec.log_path = os.path.join(self.log_dir, f"{rec.job_id}.log")
        env = dict(os.environ)
        for k, v in rec.runtime_env.items():
            env[str(k)] = str(v)
        # Entrypoints tag their step events with this (train/launcher.py).
        env.setdefault("TPU_JOB_ID", rec.job_id)
        logf = open(rec.log_path, "ab")
        try:
            rec.proc = subprocess.Popen(
                rec.entrypoint, shell=True, stdout=logf, stderr=logf, env=env)
            rec.status = "RUNNING"
            self.record_events({"type": "task", "name": "job_started",
                                "job_id": rec.job_id})
        except OSError as e:
            rec.status = "FAILED"
            rec.message = str(e)
            rec.end_time = time.time()
        self._persist_job(rec)
        if rec.proc is not None:
            threading.Thread(target=self._wait, args=(rec,),
                             daemon=True).start()

    def _wait(self, rec: JobRecord):
        code = rec.proc.wait()
        with self._lock:
            if rec.status == "RUNNING":
                rec.status = "SUCCEEDED" if code == 0 else "FAILED"
                rec.message = f"exit code {code}"
            rec.end_time = time.time()
            self._persist_job(rec)
        self.record_events({"type": "task", "name": "job_finished",
                            "job_id": rec.job_id,
                            "args": {"status": rec.status,
                                     "exit_code": code}})

    def stop(self, job_id: str) -> bool:
        with self._lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                return False
            rec.status = "STOPPED"
            rec.end_time = time.time()
            if rec.proc is not None and rec.proc.poll() is None:
                rec.proc.terminate()
            self._persist_job(rec)
            return True

    def delete(self, job_id: str) -> bool:
        self.stop(job_id)
        with self._lock:
            if self.jobs.pop(job_id, None) is None:
                return False
        self.state.delete(f"job:{job_id}")
        return True

    # -- serve -------------------------------------------------------------

    def put_serve_config(self, config: Dict[str, Any]):
        with self._lock:
            self.serve_config = config
            self.state.save("serve_config", config)
            # Applications deploy asynchronously in a real cluster; status
            # is reported by the serving processes via PUT status (or by
            # the engine in-process).
            for app in config.get("applications", []):
                name = app.get("name", "default")
                self.serve_apps.setdefault(
                    name, {"status": "DEPLOYING", "message": ""})

    def set_app_status(self, name: str, status: str, message: str = ""):
        with self._lock:
            self.serve_apps[name] = {"status": status, "message": message}

    # -- HTTP --------------------------------------------------------------

    def make_server(self, host: str = "0.0.0.0",
                    port: int = C.PORT_DASHBOARD) -> ThreadingHTTPServer:
        coord = self

        class Handler(JsonHandler):
            def _authorized(self) -> bool:
                if not coord.auth_token:
                    return True
                import hmac
                got = self.headers.get("Authorization", "")
                return hmac.compare_digest(
                    got, f"Bearer {coord.auth_token}")

            def _guard(self) -> bool:
                if self._authorized():
                    return True
                self._send(401, {"message": "unauthorized"})
                return False

            def do_GET(self):
                if self.path == "/api/healthz":
                    return self._send(200, {"status": "ok"})
                if not self._guard():
                    return
                if self.path == "/api/cluster":
                    return self._send(200, {
                        "cluster_name": os.environ.get(C.ENV_CLUSTER_NAME, ""),
                        "num_jobs": len(coord.jobs),
                    })
                if self.path == "/api/jobs/":
                    return self._send(200, {"jobs": [
                        r.to_dict() for r in coord.jobs.values()]})
                if self.path.split("?", 1)[0].endswith("/logs") and \
                        self.path.startswith("/api/jobs/"):
                    import urllib.parse
                    parts = urllib.parse.urlsplit(self.path)
                    jid = parts.path.rsplit("/", 2)[1]
                    rec = coord.jobs.get(jid)
                    if rec is None:
                        return self._send(404, {"message": "not found"})
                    q = urllib.parse.parse_qs(parts.query)
                    try:
                        tail = int((q.get("tail") or ["0"])[0] or 0)
                    except ValueError:
                        return self._send(400, {"message": "bad tail"})
                    text = ""
                    if rec.log_path and os.path.exists(rec.log_path):
                        with open(rec.log_path, "rb") as f:
                            if tail > 0:
                                # Live-tail consumers poll: read only the
                                # last N bytes, not a multi-GB log.
                                f.seek(0, os.SEEK_END)
                                f.seek(max(0, f.tell() - tail))
                            text = f.read().decode(errors="replace")
                    return self._send(200, {"logs": text})
                if self.path.startswith("/api/jobs/"):
                    jid = self.path.rsplit("/", 1)[1]
                    rec = coord.jobs.get(jid)
                    if rec is None:
                        return self._send(404, {"message": "not found"})
                    return self._send(200, rec.to_dict())
                if self.path == "/api/serve/applications/":
                    return self._send(200, dict(coord.serve_apps))
                if self.path == "/api/serve/config":
                    # The submitted serve CONFIG (what the TpuService
                    # controller PUT) — serve pods read their app's
                    # engine settings from here at startup.
                    return self._send(200, coord.serve_config or {})
                if self.path == "/api/profile/":
                    return self._send(200,
                                      {"profiles": coord.list_profiles()})
                if self.path == "/api/steps" or \
                        self.path.startswith("/api/steps/"):
                    # The straggler microscope's read side, colocated
                    # with the heartbeat ingest (same doc the operator
                    # serves at /debug/steps).
                    if coord.steps is None:
                        return self._send(
                            404, {"message": "step telemetry off"})
                    jid = self.path[len("/api/steps"):].strip("/")
                    if not jid:
                        return self._send(200, coord.steps.to_dict())
                    doc = coord.steps.job_doc(jid)
                    if doc is None:
                        return self._send(404, {"message": "not found"})
                    return self._send(200, doc)
                if self.path.split("?", 1)[0] == "/api/events":
                    import urllib.parse
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    try:
                        limit = int((q.get("limit") or [5000])[0])
                    except ValueError:
                        return self._send(400, {"message": "bad limit"})
                    if limit <= 0:
                        return self._send(200, {"events": []})
                    return self._send(200, {"events": coord.list_events(
                        job_id=(q.get("job_id") or [None])[0],
                        etype=(q.get("type") or [None])[0],
                        limit=limit)})
                return self._send(404, {"message": "unknown path"})

            def do_POST(self):
                if not self._guard():
                    return
                if self.path == "/api/jobs/":
                    b = self._body()
                    rec = coord.submit(
                        b.get("submission_id") or f"job-{int(time.time())}",
                        b.get("entrypoint", ""), b.get("runtime_env"),
                        b.get("metadata"))
                    return self._send(200, {"submission_id": rec.job_id})
                if self.path == "/api/checkpoint":
                    b = self._body()
                    return self._send(200, coord.request_checkpoint(
                        b.get("tag", ""), b.get("reason", "preemption")))
                if self.path == "/api/profile/start":
                    out = coord.start_profile(
                        float(self._body().get("duration_s", 0) or 0))
                    return self._send(400 if "error" in out else 200, out)
                if self.path == "/api/profile/stop":
                    out = coord.stop_profile()
                    return self._send(400 if "error" in out else 200, out)
                if self.path.endswith("/stop"):
                    jid = self.path.rsplit("/", 2)[1]
                    ok = coord.stop(jid)
                    return self._send(200 if ok else 404,
                                      {"stopped": ok})
                if self.path == "/api/events":
                    b = self._body()
                    n = coord.record_events(
                        b.get("events", b) if isinstance(b, dict) else b)
                    return self._send(200, {"recorded": n})
                return self._send(404, {"message": "unknown path"})

            def do_PUT(self):
                if not self._guard():
                    return
                if self.path == "/api/serve/applications/":
                    coord.put_serve_config(self._body())
                    return self._send(200, {})
                if self.path.startswith("/api/serve/applications/") and \
                        self.path.endswith("/status"):
                    name = self.path.rsplit("/", 2)[1]
                    b = self._body()
                    coord.set_app_status(name, b.get("status", "RUNNING"),
                                         b.get("message", ""))
                    return self._send(200, {})
                return self._send(404, {"message": "unknown path"})

            def do_DELETE(self):
                if not self._guard():
                    return
                if self.path.startswith("/api/jobs/"):
                    jid = self.path.rsplit("/", 1)[1]
                    ok = coord.delete(jid)
                    return self._send(200 if ok else 404, {"deleted": ok})
                return self._send(404, {"message": "unknown path"})

        return ThreadingHTTPServer((host, port), Handler)

    def serve_background(self, host="127.0.0.1", port=0):
        from kuberay_tpu.utils.httpjson import serve_background
        return serve_background(self.make_server(host, port), "coordinator-http")


def main(argv=None):  # pragma: no cover - thin process wrapper
    import argparse
    ap = argparse.ArgumentParser(prog="tpu-coordinator")
    ap.add_argument("--port", type=int, default=C.PORT_DASHBOARD)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--log-dir", default="/tmp/tpu-coordinator-logs")
    args = ap.parse_args(argv)
    from kuberay_tpu.obs.steps import StepTracker
    coord = CoordinatorServer(log_dir=args.log_dir, steps=StepTracker())
    srv = coord.make_server(args.host, args.port)
    print(f"coordinator serving on {args.host}:{args.port}", flush=True)
    srv.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
