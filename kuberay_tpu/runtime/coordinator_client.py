"""Coordinator client: the operator's window into a running cluster.

Plays the role of the reference's Ray-dashboard HTTP client
(utils/dashboardclient/dashboard_httpclient.go:29 interface — SubmitJob
:218, GetJobInfo :154, UpdateDeployments :62): job submission/status and
serve-app deployment against the head's HTTP endpoint.

The controllers depend only on this interface; tests inject
``FakeCoordinatorClient`` (the reference's client-provider seam,
suite_test.go:57-70).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


def dashboard_url(coordinator_address: str) -> str:
    """host[:port] coordinator address -> the HTTP API base URL (the job
    API listens on the dashboard port).  THE one derivation — builders
    inject addresses as host:coordinator-port; every consumer (launcher,
    serve server, apiserver proxy) must agree on this mapping."""
    from kuberay_tpu.utils import constants as C
    host = coordinator_address.split(":")[0]
    return f"http://{host}:{C.PORT_DASHBOARD}"


class CoordinatorError(Exception):
    """``code`` carries the HTTP status when the server answered (409 =
    duplicate submit, 5xx = transient server-side) and None when the
    request never completed (connect refused / timeout) — callers branch
    on it instead of parsing the message text."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


class JobInfo:
    def __init__(self, job_id: str, status: str, message: str = "",
                 start_time: float = 0.0, end_time: float = 0.0):
        self.job_id = job_id
        self.status = status          # PENDING|RUNNING|SUCCEEDED|FAILED|STOPPED
        self.message = message
        self.start_time = start_time
        self.end_time = end_time


class CoordinatorClient:
    """HTTP client for the in-cluster coordinator API (dashboard port).

    ``auth_token`` (default: the TPU_AUTH_TOKEN env the operator injects)
    is sent as a Bearer header when set."""

    def __init__(self, base_url: str, timeout: float = 5.0,
                 auth_token: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        import os
        self.auth_token = (auth_token if auth_token is not None
                           else os.environ.get("TPU_AUTH_TOKEN", ""))

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.auth_token:
            headers["Authorization"] = f"Bearer {self.auth_token}"
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, method=method,
            headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            raise CoordinatorError(f"{method} {path}: HTTP {e.code}",
                                   code=e.code) from e
        except Exception as e:
            raise CoordinatorError(f"{method} {path}: {e}") from e

    # job API (ref dashboard_httpclient.go SubmitJob/GetJobInfo/StopJob)
    def submit_job(self, job_id: str, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[dict] = None) -> str:
        out = self._req("POST", "/api/jobs/", {
            "submission_id": job_id, "entrypoint": entrypoint,
            "runtime_env": runtime_env or {}, "metadata": metadata or {}})
        return out.get("submission_id", job_id)

    def get_job_info(self, job_id: str) -> JobInfo:
        out = self._req("GET", f"/api/jobs/{job_id}")
        return JobInfo(job_id, out.get("status", "PENDING"),
                       out.get("message", ""),
                       out.get("start_time", 0.0), out.get("end_time", 0.0))

    def stop_job(self, job_id: str) -> None:
        self._req("POST", f"/api/jobs/{job_id}/stop")

    def delete_job(self, job_id: str) -> None:
        self._req("DELETE", f"/api/jobs/{job_id}")

    def get_job_logs(self, job_id: str) -> str:
        return self._req("GET", f"/api/jobs/{job_id}/logs").get("logs", "")

    def list_jobs(self) -> List[JobInfo]:
        out = self._req("GET", "/api/jobs/")
        return [JobInfo(j.get("submission_id", ""), j.get("status", "PENDING"),
                        j.get("message", "")) for j in out.get("jobs", [])]

    # serve API (ref UpdateDeployments / multi-app status)
    def update_serve_apps(self, config: Dict[str, Any]) -> None:
        self._req("PUT", "/api/serve/applications/", config)

    def set_serve_app_status(self, name: str, status: str,
                             message: str = "") -> None:
        self._req("PUT", f"/api/serve/applications/{name}/status",
                  {"status": status, "message": message})

    def get_serve_apps(self) -> Dict[str, Any]:
        return self._req("GET", "/api/serve/applications/")

    def get_serve_config(self) -> Dict[str, Any]:
        """The submitted serve config (the TpuService controller's PUT)
        — what serve pods read their engine settings from."""
        return self._req("GET", "/api/serve/config")

    # checkpoint drain (preemption notice -> save before the kill;
    # docs/preemption.md): the coordinator fans the request out to the
    # training loop's CheckpointWriter.
    def request_checkpoint(self, tag: str = "",
                           reason: str = "preemption") -> Dict[str, Any]:
        return self._req("POST", "/api/checkpoint",
                         {"tag": tag, "reason": reason})

    # device profiling (jax.profiler traces on the head)
    def start_profile(self, duration_s: float = 0.0) -> Dict[str, Any]:
        return self._req("POST", "/api/profile/start",
                         {"duration_s": duration_s})

    def stop_profile(self) -> Dict[str, Any]:
        return self._req("POST", "/api/profile/stop", {})

    def list_profiles(self) -> List[str]:
        return self._req("GET", "/api/profile/").get("profiles", [])

    # structured task/step/profile events (ref eventserver ingest)
    def post_events(self, events: List[Dict[str, Any]]) -> int:
        return self._req("POST", "/api/events",
                         {"events": events}).get("recorded", 0)

    def get_events(self, job_id: Optional[str] = None,
                   etype: Optional[str] = None,
                   limit: int = 5000) -> List[Dict[str, Any]]:
        import urllib.parse
        q = {"limit": str(limit)}
        if job_id:
            q["job_id"] = job_id
        if etype:
            q["type"] = etype
        return self._req(
            "GET", "/api/events?" + urllib.parse.urlencode(q)
        ).get("events", [])

    def healthz(self) -> bool:
        try:
            self._req("GET", "/api/healthz")
            return True
        except CoordinatorError:
            return False


class FakeCoordinatorClient:
    """In-memory fake (ref fake_serve_httpclient.go).

    Tests drive job/app state transitions explicitly:
    ``fake.set_job_status(jid, "SUCCEEDED")``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.jobs: Dict[str, JobInfo] = {}
        self.serve_config: Optional[Dict[str, Any]] = None
        self.serve_apps: Dict[str, Any] = {}
        self.healthy = True
        self.submit_count = 0
        # DCN partition simulation: while True, every control-plane RPC
        # fails as if the head were unreachable (sim/harness
        # _sync_partitions flips this for the partition window).  Test
        # helpers (set_job_status, ...) stay usable regardless.
        self.partitioned = False
        # Recorded checkpoint-drain requests: [{"tag", "reason"}].
        self.checkpoint_requests: List[Dict[str, Any]] = []

    def _check_partition(self):
        if self.partitioned:
            raise CoordinatorError("dcn partition: coordinator unreachable")

    def submit_job(self, job_id, entrypoint, runtime_env=None, metadata=None):
        self._check_partition()
        with self._lock:
            self.submit_count += 1
            if job_id not in self.jobs:
                self.jobs[job_id] = JobInfo(job_id, "PENDING")
            return job_id

    def get_job_info(self, job_id):
        self._check_partition()
        with self._lock:
            info = self.jobs.get(job_id)
            if info is None:
                raise CoordinatorError(f"job {job_id} not found", code=404)
            return info

    def stop_job(self, job_id):
        self._check_partition()
        with self._lock:
            if job_id in self.jobs:
                self.jobs[job_id].status = "STOPPED"

    def request_checkpoint(self, tag="", reason="preemption"):
        self._check_partition()
        with self._lock:
            self.checkpoint_requests.append({"tag": tag, "reason": reason})
            return {"requested": True, "tag": tag}

    def delete_job(self, job_id):
        with self._lock:
            self.jobs.pop(job_id, None)

    def list_jobs(self):
        with self._lock:
            return list(self.jobs.values())

    def update_serve_apps(self, config):
        self._check_partition()
        with self._lock:
            self.serve_config = config

    def get_serve_apps(self):
        with self._lock:
            return dict(self.serve_apps)

    def healthz(self):
        return self.healthy and not self.partitioned

    # test helpers
    def set_job_status(self, job_id, status, message=""):
        with self._lock:
            self.jobs.setdefault(job_id, JobInfo(job_id, status)).status = status
            self.jobs[job_id].message = message

    def set_serve_app(self, name, status, message=""):
        with self._lock:
            self.serve_apps[name] = {"status": status, "message": message}


def default_client_provider(cluster_status_dict: Dict[str, Any]):
    """Maps a TpuCluster status -> live HTTP client (ref FetchHeadServiceURL
    rayjob_controller.go:218)."""
    addr = cluster_status_dict.get("coordinatorAddress", "")
    host = addr.split(":")[0] if addr else "localhost"
    from kuberay_tpu.utils import constants as C
    return CoordinatorClient(f"http://{host}:{C.PORT_DASHBOARD}")
