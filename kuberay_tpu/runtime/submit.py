"""Submitter tool: what the K8s submitter Job runs (ref common/job.go:90
``ray job submit`` wrapper).  ``python -m kuberay_tpu.runtime.submit``.

Idempotent: submitting an existing job id re-attaches instead of failing,
and ``--tail-logs`` exits with the job's final status so the K8s Job's
success/failure mirrors the application's.
"""

from __future__ import annotations

import argparse
import sys
import time

from kuberay_tpu.runtime.coordinator_client import (
    CoordinatorClient,
    CoordinatorError,
)
from kuberay_tpu.utils import constants as C


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpu-submit")
    ap.add_argument("--address", required=True,
                    help="coordinator host[:port] (head service)")
    ap.add_argument("--job-id", required=True)
    ap.add_argument("--no-wait", action="store_true")
    ap.add_argument("--tail-logs", action="store_true")
    ap.add_argument("--poll-seconds", type=float, default=2.0)
    ap.add_argument("--wait-for-coordinator", type=float, default=0.0,
                    help="retry the initial submit for up to N seconds "
                         "(SidecarMode: the submitter container starts "
                         "with the head pod, possibly before the "
                         "coordinator listens)")
    ap.add_argument("entrypoint", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    host, _, port = args.address.partition(":")
    # The address usually carries the coordinator port; the job API lives
    # on the dashboard port unless an explicit port was given.
    port = port or str(C.PORT_DASHBOARD)
    if port == str(C.PORT_COORDINATOR):
        port = str(C.PORT_DASHBOARD)
    client = CoordinatorClient(f"http://{host}:{port}")

    entry = [a for a in args.entrypoint if a != "--"]
    submitted = False
    if entry:
        deadline = time.time() + args.wait_for_coordinator
        while True:
            try:
                client.submit_job(args.job_id, " ".join(entry))
                submitted = True
                print(f"submitted {args.job_id}", flush=True)
                break
            except CoordinatorError as e:
                if e.code == 409:
                    # Duplicate submission after a submitter restart —
                    # idempotent: fall through and attach.
                    print(f"already submitted, attaching: {e}", flush=True)
                    break
                # Retry within the wait budget on anything transient: the
                # coordinator not listening yet (code None: connect
                # refused/timeout) or a 5xx from a proxy fronting a
                # still-booting head.  Definitive 4xx rejections (auth,
                # validation) are hard errors immediately.
                transient = e.code is None or e.code >= 500
                if not transient or time.time() >= deadline:
                    print(f"submit failed: {e}", file=sys.stderr)
                    return 1
                print(f"coordinator not ready, retrying: {e}",
                      file=sys.stderr, flush=True)
                time.sleep(min(2.0, args.poll_seconds))
        if args.no_wait and not args.tail_logs:
            return 0

    # Attach: poll until terminal; exit code reflects the outcome.  A job
    # id the coordinator does not know (and that we did not just submit)
    # is a hard error, not a retry; transient failures are bounded.
    consecutive_errors = 0
    log_offset = 0
    while True:
        try:
            info = client.get_job_info(args.job_id)
            consecutive_errors = 0
        except CoordinatorError as e:
            if e.code == 404 and not submitted:
                print(f"job {args.job_id} not found", file=sys.stderr)
                return 1
            consecutive_errors += 1
            if consecutive_errors > 30:
                print(f"giving up after {consecutive_errors} failed polls: {e}",
                      file=sys.stderr)
                return 1
            print(f"status poll failed: {e}", file=sys.stderr, flush=True)
            time.sleep(args.poll_seconds)
            continue
        if args.tail_logs:
            try:
                logs = client.get_job_logs(args.job_id)
                if len(logs) > log_offset:
                    sys.stdout.write(logs[log_offset:])
                    sys.stdout.flush()
                    log_offset = len(logs)
            except CoordinatorError:
                pass
        if info.status in ("SUCCEEDED", "FAILED", "STOPPED"):
            print(f"job {args.job_id}: {info.status} {info.message}",
                  flush=True)
            return 0 if info.status == "SUCCEEDED" else 1
        time.sleep(args.poll_seconds)


if __name__ == "__main__":
    sys.exit(main())
